"""Benchmark: jit-train ResNet-50 (BASELINE config 2) and a BERT-base
encoder (config 3) with the framework's fused train step; print ONE JSON
line with throughput + MFU.

Headline metric: ResNet-50 imgs/sec/chip in bf16 autocast (the BASELINE.md
north star). ``vs_baseline`` is measured throughput / target, where target =
85% of a single A100's MLPerf-class ResNet-50 fp16 throughput (~2500 imgs/s
→ target 2125 imgs/s/chip), per BASELINE.md "within 85% of A100x8 step-time"
scaled per chip. The transformer result rides along in "extras".

Runs the real TPU chip when present (the axon tunnel pays ~100ms per blocking
fetch, so the loop is pipelined: no host syncs between steps); falls back to
a tiny CPU shape purely to stay runnable in CI.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np


def _tuned_flash(seq, head_dim, dtype, causal=True):
    """True when this model's step runs the Pallas flash kernel with an
    autotuned block config (tuner winner resolved for its shape key) —
    False for dense-attention or non-Pallas models, so the BENCH
    trajectory shows which numbers are autotuned."""
    try:
        from paddle_tpu import tuner
        if seq < 4096:          # transformer auto-impl crossover: dense
            return False
        return tuner.get_flash_blocks(seq, seq, head_dim, dtype,
                                      causal) is not None
    except Exception:
        return False


def _drive(model, opt, x_np, y_np, steps, use_amp, amp_dtype="bfloat16"):
    """Compile the fused train step once, then run `steps` pipelined steps.
    Returns seconds per step (excluding compile)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.core import generator as _gen

    x = paddle.to_tensor(x_np)
    y = paddle.to_tensor(y_np)
    if use_amp:
        with paddle.amp.auto_cast(enable=True, dtype=amp_dtype):
            model.train_batch([x], [y])   # traces + compiles with bf16 casts
    else:
        model.train_batch([x], [y])

    ts = model._train_step_fn
    from paddle_tpu.core.tensor import stable_uid
    opt_states = [opt._state[stable_uid(p)] for p in ts["trainable"]]
    train_raws = [p._data for p in ts["trainable"]]
    fixed_raws = [ts["state"][i]._data for i in ts["fixed_pos"]]
    x_raws = [x._data]
    y_raws = [y._data]
    lr = jnp.asarray(opt.get_lr(), jnp.float32)

    # warmup (donated-buffer path)
    loss, _, train_raws, opt_states, _ = ts["fn"](
        train_raws, fixed_raws, opt_states, x_raws, y_raws,
        _gen.next_key(), lr, jnp.asarray(2.0, jnp.float32))
    jax.block_until_ready(loss)

    # best-of-3 windows: the shared chip + tunnel add occasional stalls;
    # steady-state throughput is the min per-step time over windows
    # (the loss fetch at each window end forces real completion — plain
    # block_until_ready returns early through the axon tunnel)
    best = None
    step_no = 3
    for _ in range(3):
        t0 = time.perf_counter()
        for i in range(steps):
            loss, _, train_raws, opt_states, _ = ts["fn"](
                train_raws, fixed_raws, opt_states, x_raws, y_raws,
                _gen.next_key(), lr,
                jnp.asarray(float(step_no), jnp.float32))
            step_no += 1
        lv = float(np.asarray(loss))
        dt = (time.perf_counter() - t0) / steps
        assert np.isfinite(lv), "bench loss diverged"
        best = dt if best is None else min(best, dt)
    return best


def bench_resnet50(on_tpu: bool):
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as optim
    from paddle_tpu.vision import models

    if on_tpu:
        batch, size, steps = 256, 224, 20
    else:
        batch, size, steps = 4, 32, 2
    paddle.seed(0)
    net = models.resnet50(num_classes=1000)
    opt = optim.Momentum(learning_rate=0.1, momentum=0.9,
                         parameters=net.parameters(), weight_decay=1e-4)
    model = paddle.Model(net)
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    rng = np.random.RandomState(0)
    x = rng.rand(batch, 3, size, size).astype(np.float32)
    y = rng.randint(0, 1000, (batch,)).astype(np.int64)
    sec_per_step = _drive(model, opt, x, y, steps, use_amp=on_tpu)
    imgs_per_sec = batch / sec_per_step
    # fwd+bwd+update ≈ 3x fwd FLOPs; ResNet-50 fwd @224 = 4.09 GFLOPs/img
    flops_per_img = 3 * 4.09e9 * (size / 224.0) ** 2
    return {
        "imgs_per_sec": imgs_per_sec,
        "sec_per_step": sec_per_step,
        "batch": batch,
        "image_size": size,
        "train_tflops": imgs_per_sec * flops_per_img / 1e12,
        "tuned": False,           # conv/matmul path: XLA-scheduled, no
                                  # tunable Pallas kernel in the step
    }


def bench_bert(on_tpu: bool):
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as optim
    from paddle_tpu.models import BertConfig, BertModel
    from paddle_tpu.nn.layer_base import Layer
    from paddle_tpu import nn

    if on_tpu:
        cfg = BertConfig()              # base: 12L, 768h
        # B=256: the 6ND MFU plateau (docs/perf_notes.md "BERT") and the
        # per-step dispatch cost (~10 ms for ~600 buffers through the
        # axon tunnel, measured) amortizes to ~2.5% of the step
        batch, seq, steps = 256, 128, 6
    else:
        cfg = BertConfig(vocab_size=1000, hidden_size=64, num_layers=2,
                         num_heads=2, intermediate_size=128,
                         max_position_embeddings=64)
        batch, seq, steps = 2, 16, 2

    class MLMHead(Layer):
        def __init__(self):
            super().__init__()
            self.bert = BertModel(cfg)
            self.head = nn.Linear(cfg.hidden_size, cfg.vocab_size)

        def forward(self, ids):
            seq_out, _ = self.bert(ids)
            return self.head(seq_out)

    class FlatCE(Layer):
        def forward(self, logits, labels):
            from paddle_tpu import ops
            v = logits.shape[-1]
            return nn.functional.cross_entropy(
                ops.reshape(logits, [-1, v]), ops.reshape(labels, [-1]))

    paddle.seed(0)
    net = MLMHead()
    opt = optim.AdamW(learning_rate=1e-4, parameters=net.parameters(),
                      weight_decay=0.01)
    model = paddle.Model(net)
    model.prepare(opt, FlatCE())
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    sec_per_step = _drive(model, opt, ids, ids.astype(np.int64), steps,
                          use_amp=on_tpu)
    tokens_per_sec = batch * seq / sec_per_step
    n_params = sum(int(np.prod(p.shape)) for p in net.parameters())
    return {
        "tokens_per_sec": tokens_per_sec,
        "sec_per_step": sec_per_step,
        "batch": batch,
        "seq_len": seq,
        "n_params": n_params,
        # 6ND approximation for transformer train FLOPs
        "train_tflops": tokens_per_sec * 6 * n_params / 1e12,
        "tuned": _tuned_flash(seq, cfg.hidden_size // cfg.num_heads,
                              "bfloat16" if on_tpu else "float32"),
    }


def bench_yolov3(on_tpu: bool):
    """BASELINE workload 4: YOLOv3-DarkNet53 train step (static 416
    bucket, fixed 50 gt slots). The reference trains this shape via
    PaddleDetection over fluid yolov3_loss; here the whole 3-scale loss
    is one fused jit region."""
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as optim
    from paddle_tpu.vision.models import YOLOv3, YOLOv3Loss

    if on_tpu:
        batch, size, steps, width = 32, 416, 6, 1.0
    else:
        batch, size, steps, width = 1, 64, 2, 0.125
    paddle.seed(0)
    net = YOLOv3(num_classes=80, width_mult=width, num_max_boxes=50)
    opt = optim.Momentum(learning_rate=1e-3, momentum=0.9,
                         parameters=net.parameters(), weight_decay=5e-4)
    model = paddle.Model(net)
    model.prepare(opt, YOLOv3Loss(net))
    rng = np.random.RandomState(0)
    x = rng.rand(batch, 3, size, size).astype(np.float32)
    gt_box = np.zeros((batch, 50, 4), np.float32)
    gt_label = np.zeros((batch, 50), np.int64)
    for i in range(batch):
        for b in range(rng.randint(1, 8)):
            cx, cy = rng.uniform(0.2, 0.8, 2)
            w, h = rng.uniform(0.05, 0.4, 2)
            gt_box[i, b] = [cx, cy, w, h]
            gt_label[i, b] = rng.randint(0, 80)

    import jax
    import jax.numpy as jnp
    from paddle_tpu.core import generator as _gen
    from paddle_tpu.core.tensor import stable_uid
    xt = paddle.to_tensor(x)
    yb, yl = paddle.to_tensor(gt_box), paddle.to_tensor(gt_label)
    if on_tpu:
        with paddle.amp.auto_cast(enable=True, dtype="bfloat16"):
            model.train_batch([xt], [yb, yl])
    else:
        model.train_batch([xt], [yb, yl])
    ts = model._train_step_fn
    opt_states = [opt._state[stable_uid(p)] for p in ts["trainable"]]
    train_raws = [p._data for p in ts["trainable"]]
    fixed_raws = [ts["state"][i]._data for i in ts["fixed_pos"]]
    lr = jnp.asarray(opt.get_lr(), jnp.float32)
    loss, _, train_raws, opt_states, _ = ts["fn"](
        train_raws, fixed_raws, opt_states, [xt._data],
        [yb._data, yl._data], _gen.next_key(), lr,
        jnp.asarray(2.0, jnp.float32))
    jax.block_until_ready(loss)
    best = None
    step_no = 3
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, _, train_raws, opt_states, _ = ts["fn"](
                train_raws, fixed_raws, opt_states, [xt._data],
                [yb._data, yl._data], _gen.next_key(), lr,
                jnp.asarray(float(step_no), jnp.float32))
            step_no += 1
        lv = float(np.asarray(loss))
        dt = (time.perf_counter() - t0) / steps
        assert np.isfinite(lv), "yolo bench loss diverged"
        best = dt if best is None else min(best, dt)
    imgs_per_sec = batch / best
    # fwd+bwd+update ≈ 3x fwd; YOLOv3-DarkNet53 fwd @608 = 65.86 GFLOPs
    flops_per_img = 3 * 65.86e9 * (size / 608.0) ** 2
    return {
        "imgs_per_sec": imgs_per_sec,
        "sec_per_step": best,
        "batch": batch,
        "image_size": size,
        "train_tflops": imgs_per_sec * flops_per_img / 1e12,
        "tuned": False,           # train path is conv-only; the tuned
                                  # NMS kernel runs in eval/postprocess
    }


def bench_gpt_longseq(on_tpu: bool):
    """Round-5: long-sequence single-chip train step — GPT-small at
    S=4096 with the Pallas flash-attention kernel (auto-selected at the
    measured S>=4096 crossover) and per-layer recompute (jax.checkpoint)
    so the activations fit HBM. Exercises the 5.7 long-context stack on
    the chip rather than only in CPU-mesh tests."""
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as optim
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    from paddle_tpu.distributed.fleet import utils as fleet_utils

    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=4096,
                        hidden_dropout_prob=0.0,
                        attention_dropout_prob=0.0, attn_impl="auto")
        batch, seq, steps = 4, 4096, 3
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                        num_heads=2, max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_dropout_prob=0.0, attn_impl="auto")
        batch, seq, steps = 1, 64, 2
    paddle.seed(0)
    net = GPTForCausalLM(cfg)
    # recompute every decoder block: trade FLOPs for HBM so S=4096 fits
    for name, sub in net.named_sublayers():
        if name.endswith(tuple(f"layers.{i}" for i in range(cfg.num_layers))):
            orig = sub.forward
            sub.forward = (lambda *a, __f=orig, **k:
                           fleet_utils.recompute(__f, *a, **k))
    opt = optim.AdamW(learning_rate=1e-4, parameters=net.parameters(),
                      weight_decay=0.01)
    model = paddle.Model(net)
    model.prepare(opt, GPTPretrainingCriterion())
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    sec_per_step = _drive(model, opt, ids, ids.astype(np.int64), steps,
                          use_amp=on_tpu)
    tokens_per_sec = batch * seq / sec_per_step
    n_params = sum(int(np.prod(p.shape)) for p in net.parameters())
    return {
        "tokens_per_sec": tokens_per_sec,
        "sec_per_step": sec_per_step,
        "batch": batch,
        "seq_len": seq,
        "n_params": n_params,
        "attn": "pallas_flash+recompute" if seq >= 4096 else "dense",
        # 6ND ignores attention FLOPs; at S=4096 add 12*L*h*S^2-ish? keep
        # the standard 6ND for comparability with the BERT entry
        "train_tflops": tokens_per_sec * 6 * n_params / 1e12,
        "tuned": _tuned_flash(seq, cfg.hidden_size // cfg.num_heads,
                              "bfloat16" if on_tpu else "float32"),
    }


def bench_gpt_ring_flash(on_tpu: bool):
    """Long-context dp×sp train step: a GPT-style decoder stack whose
    attention is ring-flash (sequence dim sharded over "sp", flash kernel
    per chunk, backward through the ring-flash custom_vjp). On TPU this
    is the S=32k ROADMAP-item-2 configuration (dp=2 × sp=4 on 8 chips);
    off-TPU a shrunk interpret-mode shape proves the same program path.
    The 6ND tokens/s→TFLOPs convention matches the other GPT entries."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.distributed.fleet import sequence_parallel as sp

    n = len(jax.devices())
    dp = 2 if n >= 2 and n % 2 == 0 else 1
    spn = n // dp
    devices = np.array(jax.devices()).reshape(dp, spn)
    mesh = jax.sharding.Mesh(devices, ("dp", "sp"))
    if on_tpu:
        batch, seq, n_layers, H, D, steps = 2 * dp, 32768, 4, 8, 64, 3
        dtype = jnp.bfloat16
    else:
        batch, seq, n_layers, H, D, steps = dp, 16 * spn * 2, 2, 2, 16, 2
        dtype = jnp.float32
    E = H * D

    def layer_fn(h, lp):
        wq, wk, wv, wo, w1, w2 = lp
        B, T = h.shape[0], h.shape[1]

        def heads(w):
            return (h @ w).reshape(B, T, H, D).transpose(0, 2, 1, 3)

        o = sp.ring_flash_attention(heads(wq), heads(wk), heads(wv),
                                    mesh=mesh, axis="sp", causal=True,
                                    batch_axes="dp")
        h = h + o.transpose(0, 2, 1, 3).reshape(B, T, E) @ wo
        return h + jax.nn.gelu(h @ w1) @ w2

    def train_step(params, x, y):
        def loss_fn(ps):
            h = x
            for lp in ps:
                h = layer_fn(h, lp)
            return jnp.mean((h - y).astype(jnp.float32) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params,
                                     grads)
        return new, loss

    step = jax.jit(train_step, donate_argnums=(0,))
    rng = np.random.RandomState(0)

    def w(*shape):
        return jnp.asarray(rng.randn(*shape) * 0.1, dtype)

    params = [(w(E, E), w(E, E), w(E, E), w(E, E), w(E, 2 * E),
               w(2 * E, E)) for _ in range(n_layers)]
    x = jnp.asarray(rng.randn(batch, seq, E), dtype)
    y = jnp.asarray(rng.randn(batch, seq, E), dtype)
    params, loss = step(params, x, y)          # compile + warm
    best = None
    for _ in range(steps):
        t0 = time.perf_counter()
        params, loss = step(params, x, y)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    lv = float(np.asarray(loss))
    assert np.isfinite(lv), "ring-flash bench loss diverged"
    tokens_per_sec = batch * seq / best
    n_params = sum(int(np.prod(p.shape)) for lp in params for p in lp)
    Tl = seq // spn
    try:
        from paddle_tpu import tuner
        tuned = tuner.get_flash_blocks(Tl, Tl, D,
                                       "bfloat16" if on_tpu else "float32",
                                       False, ring=True,
                                       bwd=True) is not None
    except Exception:
        tuned = False
    return {
        "tokens_per_sec": tokens_per_sec,
        "sec_per_step": best,
        "batch": batch,
        "seq_len": seq,
        "mesh": f"dp{dp}xsp{spn}",
        "n_params": n_params,
        "attn": "ring_flash(custom_vjp bwd)",
        "train_tflops": tokens_per_sec * 6 * n_params / 1e12,
        "tuned": tuned,
    }


def main():
    import jax
    platform = jax.devices()[0].platform
    on_tpu = platform not in ("cpu",)
    peak_tflops = {"tpu": 197.0}.get(platform, 394.0 if on_tpu else 1.0)

    r = bench_resnet50(on_tpu)
    extras = {"platform": platform, "resnet50": r}
    try:
        b = bench_bert(on_tpu)
        extras["bert_base"] = b
        b_mfu = b["train_tflops"] / peak_tflops
        extras["bert_base"]["mfu"] = b_mfu
    except Exception as e:  # keep the headline metric even if bert fails
        extras["bert_base_error"] = repr(e)
    try:
        yv = bench_yolov3(on_tpu)
        yv["mfu"] = yv["train_tflops"] / peak_tflops
        extras["yolov3_darknet53"] = yv
    except Exception as e:
        extras["yolov3_error"] = repr(e)
    try:
        ls = bench_gpt_longseq(on_tpu)
        ls["mfu"] = ls["train_tflops"] / peak_tflops
        extras["gpt_small_s4096"] = ls
    except Exception as e:
        extras["gpt_longseq_error"] = repr(e)
    try:
        rf = bench_gpt_ring_flash(on_tpu)
        rf["mfu"] = rf["train_tflops"] / peak_tflops
        extras["gpt_ring_flash_s32k"] = rf
    except Exception as e:
        extras["gpt_ring_flash_error"] = repr(e)

    r_mfu = r["train_tflops"] / peak_tflops
    extras["resnet50"]["mfu"] = r_mfu
    target = 2125.0  # 85% of ~2500 imgs/s/A100 (MLPerf-class fp16 ResNet-50)
    print(json.dumps({
        "metric": "resnet50_imgs_per_sec_per_chip",
        "value": round(r["imgs_per_sec"], 2),
        "unit": "imgs/s",
        "vs_baseline": round(r["imgs_per_sec"] / target, 4),
        "extras": extras,
    }))


if __name__ == "__main__":
    main()
