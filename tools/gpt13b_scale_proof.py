"""GPT-1.3B scale proof (BASELINE workload 5: fleet hybrid-parallel GPT
1.3B on v5e-8).

Reference capability being matched:
python/paddle/distributed/fleet/meta_optimizers/sharding_optimizer.py:43
(ZeRO sharding) + fluid/optimizer.py:3946 PipelineOptimizer. The TPU-first
form: ONE jitted train step over a dp mesh with GSPMD-propagated ZeRO
(optimizer moments sharded over dp), per-block rematerialisation, and the
Pallas/XLA attention stack — no separate pipeline/sharding runtimes.

What this script does (run it with no args; needs only CPU):
1. prints the analytic memory plan per sharding level vs the 16 GB v5e
   HBM budget;
2. builds the REAL 1.3B model, jits the framework's actual fused
   train step (forward+backward+AdamW) over a virtual 8-device mesh with
   the planned shardings, AOT-compiles it (no execution), and prints
   XLA's own per-device memory analysis — the load-bearing proof that
   the full-size program compiles and fits;
3. writes the numbers to stdout for docs/perf_notes.md.

The on-chip counterpart (scaled GPT MFU measured on the single real
chip + 6ND extrapolation) lives in bench.py extras
(gpt_small_s4096) and docs/perf_notes.md round-5.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_DEV = 8
HBM_GB = 16.0       # v5e per-chip HBM


# GPT-3 1.3B shape (paper table 2.1): 24 layers, d_model 2048; heads
# chosen MXU-friendly (16 x 128)
CFG = dict(vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16,
           max_position_embeddings=1024)
SEQ = 1024
PER_DEV_BATCH = 1


def param_count(c=CFG):
    h, L, V, S = (c["hidden_size"], c["num_layers"], c["vocab_size"],
                  c["max_position_embeddings"])
    emb = V * h + S * h
    per_layer = 12 * h * h + 13 * h     # qkv/out + 2 mlp + norms/biases
    return emb + L * per_layer + 2 * h


def memory_plan():
    n = param_count()
    gb = 1024 ** 3
    p4, p2 = 4 * n / gb, 2 * n / gb           # f32 / bf16 params
    m8 = 8 * n / gb                           # two f32 Adam moments
    g4 = 4 * n / gb
    print(f"GPT-1.3B memory plan ({n/1e9:.3f}B params, v5e-8, "
          f"{HBM_GB:.0f} GB/chip):")
    rows = [
        ("replicated (no sharding)", p4 + m8 + g4),
        ("ZeRO-1 os   (moments/8)", p4 + m8 / N_DEV + g4),
        ("ZeRO-2 os_g (+ grads/8)", p4 + (m8 + g4) / N_DEV),
        ("ZeRO-3 p_g_os (everything/8)", (p4 + m8 + g4) / N_DEV),
        ("pp=4 x dp=2 (layers/4, moments/2)",
         (p4 + g4) / 4 + m8 / 8),
    ]
    for name, per_dev in rows:
        fit = "FITS" if per_dev < HBM_GB * 0.9 else "DOES NOT FIT"
        print(f"  {name:38s} {per_dev:6.2f} GB/chip + activations "
              f"-> {fit}")
    print(f"  (activations w/ per-block remat at B=1/dev, S={SEQ}: "
          f"~{24 * PER_DEV_BATCH * SEQ * CFG['hidden_size'] * 4 / gb:.2f} GB"
          f" checkpoints + one block's live set)")
    return n


def compile_full_size():
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + f" --xla_force_host_platform_device_count={N_DEV}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as optim
    from paddle_tpu.core.tensor import stable_uid
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    from paddle_tpu.distributed.fleet import utils as fleet_utils

    devs = jax.devices()[:N_DEV]
    mesh = dist.build_mesh({"dp": N_DEV}, devs)
    dist.set_mesh(mesh)

    cfg = GPTConfig(**CFG, hidden_dropout_prob=0.0,
                    attention_dropout_prob=0.0, attn_impl="dense")
    t0 = time.time()
    paddle.seed(0)
    net = GPTForCausalLM(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in net.parameters())
    print(f"built 1.3B model: {n_params/1e9:.3f}B params "
          f"({time.time()-t0:.0f}s init)")

    # per-block remat: trade FLOPs for HBM (jax.checkpoint)
    for name, sub in net.named_sublayers():
        if name.split(".")[-2:-1] == ["layers"]:
            orig = sub.forward
            sub.forward = (lambda *a, __f=orig, **k:
                           fleet_utils.recompute(__f, *a, **k))

    opt = optim.AdamW(learning_rate=1e-4, parameters=net.parameters(),
                      weight_decay=0.01)
    m = paddle.Model(net)
    m.prepare(opt, GPTPretrainingCriterion())

    B = PER_DEV_BATCH * N_DEV
    x = np.zeros((B, SEQ), np.int32)
    y = np.zeros((B, SEQ), np.int32)
    sig = (tuple([((B, SEQ), "int32"), ((B, SEQ), "int32")]), False)
    ts = m._get_train_step(sig)

    def spec_for_state(shape):
        # ZeRO: shard each moment's largest dp-divisible dim
        for i, d in enumerate(shape):
            if d % N_DEV == 0:
                s = [None] * len(shape)
                s[i] = "dp"
                return P(*s)
        return P()

    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P("dp"))

    def struct(shape, dtype, sharding):
        return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sharding)

    # ZeRO-1 layout: params replicated (f32), both Adam moments sharded
    # over dp. A ZeRO-3/FSDP variant (params sharded too) also compiles,
    # but XLA's CPU-backend memory accounting charges the full gathered
    # parameter set to temps with no overlap scheduling, overstating TPU
    # liveness — ZeRO-1 + bf16 compute is the configuration the chip
    # would actually run and the one scored here.
    train_structs = [struct(p._data.shape, p._data.dtype, repl)
                     for p in ts["trainable"]]
    fixed_structs = [struct(ts["state"][i]._data.shape,
                            ts["state"][i]._data.dtype, repl)
                     for i in ts["fixed_pos"]]
    state_structs = []
    for p in ts["trainable"]:
        st = opt._init_state(p)
        state_structs.append({
            k: struct(v.shape, v.dtype,
                      NamedSharding(mesh, spec_for_state(v.shape)))
            for k, v in st.items()})
    x_structs = [struct((B, SEQ), jnp.int32, batch_sh)]
    y_structs = [struct((B, SEQ), jnp.int32, batch_sh)]
    key_s = struct((2,), jnp.uint32, repl)
    scal = struct((), jnp.float32, repl)

    print(f"lowering + compiling the fused train step "
          f"(B={B} global, S={SEQ}, dp={N_DEV}, ZeRO-1 moments, remat)...")
    t0 = time.time()
    # traced in f32 (worst case): bf16 autocast halves the transient set
    # on TPU, but XLA's CPU backend materialises both sides of every cast
    # with no fusion, so the CPU memory accounting of an amp trace
    # OVERSTATES liveness (measured: +5 GB temps) — f32 is the honest
    # upper bound here
    lowered = ts["fn"].lower(train_structs, fixed_structs,
                             state_structs, x_structs, y_structs,
                             key_s, scal, scal)
    t_lower = time.time() - t0
    t0 = time.time()
    compiled = lowered.compile()
    t_compile = time.time() - t0
    print(f"lower {t_lower:.0f}s, compile {t_compile:.0f}s")

    ma = compiled.memory_analysis()
    gb = 1024 ** 3
    arg = ma.argument_size_in_bytes / gb
    out = ma.output_size_in_bytes / gb
    tmp = ma.temp_size_in_bytes / gb
    # donation aliases outputs onto arguments: live set is max(arg,out)+tmp
    live = max(arg, out) + tmp
    print(f"XLA memory analysis (per device): args {arg:.2f} GB, "
          f"outputs {out:.2f} GB, temps {tmp:.2f} GB -> live ~{live:.2f} GB"
          f" vs {HBM_GB:.0f} GB HBM")
    ok = live < HBM_GB
    print(f"1.3B dp8+ZeRO+remat program: "
          f"{'FITS v5e-8' if ok else 'DOES NOT FIT'} "
          f"(f32 worst case; bf16 compute + TPU collective scheduling "
          f"only lower it)")
    dist.set_mesh(None)
    return ok


if __name__ == "__main__":
    memory_plan()
    ok = compile_full_size()
    sys.exit(0 if ok else 1)
