#!/usr/bin/env python
"""Forward-operator coverage audit vs the reference catalog.

``tools/op_catalog.txt`` is the list of forward op types extracted from the
reference's registration macros (REGISTER_OPERATOR / REGISTER_OP_WITHOUT_
GRADIENT / kernel+version registrations / FOR_EACH_ACTIVATION_OP) plus
``*_op.cc`` basenames, grad ops excluded — the same extraction SURVEY
Appendix A describes (518 entries).

Every catalog op must resolve to exactly one status:

- ``impl``      — a public API in this framework implements the capability;
                  the mapping target is import-checked, so the doc can't rot.
- ``absorbed``  — the mechanism is XLA/JAX's job (fusion passes, stream
                  sync, buffer coalescing); nothing framework-side remains.
- ``adr``       — deliberately out of scope, with a written ADR.
- ``na``        — meaningless off-CUDA/Ascend/MKLDNN or engine-specific.

Run:  python tools/op_coverage.py          # regenerates docs/op_coverage.md
      python tools/op_coverage.py --check  # CI: fail on blanks/bad targets
"""
from __future__ import annotations

import argparse
import importlib
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# -- status tables ------------------------------------------------------------

# ops whose name auto-resolves against these namespaces (tried in order)
NAMESPACES = [
    ("paddle", "paddle_tpu"),
    ("ops", "paddle_tpu.ops"),
    ("F", "paddle_tpu.nn.functional"),
    ("nn", "paddle_tpu.nn"),
    ("dist", "paddle_tpu.distributed"),
    ("static.nn", "paddle_tpu.static.nn"),
    ("static", "paddle_tpu.static"),
    ("opt", "paddle_tpu.optimizer"),
    ("amp", "paddle_tpu.amp"),
    ("quant", "paddle_tpu.quantization"),
    ("io", "paddle_tpu.io"),
    ("incubate", "paddle_tpu.incubate"),
    ("metric", "paddle_tpu.metric"),
    ("vision", "paddle_tpu.vision"),
    ("text", "paddle_tpu.text"),
]

# name rewrites applied before auto-resolution (reference name -> ours)
ALIASES = {
    "arg_max": "argmax", "arg_min": "argmin",
    "reduce_sum": "sum", "reduce_mean": "mean", "reduce_max": "max",
    "reduce_min": "min", "reduce_prod": "prod", "reduce_all": "all",
    "reduce_any": "any",
    "elementwise_add": "add", "elementwise_sub": "subtract",
    "elementwise_mul": "multiply", "elementwise_div": "divide",
    "elementwise_max": "maximum", "elementwise_min": "minimum",
    "elementwise_mod": "mod", "elementwise_pow": "pow",
    "elementwise_floordiv": "floor_divide",
    "top_k": "topk", "top_k_v2": "topk",
    "fill_any_like": "full_like", "fill_constant": "full",
    "fill_zeros_like": "zeros_like", "fill": "full",
    "uniform_random": "uniform", "gaussian_random": "normal",
    "truncated_gaussian_random": "truncated_normal",
    "grid_sampler": "grid_sample",
    "lookup_table": "embedding", "lookup_table_v2": "embedding",
    "tril_triu": "tril", "where_index": "nonzero",
    "hard_sigmoid": "hardsigmoid", "hard_swish": "hardswish",
    "hard_shrink": "hardshrink", "soft_shrink": "softshrink",
    "tanh_shrink": "tanhshrink", "logsigmoid": "log_sigmoid",
    "depthwise_conv2d": "conv2d",
    "depthwise_conv2d_transpose": "conv2d_transpose",
    "sigmoid_cross_entropy_with_logits": "binary_cross_entropy_with_logits",
    "range": "arange", "isfinite_op": "isfinite",
    "reverse": "flip",
    "brelu": "hardtanh", "softshrink": "softshrink",
    "bilinear_tensor_product": "bilinear",
    "margin_rank_loss": "margin_rank_loss",
    "smooth_l1_loss": "smooth_l1_loss",
    "unpool": "max_unpool2d",
    "pool_with_index": "max_pool2d",
    "max_pool2d_with_index": "max_pool2d",
    "max_pool3d_with_index": "max_pool3d",
    "pad2d": "pad", "pad3d": "pad",
    "crop_tensor": "crop",
    "lrn": "local_response_norm",
    "thresholded_relu": "thresholded_relu",
    "kldiv_loss": "kl_div",
    "log_loss": "log_loss",
    "sampling_id": "sampling_id",
    "hierarchical_sigmoid": "hsigmoid_loss",
    "spectral_norm": "SpectralNorm",
    "sync_batch_norm": "SyncBatchNorm",
    "inplace_abn": "SyncBatchNorm",
    "squared_l2_distance": "squared_l2_distance",
    "gru": "GRU", "gru_unit": "GRUCell", "multi_gru": "GRU",
    "lstm": "LSTM", "lstm_unit": "LSTMCell", "lstmp": "LSTM",
    "cudnn_lstm": "LSTM", "rnn": "RNN", "recurrent": "RNN",
    "memcpy": "assign", "minus": "subtract",
    "seed": "seed",
    "one_hot": "one_hot", "one_hot_v2": "one_hot",
}

# explicit "impl" mappings that an attribute probe can't find (methods,
# classes with different names, multi-step capabilities)
MANUAL_IMPL = {
    # optimizers-as-ops -> optimizer classes (apply-gradients kernels)
    "adadelta": "paddle_tpu.optimizer:Adadelta",
    "adagrad": "paddle_tpu.optimizer:Adagrad",
    "adam": "paddle_tpu.optimizer:Adam",
    "adamax": "paddle_tpu.optimizer:Adamax",
    "ftrl": "paddle_tpu.optimizer:Ftrl",
    "lamb": "paddle_tpu.optimizer:Lamb",
    "lars_momentum": "paddle_tpu.optimizer:LarsMomentum",
    "momentum": "paddle_tpu.optimizer:Momentum",
    "rmsprop": "paddle_tpu.optimizer:RMSProp",
    "sgd": "paddle_tpu.optimizer:SGD",
    "decayed_adagrad": "paddle_tpu.optimizer:Adagrad",
    "proximal_adagrad": "paddle_tpu.optimizer:Adagrad",
    "proximal_gd": "paddle_tpu.optimizer:SGD",
    "dpsgd": "paddle_tpu.optimizer:SGD",
    "average_accumulates": "paddle_tpu.optimizer:ExponentialMovingAverage",
    # collectives: c_* ring ops -> mesh collective functions
    "allreduce": "paddle_tpu.distributed:all_reduce",
    "alltoall": "paddle_tpu.distributed:alltoall",
    "barrier": "paddle_tpu.distributed:barrier",
    "broadcast": "paddle_tpu.distributed:broadcast",
    "c_allgather": "paddle_tpu.distributed:all_gather",
    "c_allreduce_max": "paddle_tpu.distributed:all_reduce",
    "c_allreduce_min": "paddle_tpu.distributed:all_reduce",
    "c_allreduce_prod": "paddle_tpu.distributed:all_reduce",
    "c_allreduce_sum": "paddle_tpu.distributed:all_reduce",
    "c_broadcast": "paddle_tpu.distributed:broadcast",
    "c_concat": "paddle_tpu.distributed:all_gather",
    "c_embedding": "paddle_tpu.distributed.fleet:VocabParallelEmbedding",
    "c_identity": "paddle_tpu.distributed:replicate_tensor",
    "c_reduce_max": "paddle_tpu.distributed:reduce",
    "c_reduce_min": "paddle_tpu.distributed:reduce",
    "c_reduce_prod": "paddle_tpu.distributed:reduce",
    "c_reduce_sum": "paddle_tpu.distributed:reduce",
    "c_reducescatter": "paddle_tpu.distributed:reduce_scatter",
    "c_scatter": "paddle_tpu.distributed:scatter",
    "c_split": "paddle_tpu.distributed:split",
    "recv_v2": "paddle_tpu.distributed:recv",
    "send_v2": "paddle_tpu.distributed:send",
    "shard_index": "paddle_tpu.ops:shard_index",
    # program-structure ops -> executor / control-flow machinery
    "feed": "paddle_tpu.static:Executor",
    "fetch": "paddle_tpu.static:Executor",
    "conditional_block": "paddle_tpu.ops:cond",
    "conditional_block_infer": "paddle_tpu.ops:cond",
    "while": "paddle_tpu.ops:while_loop",
    "select_input": "paddle_tpu.ops:case",
    "select_output": "paddle_tpu.ops:case",
    "assert": "paddle_tpu.static:nn.Assert",
    "print": "paddle_tpu.static:nn.Print",
    "py_func": "paddle_tpu.ops.custom:register_op",
    "py_layer": "paddle_tpu.autograd:PyLayer",
    "run_program": "paddle_tpu.jit:to_static",
    "write_to_array": "paddle_tpu.ops:array_write",
    "read_from_array": "paddle_tpu.ops:array_read",
    "lod_array_length": "paddle_tpu.ops:array_length",
    "tensor_array_to_tensor": "paddle_tpu.ops:stack",
    "increment": "paddle_tpu.ops:increment",
    "is_empty": "paddle_tpu.ops:is_empty",
    # LoD plumbing -> padded+lengths sequence ops
    "array_to_lod_tensor": "paddle_tpu.ops:sequence_unpad",
    "lod_tensor_to_array": "paddle_tpu.ops:sequence_pad",
    "lod_reset": "paddle_tpu.ops:sequence_pad",
    "lod_rank_table": "paddle_tpu.ops:argsort",
    "max_sequence_len": "paddle_tpu.ops:sequence_mask",
    "merge_lod_tensor": "paddle_tpu.ops:multiplex",
    "merge_lod_tensor_infer": "paddle_tpu.ops:multiplex",
    "split_lod_tensor": "paddle_tpu.ops:masked_select",
    "reorder_lod_tensor_by_rank": "paddle_tpu.ops:index_select",
    "shrink_rnn_memory": "paddle_tpu.ops:sequence_slice",
    "rnn_memory_helper": "paddle_tpu.ops:assign",
    "sequence_reshape": "paddle_tpu.ops:reshape",
    "sequence_scatter": "paddle_tpu.ops:scatter_nd_add",
    "im2sequence": "paddle_tpu.ops:im2sequence",
    # IO / persistence
    "load": "paddle_tpu:load",
    "save": "paddle_tpu:save",
    "load_combine": "paddle_tpu:load",
    "save_combine": "paddle_tpu:save",
    "read": "paddle_tpu.io:DataLoader",
    "read_file": "paddle_tpu.vision:read_file",
    "decode_jpeg": "paddle_tpu.vision:decode_jpeg",
    "create_custom_reader": "paddle_tpu.io:IterableDataset",
    "create_ctr_reader": "paddle_tpu.distributed:InMemoryDataset",
    "create_py_reader": "paddle_tpu.io:DataLoader",
    "create_double_buffer_reader": "paddle_tpu.io:DataLoader",
    # AMP ops -> GradScaler internals
    "check_finite_and_unscale": "paddle_tpu.amp:GradScaler",
    "update_loss_scaling": "paddle_tpu.amp:GradScaler",
    # quantization op family -> quantization module
    "quantize": "paddle_tpu.quantization:quant_dequant_with_scale",
    "dequantize": "paddle_tpu.quantization:quant_dequant_with_scale",
    "requantize": "paddle_tpu.quantization:quant_dequant_with_scale",
    "dequantize_abs_max": "paddle_tpu.quantization:fake_quantize_abs_max",
    "dequantize_log": "paddle_tpu.quantization:quant_dequant_with_scale",
    "fake_quantize_abs_max": "paddle_tpu.quantization:fake_quantize_abs_max",
    "fake_quantize_dequantize_abs_max":
        "paddle_tpu.quantization:fake_quantize_abs_max",
    "fake_quantize_moving_average_abs_max":
        "paddle_tpu.quantization:MovingAverageAbsMaxObserver",
    "fake_quantize_range_abs_max":
        "paddle_tpu.quantization:MovingAverageAbsMaxObserver",
    "fake_dequantize_max_abs": "paddle_tpu.quantization:fake_quantize_abs_max",
    "fake_channel_wise_quantize_abs_max":
        "paddle_tpu.quantization:fake_channel_wise_quantize_abs_max",
    "fake_channel_wise_dequantize_max_abs":
        "paddle_tpu.quantization:fake_channel_wise_quantize_abs_max",
    "moving_average_abs_max_scale":
        "paddle_tpu.quantization:MovingAverageAbsMaxObserver",
    # losses/metrics with different spellings
    "accuracy": "paddle_tpu.metric:Accuracy",
    "auc": "paddle_tpu.metric:Auc",
    "precision_recall": "paddle_tpu.ops:precision_recall",
    "cross_entropy": "paddle_tpu.nn.functional:cross_entropy",
    "cross_entropy2": "paddle_tpu.nn.functional:cross_entropy",
    "softmax_with_cross_entropy": "paddle_tpu.nn.functional:cross_entropy",
    "bce_loss": "paddle_tpu.nn.functional:binary_cross_entropy",
    "huber_loss": "paddle_tpu.nn.functional:huber_loss",
    "warpctc": "paddle_tpu.nn.functional:warpctc",
    "nce": "paddle_tpu.nn.functional:nce",
    "sample_logits": "paddle_tpu.nn.functional:sample_logits",
    "linear_chain_crf": "paddle_tpu.ops:linear_chain_crf",
    "crf_decoding": "paddle_tpu.ops:crf_decoding",
    "chunk_eval": "paddle_tpu.ops:chunk_eval",
    # interp family -> interpolate(mode=...)
    "bilinear_interp": "paddle_tpu.nn.functional:interpolate",
    "bilinear_interp_v2": "paddle_tpu.nn.functional:interpolate",
    "bicubic_interp": "paddle_tpu.nn.functional:interpolate",
    "bicubic_interp_v2": "paddle_tpu.nn.functional:interpolate",
    "linear_interp": "paddle_tpu.nn.functional:interpolate",
    "linear_interp_v2": "paddle_tpu.nn.functional:interpolate",
    "nearest_interp": "paddle_tpu.nn.functional:interpolate",
    "nearest_interp_v2": "paddle_tpu.nn.functional:interpolate",
    "trilinear_interp": "paddle_tpu.nn.functional:interpolate",
    "trilinear_interp_v2": "paddle_tpu.nn.functional:interpolate",
    # misc renamed
    "fc": "paddle_tpu.static:nn.fc",
    "mul": "paddle_tpu.ops:matmul",
    "pool": "paddle_tpu.nn.functional:max_pool2d",
    "unique_with_counts": "paddle_tpu.ops:unique",
    "cos_sim": "paddle_tpu.ops:cos_sim",
    "fill_constant_batch_size_like":
        "paddle_tpu.ops:fill_constant_batch_size_like",
    "uniform_random_batch_size_like":
        "paddle_tpu.ops:uniform_random_batch_size_like",
    "gaussian_random_batch_size_like":
        "paddle_tpu.ops:gaussian_random_batch_size_like",
    "assign_value": "paddle_tpu.ops:assign_value",
    "set_value": "paddle_tpu.core.tensor:Tensor.set_value",
    "random_crop": "paddle_tpu.vision.transforms:RandomCrop",
    "prroi_pool": "paddle_tpu.ops:prroi_pool",
    "psroi_pool": "paddle_tpu.ops:psroi_pool",
    "deformable_psroi_pooling": "paddle_tpu.ops:deformable_psroi_pooling",
    "deformable_conv": "paddle_tpu.nn.functional:deformable_conv",
    "deformable_conv_v1": "paddle_tpu.nn.functional:deformable_conv",
    "segment_pool": "paddle_tpu.incubate:segment_pool",
    "class_center_sample": "paddle_tpu.nn.functional:class_center_sample",
    "partial_concat": "paddle_tpu.ops:partial_concat",
    "partial_sum": "paddle_tpu.ops:partial_sum",
    "pad_constant_like": "paddle_tpu.ops:pad_constant_like",
    "batch_fc": "paddle_tpu.ops:batch_fc",
    "data_norm": "paddle_tpu.ops:data_norm",
    "affine_channel": "paddle_tpu.ops:affine_channel",
    "shuffle_batch": "paddle_tpu.ops:shuffle_batch",
    "shuffle_channel": "paddle_tpu.ops:shuffle_channel",
    "cvm": "paddle_tpu.ops:cvm",
    "filter_by_instag": "paddle_tpu.ops:filter_by_instag",
    "row_conv": "paddle_tpu.ops:row_conv",
    "conv_shift": "paddle_tpu.ops:conv_shift",
    "add_position_encoding": "paddle_tpu.ops:add_position_encoding",
    "correlation": "paddle_tpu.ops:correlation",
    "similarity_focus": "paddle_tpu.ops:similarity_focus",
    "fsp": "paddle_tpu.ops:fsp",
    "spp": "paddle_tpu.ops:spp",
    "match_matrix_tensor": "paddle_tpu.ops:match_matrix_tensor",
    "mean_iou": "paddle_tpu.ops:mean_iou",
    "positive_negative_pair": "paddle_tpu.ops:positive_negative_pair",
    "bpr_loss": "paddle_tpu.ops:bpr_loss",
    "modified_huber_loss": "paddle_tpu.ops:modified_huber_loss",
    "teacher_student_sigmoid_loss":
        "paddle_tpu.ops:teacher_student_sigmoid_loss",
    "center_loss": "paddle_tpu.ops:center_loss",
    "sequence_topk_avg_pooling": "paddle_tpu.ops:sequence_pool",
    "edit_distance": "paddle_tpu.ops:edit_distance",
    "ctc_align": "paddle_tpu.ops:ctc_align",
    "temporal_shift": "paddle_tpu.nn.functional:temporal_shift",
    "sampling_id": "paddle_tpu.nn.functional:sampling_id",
    "multiclass_nms2": "paddle_tpu.ops:multiclass_nms",
    "multiclass_nms3": "paddle_tpu.ops:multiclass_nms",
    "locality_aware_nms": "paddle_tpu.ops:matrix_nms",
    "label_smooth": "paddle_tpu.nn.functional:label_smooth",
    "get_tensor_from_selected_rows":
        "paddle_tpu.distributed.fleet:ShardedEmbedding",
    "merge_selected_rows":
        "paddle_tpu.distributed.fleet:sparse_row_update",
    "clip_by_norm": "paddle_tpu.ops:clip_by_norm",
    "coalesce_tensor": "paddle_tpu.hapi.model:Model.train_loop",
}

# XLA/JAX absorb these mechanisms entirely (SURVEY §2 "absorbed" rows)
ABSORBED = {
    # stream/ordering ops: XLA's async runtime orders collectives/compute
    "c_sync_calc_stream": "XLA async dispatch orders compute",
    "c_sync_comm_stream": "XLA async dispatch orders collectives",
    "c_wait_comm": "XLA token-threaded collectives",
    "c_wait_compute": "XLA token-threaded collectives",
    "c_comm_init": "jax.distributed.initialize",
    "c_comm_init_all": "jax.distributed.initialize",
    "c_gen_nccl_id": "jax.distributed bootstrap",
    "gen_nccl_id": "jax.distributed bootstrap",
    # fused/inference-engine ops: XLA fusion emits these automatically
    "attention_lstm": "XLA fusion of the unfused graph",
    "conv_fusion": "XLA conv+bias+act fusion",
    "fusion_conv_inception": "XLA fusion",
    "fused_bn_activation": "XLA fusion",
    "fused_bn_add_activation": "XLA fusion",
    "fused_elemwise_activation": "XLA elementwise fusion",
    "fused_embedding_eltwise_layernorm": "XLA fusion",
    "fused_embedding_fc_lstm": "XLA fusion",
    "fused_embedding_seq_pool": "XLA gather+reduce fusion",
    "fused_fc_elementwise_layernorm": "XLA fusion",
    "fusion_group": "XLA fusion pass (this op IS a fusion pass product)",
    "fusion_gru": "XLA fusion of the scan",
    "fusion_lstm": "XLA fusion of the scan",
    "fusion_repeated_fc_relu": "XLA fusion",
    "fusion_seqconv_eltadd_relu": "XLA fusion",
    "fusion_seqexpand_concat_fc": "XLA fusion",
    "fusion_seqpool_concat": "XLA fusion",
    "fusion_seqpool_cvm_concat": "XLA fusion",
    "fusion_squared_mat_sub": "XLA fusion",
    "fusion_transpose_flatten_concat": "XLA layout assignment",
    "multihead_matmul": "XLA attention fusion",
    "skip_layernorm": "XLA fusion",
    "squared_l2_norm": "XLA fusion of square+reduce",
    # program plumbing with no XLA counterpart needed
    "delete_var": "XLA buffer liveness / donation",
    "get_places": "jax.devices()",
    "enqueue": "io prefetch thread (io/__init__.py)",
    "dequeue": "io prefetch thread",
    "queue_generator": "io prefetch thread",
    "marker": "jax.profiler.TraceAnnotation",
    "copy_cross_scope": "functional scoping (no Scope tree)",
    "alloc_float_status": "float-status registers are an Ascend mechanism;"
                          " NaN checks via FLAGS_check_nan_inf in dispatch",
}

# decided out of scope with a written ADR
ADR = {
    # docs/adr/0001-parameter-server.md: brpc PS replaced by sharded tables
    **{k: "docs/adr/0001-parameter-server.md" for k in [
        "distributed_lookup_table", "fake_init", "fetch_barrier",
        "heter_listen_and_serv", "listen_and_serv", "send", "send_and_recv",
        "send_barrier", "pull_box_sparse", "pull_box_extended_sparse",
        "push_box_sparse", "push_box_extended_sparse", "pull_sparse",
        "pull_sparse_v2", "push_sparse", "push_sparse_v2", "push_dense",
        "tdm_child", "tdm_sampler", "pyramid_hash", "hash",
        "rank_attention", "lookup_table_dequant",
    ]},
    # docs/adr/0002-dgc.md: top-k grad compression is ICI-pointless
    "dgc": "docs/adr/0002-dgc.md",
    "dgc_clip_by_norm": "docs/adr/0002-dgc.md",
    "dgc_momentum": "docs/adr/0002-dgc.md",
    # docs/adr/0003-lod-niche-ops.md (this round): LoD-era text-matching
    "var_conv_2d": "docs/adr/0003-lod-niche-ops.md",
    "tree_conv": "docs/adr/0003-lod-niche-ops.md",
    "detection_map": "docs/adr/0003-lod-niche-ops.md",
    "bilateral_slice": "docs/adr/0003-lod-niche-ops.md",
    "roi_perspective_transform": "docs/adr/0003-lod-niche-ops.md",
    "retinanet_detection_output": "docs/adr/0003-lod-niche-ops.md",
    "retinanet_target_assign": "docs/adr/0003-lod-niche-ops.md",
    "rpn_target_assign": "docs/adr/0003-lod-niche-ops.md",
    "generate_proposal_labels": "docs/adr/0003-lod-niche-ops.md",
    "generate_mask_labels": "docs/adr/0003-lod-niche-ops.md",
    "mine_hard_examples": "docs/adr/0003-lod-niche-ops.md",
}

# no meaning off the reference's backends / engines
NA = {
    "ascend_trigger": "Ascend backend",
    "c_comm_init_hccl": "Ascend HCCL",
    "c_gen_hccl_id": "Ascend HCCL",
    "c_gen_bkcl_id": "Kunlun BKCL",
    "gen_hccl_id": "Ascend HCCL",
    "gen_bkcl_id": "Kunlun BKCL",
    "dlnne_engine": "NNE inference engine",
    "lite_engine": "Paddle-Lite engine",
    "tensorrt_engine": "TensorRT engine",
}


def resolve(name):
    if name in MANUAL_IMPL:
        return "impl", MANUAL_IMPL[name]
    if name in ABSORBED:
        return "absorbed", ABSORBED[name]
    if name in ADR:
        return "adr", ADR[name]
    if name in NA:
        return "na", NA[name]
    cands = [name]
    if name in ALIASES:
        cands.append(ALIASES[name])
    if name.endswith("_v2"):
        cands.append(name[:-3])
        if name[:-3] in ALIASES:
            cands.append(ALIASES[name[:-3]])
    elif name.endswith("2") and not name.endswith("v2"):
        cands.append(name[:-1])
    for c in cands:
        for label, modname in NAMESPACES:
            try:
                mod = importlib.import_module(modname)
            except ImportError:
                continue
            if hasattr(mod, c):
                return "impl", f"{modname}:{c}"
    return None, None


def check_target(target):
    """impl targets must import (module:attr[.attr])."""
    modname, _, attr = target.partition(":")
    try:
        mod = importlib.import_module(modname)
    except ImportError:
        return False
    obj = mod
    for part in attr.split("."):
        if not hasattr(obj, part):
            return False
        obj = getattr(obj, part)
    return True


def _tests_corpus():
    """Concatenated test-suite text: the op-has-a-test check greps for
    the op name or its mapping symbol (reference discipline: one
    test_*_op.py per op; here one symbol mention per op, enforced)."""
    txt = []
    tdir = os.path.join(REPO, "tests")
    for f in sorted(os.listdir(tdir)):
        if f.endswith(".py"):
            with open(os.path.join(tdir, f)) as fh:
                txt.append(fh.read())
    return "\n".join(txt)


def check_tested(name, target, corpus):
    """An impl op counts as tested when the op name or the mapped symbol
    appears as a whole word in tests/ — import-only mappings can no
    longer pass silently (round-5 VERDICT weak-spot 1). Word-boundary
    matching so short names ('abs', 'sum') cannot ride on substrings of
    unrelated identifiers."""
    if re.search(rf"\b{re.escape(name)}\b", corpus):
        return True
    sym = target.split(":")[-1].split(".")[-1] if ":" in target else target
    return re.search(rf"\b{re.escape(sym)}\b", corpus) is not None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true")
    args = ap.parse_args()

    names = [l.strip() for l in
             open(os.path.join(REPO, "tools", "op_catalog.txt"))
             if l.strip() and not l.lstrip().startswith("#")]
    corpus = _tests_corpus()
    rows, blanks, bad, untested = [], [], [], []
    counts = {"impl": 0, "absorbed": 0, "adr": 0, "na": 0}
    for n in names:
        status, target = resolve(n)
        if status is None:
            blanks.append(n)
            rows.append((n, "BLANK", ""))
            continue
        if status == "impl" and not check_target(target):
            bad.append((n, target))
        if status == "impl" and not check_tested(n, target, corpus):
            untested.append((n, target))
        counts[status] += 1
        rows.append((n, status, target))

    out = os.path.join(REPO, "docs", "op_coverage.md")
    with open(out, "w") as f:
        f.write("# Forward-operator coverage vs the reference catalog\n\n")
        f.write("Generated by `python tools/op_coverage.py` from "
                "`tools/op_catalog.txt` (extracted from the reference's "
                "registration macros; see SURVEY Appendix A).\n\n")
        total = len(names)
        f.write(f"**{total} catalog ops**: {counts['impl']} implemented, "
                f"{counts['absorbed']} absorbed by XLA/JAX, "
                f"{counts['adr']} ADR'd out of scope, {counts['na']} n/a "
                f"(other-backend/engine), {len(blanks)} blank.\n\n")
        f.write(f"Implemented + absorbed = "
                f"{counts['impl'] + counts['absorbed']} / "
                f"{total - counts['na']} TPU-meaningful ops.\n\n")
        f.write("| reference op | status | mapping |\n|---|---|---|\n")
        for n, s, tgt in rows:
            f.write(f"| `{n}` | {s} | {tgt} |\n")
    print(f"wrote {out}")
    print(f"{len(names)} ops: {counts} blanks={len(blanks)}")
    if blanks:
        print("BLANK:", " ".join(blanks))
    if bad:
        print("BAD TARGETS:")
        for n, tgt in bad:
            print(f"  {n} -> {tgt}")
    if untested:
        print(f"UNTESTED impl ops ({len(untested)}):")
        for n, tgt in untested:
            print(f"  {n} -> {tgt}")
    if args.check and (blanks or bad or untested):
        sys.exit(1)


if __name__ == "__main__":
    main()
