#!/usr/bin/env python
"""Bench regression gate: compare the two newest BENCH_r{N}.json files and
fail on a >5% throughput drop.

TPU-native equivalent of the reference's PR-gated op benchmark
(reference: tools/check_op_benchmark_result.py:69-90 — a PR fails if
gpu_time regresses more than 5% vs the develop branch).

Usage: python tools/check_bench_regression.py [--threshold 0.05] [dir]
Exit code 1 on regression, 0 otherwise (including when fewer than two
rounds exist yet).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys


def load_value(path):
    """Returns (value, metric) or None for rounds with no parsed result
    (e.g. the round-1 file predates bench.py's JSON line)."""
    with open(path) as f:
        data = json.load(f)
    parsed = data.get("parsed", data)
    if not isinstance(parsed, dict) or "value" not in parsed:
        return None
    return float(parsed["value"]), parsed.get("metric", "?")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("dir", nargs="?", default=".")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="max allowed fractional drop (default 5%%)")
    args = ap.parse_args()

    files = glob.glob(os.path.join(args.dir, "BENCH_r*.json"))
    files.sort(key=lambda p: int(re.search(r"r(\d+)", os.path.basename(p))
                                 .group(1)))
    loaded = [(p, load_value(p)) for p in files]
    loaded = [(p, v) for p, v in loaded if v is not None]
    if len(loaded) < 2:
        print(f"bench gate: {len(loaded)} comparable round(s) recorded, "
              f"nothing to compare")
        return 0

    (prev_path, (prev, metric)), (cur_path, (cur, _)) = loaded[-2:]
    change = (cur - prev) / prev
    print(f"bench gate [{metric}]: {os.path.basename(prev_path)} "
          f"{prev:.2f} -> {os.path.basename(cur_path)} {cur:.2f} "
          f"({change * 100:+.2f}%)")
    if -change > args.threshold:
        print(f"FAIL: throughput dropped more than "
              f"{args.threshold * 100:.0f}% "
              f"(reference gate: check_op_benchmark_result.py:69)")
        return 1
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
