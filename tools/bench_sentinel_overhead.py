#!/usr/bin/env python
"""Sentinel overhead microbench — guarded vs unguarded step time, one JSON
document.

    python -m tools.bench_sentinel_overhead
    python -m tools.bench_sentinel_overhead --check-every 10 --json out.json

Runs the same synthetic training loop (MLP + SGD, fixed data) three ways —
no sentinel, sentinel probing every step, sentinel probing every
``--check-every`` steps — and reports median steady-state step times. The
acceptance budget for the guarded path is ≤5% over unguarded
(tests/test_sentinel_e2e.py carries the ``slow``-marked assertion); the
amortized column should be indistinguishable from baseline. The probe's
cost model: one extra fused XLA program over grads+loss and one 2-float
host fetch per *guarded* step, zero work on amortized-out steps.
"""
from __future__ import annotations

import argparse
import json
import statistics
import sys
import time


def _build(hidden: int, batch: int, seed: int = 0):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn

    rng = np.random.RandomState(seed)
    net = nn.Sequential(
        nn.Linear(hidden, hidden), nn.ReLU(),
        nn.Linear(hidden, hidden), nn.ReLU(),
        nn.Linear(hidden, 1))
    opt = paddle.optimizer.Momentum(learning_rate=1e-3,
                                    parameters=net.parameters())
    x = paddle.to_tensor(rng.randn(batch, hidden).astype("float32"))
    y = paddle.to_tensor(rng.randn(batch, 1).astype("float32"))
    return net, opt, x, y


def _run(steps: int, warmup: int, hidden: int, batch: int,
         check_every=None):
    """Median per-step wall time (seconds) after warmup; ``check_every``
    None means no sentinel at all."""
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import sentinel

    net, opt, x, y = _build(hidden, batch)
    s = None
    if check_every is not None:
        s = sentinel.Sentinel(
            sentinel.SentinelConfig(check_every=check_every,
                                    warmup_steps=steps + warmup + 1),
            optimizer=opt)

    def one_step():
        loss = F.mse_loss(net(x), y)
        loss.backward()
        if s is not None:
            s.observe(loss=loss)
        opt.step()
        opt.clear_grad()
        return loss

    times = []
    for i in range(warmup + steps):
        t0 = time.perf_counter()
        loss = one_step()
        # the bench must not let async dispatch hide the probe's sync:
        # block on the step's output so each sample is a full step
        jax.block_until_ready(loss._data)
        if i >= warmup:
            times.append(time.perf_counter() - t0)
    if s is not None:
        s.detach()
    return statistics.median(times), times


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=60,
                    help="measured steps per variant (default 60)")
    ap.add_argument("--warmup", type=int, default=10,
                    help="untimed compile/steady-state steps (default 10)")
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--check-every", type=int, default=10,
                    help="amortization interval for the third variant")
    ap.add_argument("--json", default=None,
                    help="also write the JSON document to this path")
    args = ap.parse_args(argv)

    unguarded, _ = _run(args.steps, args.warmup, args.hidden, args.batch)
    guarded, _ = _run(args.steps, args.warmup, args.hidden, args.batch,
                      check_every=1)
    amortized, _ = _run(args.steps, args.warmup, args.hidden, args.batch,
                        check_every=args.check_every)

    def pct(t):
        return 100.0 * (t - unguarded) / unguarded

    doc = {
        "config": {"steps": args.steps, "warmup": args.warmup,
                   "hidden": args.hidden, "batch": args.batch,
                   "check_every": args.check_every},
        "unguarded_ms": unguarded * 1e3,
        "guarded_ms": guarded * 1e3,
        "amortized_ms": amortized * 1e3,
        "guarded_overhead_pct": pct(guarded),
        "amortized_overhead_pct": pct(amortized),
        "budget_pct": 5.0,
        "within_budget": pct(guarded) <= 5.0,
    }
    out = json.dumps(doc, indent=2)
    print(out)
    if args.json:
        with open(args.json, "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
