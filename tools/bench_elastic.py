#!/usr/bin/env python
"""Host-loss chaos campaign + watchdog overhead microbench, one JSON doc.

    python -m tools.bench_elastic                   # run the campaign
    python -m tools.bench_elastic --check           # CI gate (run_tests.py
                                                    #   --bench-elastic)
    python -m tools.bench_elastic --write-baseline  # refresh the committed
                                                    #   bench_elastic_baseline.json

Two halves, matching the elastic_runtime acceptance bars
(docs/fault_tolerance.md, "Surviving host loss"):

1. **Kill matrix × detection-latency budget.** Every way a host can
   "disappear" is simulated in-process against the real detector and the
   wall-clock to detection is measured against an explicit budget:

   - ``watchdog_hang`` — a guarded step that never disarms (the survivor
     side of a peer SIGKILLed mid-allreduce); the StepWatchdog must fire
     within ``deadline + a few polls``. Run at several deadlines.
   - ``heartbeat_silence`` — a BeaconSender stops beating (the host was
     SIGKILLed); the HeartbeatCoordinator must declare death within
     ``interval * miss_threshold + sweep slack``.
   - ``heartbeat_partition`` — the ``heartbeat_partition:N:drop`` fault
     site latches the sender silent while the process lives; same
     declaration budget (the partition case).
   - ``coordinator_partition`` — the coordinator dies; the *sender* must
     declare ``coordinator_lost`` within the same symmetric budget.
   - ``slow_link`` — one beacon delayed by ``slow_link:N:delay`` (a
     transient blip strictly shorter than the death window) must NOT
     produce a death declaration: the false-positive bar.

2. **Watchdog overhead microbench.** The same fixed CPU-bound step is
   timed bare and under ``arm``/``disarm``; the acceptance bar is ≤2%
   overhead (the step path is two clock reads + two short lock sections).
   Min-of-reps on both sides to shed scheduler noise.

Absolute latencies are machine-dependent; the committed baseline
(``bench_elastic_baseline.json``) records them for reference, and the
gate checks the *budgets* (derived from the configured deadlines, not the
machine) plus the structural invariants (everything detected, no false
positive, every declared death preceded by its flight event).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "bench_elastic_baseline.json")

#: heartbeat tuning for the campaign: fast enough to keep the bench
#: seconds-long, slow enough that CI-box scheduling jitter (~10ms) cannot
#: fake a missed interval.
HB_INTERVAL_S = 0.15
HB_MISS = 3

#: the transient-blip delay for the slow_link scenario — strictly inside
#: the death window (HB_INTERVAL_S * HB_MISS = 0.45).
SLOW_LINK_DELAY_S = 0.12


def _arm_faults(spec):
    from paddle_tpu.utils import resilience
    if spec is None:
        os.environ.pop("PADDLE_TPU_FAULT_SPEC", None)
    else:
        os.environ["PADDLE_TPU_FAULT_SPEC"] = spec
    resilience._reset_fault_injector_for_tests()


def _wait_until(pred, timeout_s, poll_s=0.01):
    """Wall-clock until pred() turns true (or timeout); returns (ok, s)."""
    t0 = time.perf_counter()
    deadline = t0 + timeout_s
    while time.perf_counter() < deadline:
        if pred():
            return True, time.perf_counter() - t0
        time.sleep(poll_s)
    return pred(), time.perf_counter() - t0


def bench_watchdog_hang(deadline_s):
    """A guarded step that never completes: detection ≤ deadline + polls."""
    from paddle_tpu.distributed.elastic_runtime import StepWatchdog
    fired = []
    wd = StepWatchdog(deadline_s,
                      on_timeout=lambda step, el: fired.append(el))
    budget = deadline_s + 4 * wd._poll_s + 0.25
    t0 = time.perf_counter()
    wd.arm(step=7)
    ok, _ = _wait_until(lambda: bool(fired), budget + 1.0)
    detect = time.perf_counter() - t0
    wd.stop()
    return {"scenario": "watchdog_hang", "deadline_s": deadline_s,
            "detected": ok, "detect_s": round(detect, 4),
            "budget_s": round(budget, 4)}


def _flight_has(kind, since_idx=0):
    from paddle_tpu.observability import flight
    return any(e.get("kind") == kind
               for e in flight.default_recorder().events()[since_idx:])


def bench_heartbeat(scenario):
    """heartbeat_silence / heartbeat_partition: a host goes quiet (stopped
    sender vs latched fault-site partition); the coordinator must declare
    death inside the window AND record the flight event before on_death."""
    from paddle_tpu.distributed.elastic_runtime import (
        BeaconSender, HeartbeatConfig, HeartbeatCoordinator)
    from paddle_tpu.observability import flight

    if scenario == "heartbeat_partition":
        # the 3rd beat and every later one is dropped (latching partition)
        _arm_faults("heartbeat_partition:3:drop")
    else:
        _arm_faults(None)
    cfg = HeartbeatConfig(interval_s=HB_INTERVAL_S, miss_threshold=HB_MISS)
    deaths = []
    event_first = []

    n_events = len(flight.default_recorder().events())

    def on_death(rank, info):
        # the acceptance contract: flight event lands BEFORE teardown
        event_first.append(_flight_has("distributed.host_lost", n_events))
        deaths.append((rank, time.perf_counter()))

    coord = HeartbeatCoordinator(config=cfg, on_death=on_death).start()
    sender = BeaconSender(coord.address, rank=1, config=cfg).start()
    # let the host register as alive first
    _wait_until(lambda: 1 in coord.snapshot(), 5.0)
    t0 = time.perf_counter()
    if scenario == "heartbeat_silence":
        sender.stop()   # the SIGKILL analog: beats just stop
    budget = cfg.death_after_s + 4 * cfg.interval_s + 0.5
    ok, _ = _wait_until(lambda: bool(deaths), budget + 2.0)
    detect = (deaths[0][1] - t0) if deaths else float("inf")
    sender.stop()
    coord.stop()
    _arm_faults(None)
    return {"scenario": scenario,
            "death_after_s": round(cfg.death_after_s, 4),
            "detected": ok,
            "flight_event_before_teardown": bool(event_first
                                                 and event_first[0]),
            "detect_s": round(detect, 4), "budget_s": round(budget, 4)}


def bench_coordinator_partition():
    """The symmetric half: the coordinator dies, the sender must notice."""
    from paddle_tpu.distributed.elastic_runtime import (
        BeaconSender, HeartbeatConfig, HeartbeatCoordinator)
    _arm_faults(None)
    cfg = HeartbeatConfig(interval_s=HB_INTERVAL_S, miss_threshold=HB_MISS)
    lost = []
    coord = HeartbeatCoordinator(config=cfg).start()
    sender = BeaconSender(coord.address, rank=1, config=cfg,
                          on_coordinator_lost=lambda:
                          lost.append(time.perf_counter()))
    sender.start()
    _wait_until(lambda: 1 in coord.snapshot(), 5.0)
    t0 = time.perf_counter()
    coord.stop()
    budget = cfg.death_after_s + 4 * cfg.interval_s + 0.5
    ok, _ = _wait_until(lambda: bool(lost), budget + 2.0)
    detect = (lost[0] - t0) if lost else float("inf")
    sender.stop()
    return {"scenario": "coordinator_partition",
            "death_after_s": round(cfg.death_after_s, 4),
            "detected": ok,
            "detect_s": round(detect, 4), "budget_s": round(budget, 4)}


def bench_slow_link():
    """A transient slow link (one delayed beacon, strictly inside the death
    window) must NOT be declared a death — the false-positive bar."""
    from paddle_tpu.distributed.elastic_runtime import (
        BeaconSender, HeartbeatConfig, HeartbeatCoordinator)
    _arm_faults("slow_link:2:delay")
    cfg = HeartbeatConfig(interval_s=HB_INTERVAL_S, miss_threshold=HB_MISS)
    deaths = []
    coord = HeartbeatCoordinator(
        config=cfg, on_death=lambda r, i: deaths.append(r)).start()
    sender = BeaconSender(coord.address, rank=1, config=cfg).start()
    # hold the link open across the delayed beat plus two full windows
    time.sleep(SLOW_LINK_DELAY_S + 2 * cfg.death_after_s)
    snapshot = coord.snapshot()
    sender.stop()
    coord.stop()
    _arm_faults(None)
    return {"scenario": "slow_link",
            "delay_s": SLOW_LINK_DELAY_S,
            "death_after_s": round(cfg.death_after_s, 4),
            "false_positive": bool(deaths),
            "host_seen": 1 in snapshot}


def bench_watchdog_overhead(steps, reps):
    """Fixed ~10ms numpy step, bare vs guarded; min-of-reps on both sides."""
    import numpy as np

    from paddle_tpu.distributed.elastic_runtime import StepWatchdog

    # ~10ms of GIL-releasing C work, like a real train step (jax/XLA
    # dispatch drops the GIL). A pure-Python busy loop would instead
    # measure the scheduler tax of the watchdog *thread's* timed waits on
    # a thread that never yields the GIL — a contention no real step has.
    a = np.random.default_rng(0).standard_normal((768, 768)) \
        .astype(np.float32)

    def step_fn():
        return a @ a

    def run_bare():
        t0 = time.perf_counter()
        for _ in range(steps):
            step_fn()
        return time.perf_counter() - t0

    # generous deadline: the watchdog must never fire during the bench,
    # only tick its poll loop in the background like production
    wd = StepWatchdog(deadline_s=60.0)

    def run_guarded():
        t0 = time.perf_counter()
        for s in range(steps):
            wd.arm(s)
            step_fn()
            wd.disarm()
        return time.perf_counter() - t0

    run_bare(), run_guarded()   # warm both paths
    bare = min(run_bare() for _ in range(reps))
    guarded = min(run_guarded() for _ in range(reps))
    wd.stop()
    overhead_pct = max(0.0, (guarded - bare) / bare * 100.0)
    return {"steps": steps, "reps": reps,
            "bare_s": round(bare, 4), "guarded_s": round(guarded, 4),
            "overhead_pct": round(overhead_pct, 3),
            "fired": wd.fired}


def run_campaign(args) -> dict:
    # the latched fault sites read this at import; pin it before any
    # paddle_tpu import so the slow_link scenario delay is the bench's
    os.environ.setdefault("PADDLE_TPU_FAULT_SLOW_LINK_S",
                          str(SLOW_LINK_DELAY_S))
    detection = []
    for d in args.deadlines:
        detection.append(bench_watchdog_hang(d))
    detection.append(bench_heartbeat("heartbeat_silence"))
    detection.append(bench_heartbeat("heartbeat_partition"))
    detection.append(bench_coordinator_partition())
    detection.append(bench_slow_link())
    overhead = bench_watchdog_overhead(args.steps, args.reps)
    return {"bench": "elastic",
            "heartbeat": {"interval_s": HB_INTERVAL_S, "miss": HB_MISS},
            "detection": detection,
            "watchdog_overhead": overhead}


def check(doc, baseline=None):
    """Acceptance bars: budgets are derived from the configured deadlines
    (machine-independent); the overhead bar is the ≤2% contract."""
    problems = []
    for row in doc["detection"]:
        sc = row["scenario"]
        if sc == "slow_link":
            if row["false_positive"]:
                problems.append(
                    "slow_link: a transient delayed beacon was declared a "
                    "death (false positive)")
            if not row["host_seen"]:
                problems.append("slow_link: the host never registered")
            continue
        if not row["detected"]:
            problems.append(f"{sc}: never detected")
            continue
        if row["detect_s"] > row["budget_s"]:
            problems.append(
                f"{sc}: detected in {row['detect_s']}s, over the "
                f"{row['budget_s']}s budget")
        if sc in ("heartbeat_silence", "heartbeat_partition") \
                and not row.get("flight_event_before_teardown"):
            problems.append(
                f"{sc}: the distributed.host_lost flight event did not "
                f"precede the on_death teardown callback")
    ov = doc["watchdog_overhead"]
    if ov["fired"]:
        problems.append("watchdog fired during the overhead microbench "
                        "(a 60s deadline on a millisecond step)")
    if ov["overhead_pct"] > 2.0:
        problems.append(
            f"watchdog overhead {ov['overhead_pct']}% > 2% of the step "
            f"(bare {ov['bare_s']}s vs guarded {ov['guarded_s']}s)")
    if baseline:
        bov = baseline.get("watchdog_overhead", {})
        # relative guard with generous slack: a 10x regression in the
        # arm/disarm cost shows up here even while still under 2%
        base_pct = bov.get("overhead_pct", 0.0)
        if base_pct and ov["overhead_pct"] > max(2.0, 10 * base_pct):
            problems.append(
                f"watchdog overhead {ov['overhead_pct']}% > 10x baseline "
                f"{base_pct}%")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadlines", type=float, nargs="*",
                    default=[0.2, 0.5],
                    help="watchdog kill-matrix deadlines, seconds")
    ap.add_argument("--steps", type=int, default=100,
                    help="overhead microbench steps per rep")
    ap.add_argument("--reps", type=int, default=5,
                    help="overhead microbench repetitions (min taken)")
    ap.add_argument("--check", action="store_true",
                    help="gate the acceptance bars + baseline budgets")
    ap.add_argument("--write-baseline", action="store_true",
                    help="refresh the committed baseline")
    ap.add_argument("--baseline", default=BASELINE)
    args = ap.parse_args(argv)

    doc = run_campaign(args)
    json.dump(doc, sys.stdout, indent=2)
    print()

    if args.write_baseline:
        base = {
            "version": 1,
            "detection": {
                row["scenario"] + (f"_{row['deadline_s']}"
                                   if "deadline_s" in row else ""):
                row.get("detect_s")
                for row in doc["detection"] if "detect_s" in row},
            "watchdog_overhead": {
                "overhead_pct": doc["watchdog_overhead"]["overhead_pct"]},
        }
        with open(args.baseline, "w") as f:
            json.dump(base, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"bench elastic: baseline written to {args.baseline}",
              file=sys.stderr)

    if args.check:
        baseline = None
        try:
            with open(args.baseline) as f:
                baseline = json.load(f)
        except (OSError, ValueError):
            print(f"bench elastic: no baseline at {args.baseline} "
                  f"(relative budgets skipped)", file=sys.stderr)
        problems = check(doc, baseline)
        if problems:
            for p in problems:
                print(f"FAIL: {p}", file=sys.stderr)
            return 1
        print("OK: kill matrix detected in budget, no false positives, "
              "watchdog overhead under 2%", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
