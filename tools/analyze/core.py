"""Analyzer core: source model, findings, noqa suppression, baseline.

Design notes
------------
Fingerprints are line-number independent: sha1(rule | relpath | stripped
source-line text). Unrelated edits that shift line numbers therefore do not
invalidate the baseline; duplicate identical lines in one file share a
fingerprint, so the baseline stores an occurrence *count* per fingerprint
and only occurrences beyond that count register as new (the same scheme
ruff/pylint baselines use).

Everything here is stdlib-only so the gate can run before pytest without
importing jax or paddle_tpu.
"""
from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: matches an inline suppression: `# noqa` (all rules) or `# noqa: PTA001`
#: or `# noqa: PTA001,PTA004 -- justification text`
_NOQA_RE = re.compile(
    r"#\s*noqa\b(?::\s*(?P<codes>[A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*))?"
    r"(?:\s*--\s*(?P<why>\S.*))?",
    re.IGNORECASE)

_ALL_CODES = "__all__"


@dataclass(frozen=True)
class Finding:
    rule: str          # "PTA001"
    path: str          # repo-root-relative posix path
    line: int          # 1-based
    col: int           # 0-based
    message: str
    anchor: str = ""   # text the fingerprint hashes (defaults to source line)
    severity: str = "error"  # "error" gates; "warning" gates under --strict

    @property
    def fingerprint(self) -> str:
        h = hashlib.sha1()
        h.update(f"{self.rule}|{self.path}|{self.anchor}".encode())
        return h.hexdigest()[:16]

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: " \
               f"{self.rule} {self.message}"


class SourceFile:
    """One parsed python (or text) file plus its suppression map."""

    def __init__(self, abspath: str, relpath: str):
        self.abspath = abspath
        self.relpath = relpath
        with open(abspath, "rb") as f:
            raw = f.read()
        self.text = raw.decode("utf-8", errors="replace")
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[Tuple[int, str]] = None
        if relpath.endswith(".py"):
            try:
                self.tree = ast.parse(self.text, filename=abspath)
            except SyntaxError as e:
                self.parse_error = (e.lineno or 0, e.msg or "syntax error")
        #: line -> suppressed codes; line -> bool(justification present)
        self.noqa: Dict[int, set] = {}
        self.noqa_justified: Dict[int, bool] = {}
        self._parse_noqa()

    def _parse_noqa(self):
        for i, ln in enumerate(self.lines, 1):
            if "noqa" not in ln:
                continue
            m = _NOQA_RE.search(ln)
            if not m:
                continue
            codes = m.group("codes")
            if codes:
                self.noqa[i] = {c.strip().upper() for c in codes.split(",")}
            else:
                self.noqa[i] = {_ALL_CODES}
            self.noqa_justified[i] = bool(m.group("why"))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node_or_line, message: str,
                col: Optional[int] = None, anchor: str = "",
                severity: str = "error") -> Finding:
        if isinstance(node_or_line, int):
            line, c = node_or_line, (col or 0)
        else:
            line = getattr(node_or_line, "lineno", 0)
            c = getattr(node_or_line, "col_offset", 0) if col is None else col
        return Finding(rule=rule, path=self.relpath, line=line, col=c,
                       message=message,
                       anchor=anchor or self.line_text(line),
                       severity=severity)

    def is_suppressed(self, f: Finding) -> bool:
        codes = self.noqa.get(f.line)
        if not codes:
            return False
        if f.rule in codes:
            return True
        # A blanket codeless `# noqa` suppresses everything EXCEPT findings
        # about the noqa comment itself (anchor "noqa-hygiene:*") — a bare
        # suppression must not be able to silence the rule that polices
        # bare suppressions.
        return (_ALL_CODES in codes
                and not f.anchor.startswith("noqa-hygiene:"))


class Project:
    """All files under the analyzed paths, plus a lazily built call graph."""

    def __init__(self, root: str, paths: List[str]):
        self.root = os.path.abspath(root)
        self.files: List[SourceFile] = []
        self.by_relpath: Dict[str, SourceFile] = {}
        self._callgraph = None
        for p in paths:
            ap = p if os.path.isabs(p) else os.path.join(self.root, p)
            if os.path.isfile(ap):
                self._add(ap)
            else:
                for dirpath, dirnames, filenames in os.walk(ap):
                    dirnames[:] = sorted(
                        d for d in dirnames
                        if d not in ("__pycache__", ".git"))
                    for fn in sorted(filenames):
                        if fn.endswith(".py"):
                            self._add(os.path.join(dirpath, fn))

    def _add(self, abspath: str):
        rel = os.path.relpath(abspath, self.root).replace(os.sep, "/")
        if rel in self.by_relpath:
            return
        sf = SourceFile(abspath, rel)
        self.files.append(sf)
        self.by_relpath[rel] = sf

    @property
    def callgraph(self):
        if self._callgraph is None:
            from . import callgraph
            self._callgraph = callgraph.build(self)
        return self._callgraph

    def read_rootfile(self, relpath: str) -> Optional[SourceFile]:
        """A file addressed from the repo root (e.g. tools/op_catalog.txt)
        whether or not it was in the analyzed paths."""
        sf = self.by_relpath.get(relpath)
        if sf is not None:
            return sf
        ap = os.path.join(self.root, relpath)
        if not os.path.isfile(ap):
            return None
        return SourceFile(ap, relpath)


# -- rule running -------------------------------------------------------------

def run_rules(project: Project, rules) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project.files:
        if sf.parse_error is not None:
            line, msg = sf.parse_error
            findings.append(Finding("PTA000", sf.relpath, line, 0,
                                    f"syntax error: {msg}", anchor=msg))
            continue
        for rule in rules:
            findings.extend(rule.visit_file(sf, project))
    for rule in rules:
        findings.extend(rule.finalize(project))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def filter_noqa(project: Project,
                findings: List[Finding]) -> Tuple[List[Finding],
                                                  List[Finding]]:
    """Split into (kept, suppressed) using each file's inline noqa map."""
    kept, suppressed = [], []
    for f in findings:
        sf = project.by_relpath.get(f.path)
        if sf is not None and sf.is_suppressed(f):
            suppressed.append(f)
        else:
            kept.append(f)
    return kept, suppressed


# -- baseline -----------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> {"rule", "path", "message", "count"}; {} if absent."""
    if not path or not os.path.isfile(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')!r}")
    return data.get("findings", {})


def split_findings(findings: List[Finding], baseline: Dict[str, dict]
                   ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """Return (new, baselined, expired_fingerprints).

    For each fingerprint the first `count` occurrences (in line order —
    run_rules sorts) are baselined; any beyond that are new. Baseline
    entries whose fingerprint occurs fewer times than recorded are
    (partially) expired — reported so `--write-baseline` can prune them.
    """
    seen: Dict[str, int] = {}
    new, baselined = [], []
    for f in findings:
        fp = f.fingerprint
        allowed = baseline.get(fp, {}).get("count", 0)
        seen[fp] = seen.get(fp, 0) + 1
        if seen[fp] <= allowed:
            baselined.append(f)
        else:
            new.append(f)
    expired = [fp for fp, entry in baseline.items()
               if seen.get(fp, 0) < entry.get("count", 0)]
    return new, baselined, expired


def baseline_payload(findings: List[Finding]) -> dict:
    entries: Dict[str, dict] = {}
    for f in findings:
        e = entries.get(f.fingerprint)
        if e is None:
            entries[f.fingerprint] = {"rule": f.rule, "path": f.path,
                                      "message": f.message, "count": 1}
        else:
            e["count"] += 1
    return {"version": BASELINE_VERSION, "findings": entries}


def write_baseline(path: str, findings: List[Finding]):
    payload = baseline_payload(findings)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")


# -- shared AST helpers (used by several rules) -------------------------------

#: attribute reads that are trace-static python values even on a traced
#: array (jax shapes/dtypes are concrete at trace time)
STATIC_VALUE_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize"}

#: builtins whose *result* is a host python value. If their argument is a
#: traced value that is its own bug (PTA001's cast check flags it); for
#: static-ness purposes the result is host-side either way.
STATIC_RESULT_BUILTINS = {
    "int", "float", "bool", "str", "len", "min", "max", "abs", "round",
    "sum", "tuple", "list", "sorted", "range", "enumerate", "zip",
    "divmod", "pow", "isinstance", "getattr", "hasattr",
}


def is_static_host_expr(node: ast.AST, static_names=frozenset()) -> bool:
    """True when ``node`` provably evaluates to a host python value
    (int/float/tuple/...), never a traced array.

    Used by PTA001/PTA002 to stop flagging ``np.sqrt(head_dim)``-style
    numpy-on-static-shapes calls: constants, ``.shape``/``.ndim`` reads,
    ``len()``/``int()`` results, arithmetic over those, and names proven
    static by local assignment analysis (``static_names``).
    Conservative: anything unrecognized is NOT static.
    """
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return node.id in static_names
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        return all(is_static_host_expr(e, static_names) for e in node.elts)
    if isinstance(node, ast.Starred):
        return is_static_host_expr(node.value, static_names)
    if isinstance(node, ast.UnaryOp):
        return is_static_host_expr(node.operand, static_names)
    if isinstance(node, ast.BinOp):
        return (is_static_host_expr(node.left, static_names)
                and is_static_host_expr(node.right, static_names))
    if isinstance(node, ast.BoolOp):
        return all(is_static_host_expr(v, static_names) for v in node.values)
    if isinstance(node, ast.Compare):
        return (is_static_host_expr(node.left, static_names)
                and all(is_static_host_expr(c, static_names)
                        for c in node.comparators))
    if isinstance(node, ast.IfExp):
        return all(is_static_host_expr(n, static_names)
                   for n in (node.test, node.body, node.orelse))
    if isinstance(node, ast.Attribute):
        return node.attr in STATIC_VALUE_ATTRS
    if isinstance(node, ast.Subscript):
        # x.shape[0], static_tuple[i] — indexing a static container is
        # static regardless of how exotic the index expression is
        return is_static_host_expr(node.value, static_names)
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id in STATIC_RESULT_BUILTINS:
            return True
        if isinstance(f, ast.Attribute):
            base = dotted_name(f.value)
            if base in ("np", "numpy", "math"):
                # np.log2(static) etc: numpy math over provably-static
                # inputs yields a host scalar/array of static data
                return (all(is_static_host_expr(a, static_names)
                            for a in node.args)
                        and all(is_static_host_expr(k.value, static_names)
                                for k in node.keywords))
    return False


def static_local_names(func_node: ast.AST, params) -> set:
    """Names inside ``func_node`` provably bound only to static host
    values: fixpoint over simple assignments and for-targets; any name
    that is a parameter or has a non-static binding is excluded."""
    candidates: Dict[str, List[ast.AST]] = {}
    poisoned = set(params)

    def _targets(t):
        if isinstance(t, ast.Name):
            yield t.id
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from _targets(e)

    for node in walk_own_body(func_node):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    candidates.setdefault(tgt.id, []).append(node.value)
                else:  # tuple unpack etc — too clever, poison all names
                    poisoned.update(_targets(tgt))
        elif isinstance(node, ast.AnnAssign):
            if isinstance(node.target, ast.Name) and node.value is not None:
                candidates.setdefault(node.target.id, []).append(node.value)
        elif isinstance(node, ast.AugAssign):
            poisoned.update(_targets(node.target))
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            poisoned.update(_targets(node.target))
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    poisoned.update(_targets(item.optional_vars))
        elif isinstance(node, (ast.NamedExpr,)):
            poisoned.update(_targets(node.target))
        elif isinstance(node, ast.comprehension):
            poisoned.update(_targets(node.target))

    static: set = set()
    for _ in range(len(candidates) + 1):
        grew = False
        for name, values in candidates.items():
            if name in static or name in poisoned:
                continue
            if all(is_static_host_expr(v, static) for v in values):
                static.add(name)
                grew = True
        if not grew:
            break
    return static


def _binding_target_names(t):
    if isinstance(t, ast.Name):
        yield t.id
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _binding_target_names(e)
    elif isinstance(t, ast.Starred):
        yield from _binding_target_names(t.value)


_TAINT_MUTATORS = {"append", "extend", "insert", "add", "update"}


def tainted_local_names(func_node: ast.AST, params,
                        static_names=frozenset()) -> set:
    """Names that may hold *traced* values: the function's parameters plus
    anything transitively bound from them — via assignment, for-targets,
    augmented assignment, or in-place container mutation
    (``xs.append(tainted)``).

    A binding whose RHS is a provably-static host expression
    (:func:`is_static_host_expr`, e.g. ``h = x.shape[2]``) does NOT
    propagate taint even though it mentions a tainted name: shape reads
    are concrete at trace time. Closure variables from enclosing scopes
    are never tainted — under jit they are captured python constants,
    not tracers.
    """
    bindings: List[Tuple[list, ast.AST]] = []
    for node in walk_own_body(func_node):
        if isinstance(node, ast.Assign):
            names = [n for t in node.targets
                     for n in _binding_target_names(t)]
            bindings.append((names, node.value))
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                bindings.append(
                    (list(_binding_target_names(node.target)), node.value))
        elif isinstance(node, ast.AugAssign):
            bindings.append(
                (list(_binding_target_names(node.target)), node.value))
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            bindings.append(
                (list(_binding_target_names(node.target)), node.iter))
        elif isinstance(node, ast.comprehension):
            bindings.append(
                (list(_binding_target_names(node.target)), node.iter))
        elif isinstance(node, ast.NamedExpr):
            bindings.append(
                (list(_binding_target_names(node.target)), node.value))
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and isinstance(node.func.value, ast.Name)
              and node.func.attr in _TAINT_MUTATORS and node.args):
            bindings.append(([node.func.value.id], node.args[0]))

    tainted = set(params)

    def _mentions_tainted(expr):
        return any(isinstance(n, ast.Name) and n.id in tainted
                   for n in ast.walk(expr))

    for _ in range(len(bindings) + 1):
        grew = False
        for names, rhs in bindings:
            if all(n in tainted for n in names):
                continue
            if (not is_static_host_expr(rhs, static_names)
                    and _mentions_tainted(rhs)):
                tainted.update(names)
                grew = True
        if not grew:
            break
    return tainted


def mentions_any_name(expr: ast.AST, names) -> bool:
    """True if the expression subtree reads any of the given names."""
    return any(isinstance(n, ast.Name) and n.id in names
               for n in ast.walk(expr))


def dotted_name(node: ast.AST) -> str:
    """Flatten Name/Attribute chains: jax.lax.scan -> "jax.lax.scan"."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("?")
    return ".".join(reversed(parts))


def walk_own_body(func_node: ast.AST):
    """Yield nodes of a function's body without descending into nested
    function/class definitions (those are analyzed as their own units)."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
