"""Pure analysis passes over jaxprs and HLO text.

Everything here is a function of a (closed) jaxpr or an HLO dump — no
registry, no jit, no I/O — so each pass is unit-testable against tiny
hand-built programs (tests/test_trace_audit.py) without touching the
entrypoint machinery.
"""
from __future__ import annotations

import re
from typing import Any, Dict, Iterator, List, Tuple

#: primitives whose presence inside a compiled region means a host
#: round-trip (or a host callback that blocks the device stream)
HOST_TRANSFER_PRIMITIVES = {
    "device_put", "pure_callback", "io_callback", "debug_callback",
    "callback",
}

#: structured-control-flow primitives whose closed-over consts become
#: baked-in program constants (re-materialized per executable)
CONTROL_FLOW_PRIMITIVES = {"while", "cond", "scan"}

#: a closed-over const at/above this many elements inside a control-flow
#: body is worth a finding (64KiB of f32)
LARGE_CONST_ELEMENTS = 16384


def _sub_jaxprs(value: Any) -> Iterator[Any]:
    """Yield every (open) jaxpr reachable from an eqn param value —
    ClosedJaxpr, bare Jaxpr, or lists/tuples of either (cond branches)."""
    if value is None:
        return
    if isinstance(value, (list, tuple)):
        for v in value:
            yield from _sub_jaxprs(v)
        return
    inner = getattr(value, "jaxpr", None)  # ClosedJaxpr
    if inner is not None and hasattr(inner, "eqns"):
        yield value  # yield the CLOSED jaxpr: callers may want .consts
        return
    if hasattr(value, "eqns"):  # bare Jaxpr
        yield value


def _open(j: Any):
    return getattr(j, "jaxpr", j)


def iter_eqns(jaxpr: Any) -> Iterator[Any]:
    """All equations in a (closed) jaxpr, recursing into sub-jaxprs
    carried in equation params (pjit bodies, scan/while/cond branches)."""
    for eqn in _open(jaxpr).eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_eqns(sub)


def scan_transfers(jaxpr: Any) -> List[str]:
    """Names of host-transfer/callback primitives anywhere in the
    program, one entry per occurrence."""
    return [eqn.primitive.name for eqn in iter_eqns(jaxpr)
            if eqn.primitive.name in HOST_TRANSFER_PRIMITIVES]


def _record_const(out: List[Dict[str, Any]], kind: str, const: Any,
                  threshold: int) -> None:
    size = int(getattr(const, "size", 0) or 0)
    if size >= threshold:
        out.append({
            "control_flow": kind,
            "elements": size,
            "dtype": str(getattr(const, "dtype", "?")),
            "shape": list(getattr(const, "shape", ())),
        })


def scan_large_consts(jaxpr: Any,
                      threshold: int = LARGE_CONST_ELEMENTS
                      ) -> List[Dict[str, Any]]:
    """Closed-over constants of ``while``/``cond``/``scan`` bodies with
    ``size >= threshold`` elements. Large captured constants are baked
    into every executable that traces the loop — they should be loop
    carries or explicit arguments instead.

    Tracing hoists body-captured arrays to the TOP-LEVEL jaxpr's consts
    and threads them into the control-flow equation as plain operands, so
    the check is "a top-level constvar feeds a while/cond/scan directly";
    older-style consts embedded in the branch ClosedJaxprs are covered
    too. Consts reaching a loop through intermediate equations are not
    attributed (one-hop only — precise enough for the audit, cheap enough
    for every entrypoint)."""
    out: List[Dict[str, Any]] = []
    closed_const_of = {}  # id(Var) keys: Literal operands may be unhashable
    open_j = _open(jaxpr)
    for var, val in zip(getattr(open_j, "constvars", ()),
                        getattr(jaxpr, "consts", ())):
        closed_const_of[id(var)] = val
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name not in CONTROL_FLOW_PRIMITIVES:
            continue
        for invar in eqn.invars:
            if id(invar) in closed_const_of:
                _record_const(out, eqn.primitive.name,
                              closed_const_of[id(invar)], threshold)
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                for const in getattr(sub, "consts", ()):
                    _record_const(out, eqn.primitive.name, const, threshold)
    return out


def donation_opportunities(jaxpr: Any) -> Dict[str, Any]:
    """How many inputs could be donated: inputs whose (shape, dtype)
    matches an output's. A train step that updates parameters in place
    but donates nothing pays double-buffering for the whole parameter
    set; the matched byte count quantifies the waste."""
    closed = jaxpr
    open_j = _open(closed)
    key = lambda v: (tuple(getattr(v.aval, "shape", ())),
                     str(getattr(v.aval, "dtype", "?")))
    outs: Dict[Tuple, int] = {}
    for v in open_j.outvars:
        k = key(v)
        outs[k] = outs.get(k, 0) + 1
    matched, matched_bytes = 0, 0
    for v in open_j.invars:
        k = key(v)
        if outs.get(k, 0) > 0:
            outs[k] -= 1
            matched += 1
            aval = v.aval
            nbytes = 1
            for d in getattr(aval, "shape", ()):
                nbytes *= int(d)
            itemsize = getattr(getattr(aval, "dtype", None), "itemsize", 4)
            matched_bytes += nbytes * itemsize
    return {"donatable_inputs": matched, "donatable_bytes": matched_bytes,
            "total_inputs": len(open_j.invars)}


#: cross-rank collective primitives (jaxpr names). ``pmean`` lowers to
#: psum+div before the jaxpr, so psum covers it; ``psum2`` is what
#: shard_map's replication-rule rewrite turns psum into. ``pbroadcast``/
#: pvary are replication type-casts, not communication.
COLLECTIVE_PRIMITIVES = {
    "psum", "psum2", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pgather",
}

#: rewrite aliases -> the primitive the schedule should report
_PRIMITIVE_ALIASES = {"psum2": "psum"}


def _collective_axes(params: Dict[str, Any]) -> List[str]:
    for key in ("axis_name", "axes"):
        v = params.get(key)
        if v is None:
            continue
        if isinstance(v, (list, tuple)):
            return [str(a) for a in v if isinstance(a, str)]
        if isinstance(v, str):
            return [v]
    return []


def _aval_bytes(aval: Any) -> int:
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n * int(getattr(getattr(aval, "dtype", None), "itemsize", 4))


def _classify_perm(perm, axis_size) -> str:
    """ring (single cycle covering the axis) | shift (open chain over all
    ranks) | empty | partial (some rank never participates) | invalid
    (duplicate/out-of-range endpoints) | unknown (axis size unresolved)."""
    pairs = [(int(s), int(d)) for s, d in perm]
    if not pairs:
        return "empty"
    srcs = [s for s, _ in pairs]
    dsts = [d for _, d in pairs]
    if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
        return "invalid"
    if axis_size is None:
        return "unknown"
    ranks = set(range(axis_size))
    if any(s not in ranks for s in srcs) or any(d not in ranks
                                                for d in dsts):
        return "invalid"
    covered = set(srcs) | set(dsts)
    if covered != ranks:
        return "partial"
    if set(srcs) == ranks and set(dsts) == ranks:
        # walk the cycle from rank 0: a single cycle visits all ranks
        nxt = dict(pairs)
        seen, cur = set(), 0
        while cur not in seen:
            seen.add(cur)
            cur = nxt[cur]
        return "ring" if len(seen) == axis_size else "multi-cycle"
    if len(pairs) == axis_size - 1:
        return "shift"
    return "other"


def _sig(entry: Dict[str, Any]):
    """Rank-invariance signature of one schedule entry: what must match
    across cond branches for every rank to run the same collective."""
    return (entry["primitive"], tuple(entry["axes"]),
            tuple(entry["shape"]), entry["dtype"],
            tuple(map(tuple, entry["perm"] or ())))


def collective_schedule(jaxpr: Any) -> Tuple[List[Dict[str, Any]],
                                             List[Dict[str, Any]]]:
    """Extract the ordered per-rank collective schedule of a traced
    program and verify its SPMD invariants.

    Returns ``(schedule, issues)``. Each schedule entry records
    (primitive, axis names, operand shape/dtype, ppermute permutation,
    all_to_all split/concat dims, estimated wire bytes). Wire bytes are
    operand bytes entering the collective × the static trip count of
    enclosing scans — a regression counter for the audit gate, not an
    exact wire model.

    Issues found (each a dict with ``kind`` + message fields):

    - ``rank-divergent-cond``: a ``cond``/``switch`` whose branches carry
      different collective schedules — branch selection can differ per
      rank at runtime, so some ranks issue collectives peers never join.
    - ``broken-permutation``: a ppermute whose perm has duplicate or
      out-of-range endpoints, or covers only a strict subset of the axis
      (a broken ring: the uncovered rank never participates while its
      peers cycle).
    - ``alltoall-pairing``: consecutive all_to_alls on one axis whose
      split/concat dims are not transposes of each other — the return
      trip does not undo the dispatch and tokens land scrambled.
    """
    schedule: List[Dict[str, Any]] = []
    issues: List[Dict[str, Any]] = []

    def walk(j: Any, axis_sizes: Dict[str, int], mult: int,
             out: List[Dict[str, Any]]) -> None:
        for eqn in _open(j).eqns:
            name = eqn.primitive.name
            params = eqn.params
            if name == "shard_map":
                mesh = params.get("mesh")
                sizes = dict(axis_sizes)
                shape = getattr(mesh, "shape", None)
                if shape:
                    try:
                        sizes.update({str(k): int(v)
                                      for k, v in dict(shape).items()})
                    except (TypeError, ValueError):
                        pass
                for sub in _sub_jaxprs(params.get("jaxpr")):
                    walk(sub, sizes, mult, out)
                continue
            if name == "scan":
                trip = int(params.get("length", 1) or 1)
                for sub in _sub_jaxprs(params.get("jaxpr")):
                    walk(sub, axis_sizes, mult * trip, out)
                continue
            if name in ("cond", "switch"):
                branches = params.get("branches", ())
                sub_scheds: List[List[Dict[str, Any]]] = []
                for b in _sub_jaxprs(branches):
                    s: List[Dict[str, Any]] = []
                    walk(b, axis_sizes, mult, s)
                    sub_scheds.append(s)
                sigs = {tuple(_sig(e) for e in s) for s in sub_scheds}
                if len(sigs) > 1:
                    issues.append({
                        "kind": "rank-divergent-cond",
                        "branch_schedules": [
                            [e["primitive"] for e in s]
                            for s in sub_scheds],
                    })
                if sub_scheds:
                    # account the heaviest branch so wire bytes bound
                    # the true cost whichever branch a rank takes
                    heaviest = max(
                        sub_scheds,
                        key=lambda s: sum(e["bytes"] for e in s))
                    for e in heaviest:
                        e = dict(e)
                        e["context"] = "cond"
                        out.append(e)
                continue
            if name in COLLECTIVE_PRIMITIVES:
                avals = [v.aval for v in eqn.invars
                         if hasattr(v, "aval")
                         and getattr(v.aval, "shape", None) is not None]
                first = avals[0] if avals else None
                axes = _collective_axes(params)
                perm = params.get("perm")
                entry = {
                    "primitive": _PRIMITIVE_ALIASES.get(name, name),
                    "axes": axes,
                    "shape": list(getattr(first, "shape", ())),
                    "dtype": str(getattr(first, "dtype", "?")),
                    "perm": ([[int(s), int(d)] for s, d in perm]
                             if perm is not None else None),
                    "split_axis": params.get("split_axis"),
                    "concat_axis": params.get("concat_axis"),
                    "trip_count": mult,
                    "bytes": mult * sum(_aval_bytes(a) for a in avals),
                    "context": "top",
                }
                if name == "ppermute" and perm is not None:
                    size = None
                    for ax in axes:
                        if ax in axis_sizes:
                            size = axis_sizes[ax]
                            break
                    kind = _classify_perm(perm, size)
                    entry["perm_kind"] = kind
                    if kind in ("invalid", "partial"):
                        covered = sorted({int(r) for p in perm
                                          for r in p})
                        issues.append({
                            "kind": "broken-permutation",
                            "axis": axes[0] if axes else "?",
                            "axis_size": size,
                            "perm": entry["perm"],
                            "classification": kind,
                            "covered_ranks": covered,
                        })
                out.append(entry)
                continue
            for v in params.values():
                for sub in _sub_jaxprs(v):
                    walk(sub, axis_sizes, mult, out)

    walk(jaxpr, {}, 1, schedule)

    # paired all_to_alls (dispatch/return) must transpose their
    # split/concat dims; a lone all_to_all (compressed allreduce's
    # scatter phase) has no pairing to check
    by_axis: Dict[str, List[Dict[str, Any]]] = {}
    for e in schedule:
        if e["primitive"] == "all_to_all":
            by_axis.setdefault(
                ",".join(e["axes"]), []).append(e)
    for axis, group in by_axis.items():
        for a, b in zip(group[0::2], group[1::2]):
            if (b["split_axis"], b["concat_axis"]) != \
                    (a["concat_axis"], a["split_axis"]):
                issues.append({
                    "kind": "alltoall-pairing",
                    "axis": axis,
                    "first": [a["split_axis"], a["concat_axis"]],
                    "second": [b["split_axis"], b["concat_axis"]],
                })
    return schedule, issues


# one HLO instruction: `[ROOT] %name = type opcode(...)`
_HLO_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*\S+\s+([a-z][\w\-]*)\(")

# full capture: name, result type (possibly a tuple type), opcode
_HLO_FULL_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"
    r"((?:\([^)]*\)|\S+))\s+([a-z][\w\-]*)\(")

# computation header: `[ENTRY ]%name (params) -> type {`
_HLO_COMP_RE = re.compile(
    r"^\s*(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->.*\{\s*$")

# the computation an instruction calls into (fusion body, reduce apply)
_HLO_CALLS_RE = re.compile(r"(?:calls|to_apply)=%?([\w.\-]+)")

_DTYPE_OVERRIDE_BYTES = {"pred": 1}


def _dtype_bytes(dtype: str) -> int:
    """Bytes per element of an HLO dtype token (f32, bf16, s8, c64...).
    The trailing bit count is authoritative; f8 variants (f8e4m3fn) and
    pred are special-cased."""
    if dtype in _DTYPE_OVERRIDE_BYTES:
        return _DTYPE_OVERRIDE_BYTES[dtype]
    if dtype.startswith("f8"):
        return 1
    m = re.search(r"(\d+)", dtype)
    return max(1, int(m.group(1)) // 8) if m else 4


_SHAPE_RE = re.compile(r"([a-z]+[a-z0-9]*)\[([\d,]*)\]")


def _shape_bytes(type_str: str) -> int:
    """Total bytes of an HLO result type string — `f32[4,512]{1,0}`,
    scalar `s32[]`, or a tuple `(f32[8,4]{1,0}, f32[8]{0})` (summed)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _dtype_bytes(dtype)
    return total


def parse_hlo_module(text: str) -> Dict[str, Any]:
    """Parse an HLO dump into its computations.

    Returns ``{"entry": name_or_None, "computations": {name: [instr...]}}``
    where each instr is ``{"name", "opcode", "type", "bytes", "operands",
    "calls", "line"}`` — operands are the ``%``-referenced instruction
    names in the first argument list, ``calls`` the fused/applied
    computation name (or None). Text-level parsing on purpose: the audit
    already works from ``compiled.as_text()`` and a parser keeps the pass
    unit-testable on hand-built dumps."""
    computations: Dict[str, List[Dict[str, Any]]] = {}
    entry: Any = None
    current: Any = None
    for line in text.splitlines():
        mc = _HLO_COMP_RE.match(line)
        if mc and "=" not in line.split("(")[0]:
            current = mc.group(2)
            computations[current] = []
            if mc.group(1):
                entry = current
            continue
        if current is None:
            continue
        if line.strip() == "}":
            current = None
            continue
        mi = _HLO_FULL_INSTR_RE.match(line)
        if not mi:
            continue
        name, rtype, opcode = mi.groups()
        # operand list: balanced-paren scan from the opcode's open paren
        start = mi.end() - 1
        depth, i = 0, start
        for i in range(start, len(line)):
            if line[i] == "(":
                depth += 1
            elif line[i] == ")":
                depth -= 1
                if depth == 0:
                    break
        arglist = line[start + 1:i]
        operands = re.findall(r"%([\w.\-]+)", arglist)
        mcall = _HLO_CALLS_RE.search(line[i:])
        computations[current].append({
            "name": name, "opcode": opcode, "type": rtype,
            "bytes": _shape_bytes(rtype), "operands": operands,
            "calls": mcall.group(1) if mcall else None,
            "line": line.strip(),
        })
    return {"entry": entry, "computations": computations}


#: opcodes that are pure elementwise math — the producer/consumer halves
#: XLA's loop fusion could absorb into a neighbouring dot
_ELEMENTWISE_OPS = {
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "exponential", "exponential-minus-one", "log", "log-plus-one",
    "tanh", "negate", "abs", "power", "sqrt", "rsqrt", "cbrt", "sign",
    "floor", "ceil", "round-nearest-afz", "round-nearest-even",
    "convert", "select", "compare", "and", "or", "not", "xor",
    "clamp", "logistic", "erf", "atan2", "remainder", "sine", "cosine",
    "tan", "shift-left", "shift-right-logical", "shift-right-arithmetic",
}

_DOT_OPS = {"dot", "convolution"}

#: reduction opcodes — the LayerNorm/softmax statistic half of a
#: norm->dot chain
_NORM_OPS = {"reduce", "reduce-window"}


def _classify_instr(instr: Dict[str, Any],
                    computations: Dict[str, List[Dict[str, Any]]],
                    memo: Dict[str, str]) -> str:
    """'dot' | 'norm' | 'elementwise' | 'other' for one instruction.
    Fusions classify by their called computation's contents (a fusion
    containing a dot is a dot region)."""
    op = instr["opcode"]
    if op in _DOT_OPS:
        return "dot"
    if op == "custom-call":
        return ("dot" if re.search(r"(matmul|dot|conv)",
                                   instr["line"], re.IGNORECASE)
                else "other")
    if op in _NORM_OPS:
        return "norm"
    if op in _ELEMENTWISE_OPS:
        return "elementwise"
    if op == "fusion" and instr["calls"]:
        return _classify_computation(instr["calls"], computations, memo)
    return "other"


def _classify_computation(name: str,
                          computations: Dict[str, List[Dict[str, Any]]],
                          memo: Dict[str, str]) -> str:
    if name in memo:
        return memo[name]
    memo[name] = "other"  # cycle guard
    ops = {i["opcode"] for i in computations.get(name, ())}
    called = [i["calls"] for i in computations.get(name, ())
              if i["calls"]]
    sub = {_classify_computation(c, computations, memo) for c in called}
    if ops & _DOT_OPS or "dot" in sub:
        cls = "dot"
    elif ops & _NORM_OPS or "norm" in sub:
        cls = "norm"
    elif ops & _ELEMENTWISE_OPS or "elementwise" in sub:
        cls = "elementwise"
    else:
        cls = "other"
    memo[name] = cls
    return cls


#: producer-class -> consumer-class pairs that XLA's fusion pass could
#: have merged; each surviving edge is HBM traffic a megakernel removes
_MISS_KINDS = {
    ("elementwise", "dot"): "elementwise->dot",
    ("norm", "dot"): "norm->dot",
    ("dot", "elementwise"): "dot->elementwise",
    ("dot", "norm"): "dot->elementwise",
}


def fusion_miss_report(text: str, top_n: int = 10) -> Dict[str, Any]:
    """Segment an optimized HLO dump into fusion regions and rank the
    unfused elementwise->dot / dot->elementwise / norm->dot boundaries by
    the HBM bytes crossing them.

    Every def-use edge in the ENTRY computation between two compute
    regions is a fusion boundary: the producer's result materializes in
    HBM and is re-read by the consumer. Edges whose (producer class,
    consumer class) pair XLA's producer/consumer loop fusion could have
    merged (``_MISS_KINDS``) are misses; ``unfused_boundary_bytes`` sums
    the producer result bytes over ALL misses and ``top_fusion_misses``
    keeps the ``top_n`` heaviest — the ranked work order for hand-fused
    Pallas megakernels (ROADMAP item 1).
    """
    mod = parse_hlo_module(text)
    computations = mod["computations"]
    entry_instrs = computations.get(mod["entry"], [])
    memo: Dict[str, str] = {}
    cls_of: Dict[str, str] = {}
    instr_of: Dict[str, Dict[str, Any]] = {}
    regions = 0
    for instr in entry_instrs:
        cls = _classify_instr(instr, computations, memo)
        cls_of[instr["name"]] = cls
        instr_of[instr["name"]] = instr
        if instr["opcode"] == "fusion" or cls != "other":
            regions += 1
    misses: List[Dict[str, Any]] = []
    seen_edges = set()
    for instr in entry_instrs:
        ccls = cls_of[instr["name"]]
        for op_name in instr["operands"]:
            pcls = cls_of.get(op_name)
            if pcls is None:
                continue
            kind = _MISS_KINDS.get((pcls, ccls))
            if kind is None:
                continue
            edge = (op_name, instr["name"])
            if edge in seen_edges:
                continue
            seen_edges.add(edge)
            producer = instr_of[op_name]
            misses.append({
                "kind": kind,
                "producer": op_name,
                "producer_op": producer["opcode"],
                "consumer": instr["name"],
                "consumer_op": instr["opcode"],
                "bytes": producer["bytes"],
                "shape": producer["type"],
            })
    misses.sort(key=lambda m: (-m["bytes"], m["producer"], m["consumer"]))
    return {
        "fusion_regions": regions,
        "unfused_boundary_bytes": sum(m["bytes"] for m in misses),
        "top_fusion_misses": misses[:top_n],
    }


def parse_hlo_stats(text: str) -> Dict[str, int]:
    """Opcode census of an HLO dump (``compiled.as_text()``): total
    instruction count plus the opcodes the fusion audit cares about —
    ``fusion`` (more is better: bigger fused regions), ``copy`` (layout
    churn splitting fusions), ``custom-call``, host-transfer ops."""
    stats = {"instructions": 0, "fusions": 0, "copies": 0,
             "custom_calls": 0, "host_transfers": 0}
    for line in text.splitlines():
        m = _HLO_INSTR_RE.match(line)
        if not m:
            continue
        op = m.group(1)
        stats["instructions"] += 1
        if op == "fusion":
            stats["fusions"] += 1
        elif op == "copy":
            stats["copies"] += 1
        elif op == "custom-call":
            stats["custom_calls"] += 1
        elif op in ("copy-start", "copy-done", "send", "recv",
                    "outfeed", "infeed"):
            stats["host_transfers"] += 1
    return stats
