"""Pure analysis passes over jaxprs and HLO text.

Everything here is a function of a (closed) jaxpr or an HLO dump — no
registry, no jit, no I/O — so each pass is unit-testable against tiny
hand-built programs (tests/test_trace_audit.py) without touching the
entrypoint machinery.
"""
from __future__ import annotations

import re
from typing import Any, Dict, Iterator, List, Tuple

#: primitives whose presence inside a compiled region means a host
#: round-trip (or a host callback that blocks the device stream)
HOST_TRANSFER_PRIMITIVES = {
    "device_put", "pure_callback", "io_callback", "debug_callback",
    "callback",
}

#: structured-control-flow primitives whose closed-over consts become
#: baked-in program constants (re-materialized per executable)
CONTROL_FLOW_PRIMITIVES = {"while", "cond", "scan"}

#: a closed-over const at/above this many elements inside a control-flow
#: body is worth a finding (64KiB of f32)
LARGE_CONST_ELEMENTS = 16384


def _sub_jaxprs(value: Any) -> Iterator[Any]:
    """Yield every (open) jaxpr reachable from an eqn param value —
    ClosedJaxpr, bare Jaxpr, or lists/tuples of either (cond branches)."""
    if value is None:
        return
    if isinstance(value, (list, tuple)):
        for v in value:
            yield from _sub_jaxprs(v)
        return
    inner = getattr(value, "jaxpr", None)  # ClosedJaxpr
    if inner is not None and hasattr(inner, "eqns"):
        yield value  # yield the CLOSED jaxpr: callers may want .consts
        return
    if hasattr(value, "eqns"):  # bare Jaxpr
        yield value


def _open(j: Any):
    return getattr(j, "jaxpr", j)


def iter_eqns(jaxpr: Any) -> Iterator[Any]:
    """All equations in a (closed) jaxpr, recursing into sub-jaxprs
    carried in equation params (pjit bodies, scan/while/cond branches)."""
    for eqn in _open(jaxpr).eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_eqns(sub)


def scan_transfers(jaxpr: Any) -> List[str]:
    """Names of host-transfer/callback primitives anywhere in the
    program, one entry per occurrence."""
    return [eqn.primitive.name for eqn in iter_eqns(jaxpr)
            if eqn.primitive.name in HOST_TRANSFER_PRIMITIVES]


def _record_const(out: List[Dict[str, Any]], kind: str, const: Any,
                  threshold: int) -> None:
    size = int(getattr(const, "size", 0) or 0)
    if size >= threshold:
        out.append({
            "control_flow": kind,
            "elements": size,
            "dtype": str(getattr(const, "dtype", "?")),
            "shape": list(getattr(const, "shape", ())),
        })


def scan_large_consts(jaxpr: Any,
                      threshold: int = LARGE_CONST_ELEMENTS
                      ) -> List[Dict[str, Any]]:
    """Closed-over constants of ``while``/``cond``/``scan`` bodies with
    ``size >= threshold`` elements. Large captured constants are baked
    into every executable that traces the loop — they should be loop
    carries or explicit arguments instead.

    Tracing hoists body-captured arrays to the TOP-LEVEL jaxpr's consts
    and threads them into the control-flow equation as plain operands, so
    the check is "a top-level constvar feeds a while/cond/scan directly";
    older-style consts embedded in the branch ClosedJaxprs are covered
    too. Consts reaching a loop through intermediate equations are not
    attributed (one-hop only — precise enough for the audit, cheap enough
    for every entrypoint)."""
    out: List[Dict[str, Any]] = []
    closed_const_of = {}  # id(Var) keys: Literal operands may be unhashable
    open_j = _open(jaxpr)
    for var, val in zip(getattr(open_j, "constvars", ()),
                        getattr(jaxpr, "consts", ())):
        closed_const_of[id(var)] = val
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name not in CONTROL_FLOW_PRIMITIVES:
            continue
        for invar in eqn.invars:
            if id(invar) in closed_const_of:
                _record_const(out, eqn.primitive.name,
                              closed_const_of[id(invar)], threshold)
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                for const in getattr(sub, "consts", ()):
                    _record_const(out, eqn.primitive.name, const, threshold)
    return out


def donation_opportunities(jaxpr: Any) -> Dict[str, Any]:
    """How many inputs could be donated: inputs whose (shape, dtype)
    matches an output's. A train step that updates parameters in place
    but donates nothing pays double-buffering for the whole parameter
    set; the matched byte count quantifies the waste."""
    closed = jaxpr
    open_j = _open(closed)
    key = lambda v: (tuple(getattr(v.aval, "shape", ())),
                     str(getattr(v.aval, "dtype", "?")))
    outs: Dict[Tuple, int] = {}
    for v in open_j.outvars:
        k = key(v)
        outs[k] = outs.get(k, 0) + 1
    matched, matched_bytes = 0, 0
    for v in open_j.invars:
        k = key(v)
        if outs.get(k, 0) > 0:
            outs[k] -= 1
            matched += 1
            aval = v.aval
            nbytes = 1
            for d in getattr(aval, "shape", ()):
                nbytes *= int(d)
            itemsize = getattr(getattr(aval, "dtype", None), "itemsize", 4)
            matched_bytes += nbytes * itemsize
    return {"donatable_inputs": matched, "donatable_bytes": matched_bytes,
            "total_inputs": len(open_j.invars)}


# one HLO instruction: `[ROOT] %name = type opcode(...)`
_HLO_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*\S+\s+([a-z][\w\-]*)\(")


def parse_hlo_stats(text: str) -> Dict[str, int]:
    """Opcode census of an HLO dump (``compiled.as_text()``): total
    instruction count plus the opcodes the fusion audit cares about —
    ``fusion`` (more is better: bigger fused regions), ``copy`` (layout
    churn splitting fusions), ``custom-call``, host-transfer ops."""
    stats = {"instructions": 0, "fusions": 0, "copies": 0,
             "custom_calls": 0, "host_transfers": 0}
    for line in text.splitlines():
        m = _HLO_INSTR_RE.match(line)
        if not m:
            continue
        op = m.group(1)
        stats["instructions"] += 1
        if op == "fusion":
            stats["fusions"] += 1
        elif op == "copy":
            stats["copies"] += 1
        elif op == "custom-call":
            stats["custom_calls"] += 1
        elif op in ("copy-start", "copy-done", "send", "recv",
                    "outfeed", "infeed"):
            stats["host_transfers"] += 1
    return stats
