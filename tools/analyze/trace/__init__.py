"""Trace-level audit runner: the dynamic half of ``tools.analyze``.

The AST tier reads source text; this tier runs the *programs*. It imports
the repo's registered auditable entrypoints (``paddle_tpu.core.audit`` —
hapi train step, static Executor step, serving predict, LLM
prefill/decode), captures each one's jaxpr and lowered HLO under
``JAX_PLATFORMS=cpu``, and records per-entrypoint stats that the trace
rules (PTA009 fusion/transfer audit, PTA010 retrace sentinel) turn into
findings anchored at the registration site.

The audit compiles real code, so it only runs when a trace rule is
selected explicitly (``--only PTA009,PTA010``) and its result is memoized
per process — both rules read one report. ``PTA_TRACE_ENTRYPOINTS``
(comma-separated names) restricts which entrypoints run, for CI shards
and focused debugging.
"""
from __future__ import annotations

import hashlib
import os
import sys
import traceback
import warnings
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import passes


@dataclass
class EntrypointStats:
    """Everything the audit learned about one entrypoint."""
    name: str
    tags: Tuple[str, ...] = ()
    path: str = ""   # registration site (repo-relative)
    line: int = 0
    error: str = ""  # build/trace failure — other fields are then partial
    trace_count: int = -1           # jit traces across the two variants
    fingerprints: List[str] = field(default_factory=list)
    fingerprint_stable: bool = True
    transfers: List[str] = field(default_factory=list)
    large_consts: List[Dict[str, Any]] = field(default_factory=list)
    donation: Optional[Dict[str, Any]] = None  # set when check applies
    hlo: Dict[str, int] = field(default_factory=dict)
    # collective-schedule audit (PTA012): ordered per-rank schedule,
    # total wire bytes per step, and any invariant violations
    collectives: List[Dict[str, Any]] = field(default_factory=list)
    collective_bytes: int = 0
    collective_issues: List[Dict[str, Any]] = field(default_factory=list)

    def payload(self) -> Dict[str, Any]:
        return {
            "tags": list(self.tags), "path": self.path, "line": self.line,
            "error": self.error, "trace_count": self.trace_count,
            "fingerprints": self.fingerprints,
            "fingerprint_stable": self.fingerprint_stable,
            "transfers": self.transfers,
            "large_consts": self.large_consts,
            "donation": self.donation, "hlo": self.hlo,
            "collectives": self.collectives,
            "collective_bytes": self.collective_bytes,
            "collective_issues": self.collective_issues,
        }


@dataclass
class TraceReport:
    platform: str
    entrypoint_stats: Dict[str, EntrypointStats]
    error: str = ""  # registry-level failure (jax/paddle_tpu unimportable)

    def stats_payload(self) -> Dict[str, Any]:
        return {
            "version": 1,
            "platform": self.platform,
            "error": self.error,
            "entrypoints": {n: s.payload()
                            for n, s in sorted(
                                self.entrypoint_stats.items())},
        }


_LAST: Optional[TraceReport] = None


def last_report() -> Optional[TraceReport]:
    return _LAST


def get_report() -> TraceReport:
    """Run the audit once per process; PTA009 and PTA010 share it."""
    global _LAST
    if _LAST is None:
        _LAST = run_audit()
    return _LAST


def _reset_for_tests() -> None:
    global _LAST
    _LAST = None


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))


def run_audit(names: Optional[List[str]] = None) -> TraceReport:
    """Build + trace every registered entrypoint. Never raises: failures
    are recorded per-entrypoint (or report-level for import failures) so
    one broken entrypoint doesn't hide the rest."""
    # must win the race with the first jax import: tracing on an
    # accelerator would make the audit a TPU job
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    root = _repo_root()
    if root not in sys.path:
        sys.path.insert(0, root)
    try:
        import jax
        try:
            # some images install accelerator plugins that override the
            # env var; the config knob wins if no backend is live yet
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass  # backend already initialized — platform field records it
        from paddle_tpu.core import audit as _audit
        eps = _audit.load_default_entrypoints()
        platform = jax.default_backend()
    except Exception:
        return TraceReport(platform="unavailable", entrypoint_stats={},
                           error=traceback.format_exc(limit=3))

    if names is None:
        env = os.environ.get("PTA_TRACE_ENTRYPOINTS", "")
        names = [n.strip() for n in env.split(",") if n.strip()] or None
    stats: Dict[str, EntrypointStats] = {}
    for name, ep in sorted(eps.items()):
        if names is not None and name not in names:
            continue
        stats[name] = audit_entrypoint(name, ep)
    return TraceReport(platform=platform, entrypoint_stats=stats)


def audit_spec(name: str, spec, tags: Tuple[str, ...] = (),
               path: str = "", line: int = 0) -> EntrypointStats:
    """Audit one already-built AuditSpec (the test seam: fixtures hand in
    synthetic specs without touching the registry)."""
    import jax

    st = EntrypointStats(name=name, tags=tuple(tags), path=path, line=line)
    try:
        # -- static program analysis (jaxpr level) -------------------------
        mj_kwargs = {}
        if "static_argnums" in spec.jit_kwargs:
            mj_kwargs["static_argnums"] = spec.jit_kwargs["static_argnums"]
        closed = jax.make_jaxpr(spec.fn, **mj_kwargs)(*spec.make_args(0))
        st.transfers = passes.scan_transfers(closed)
        st.large_consts = passes.scan_large_consts(closed)
        st.collectives, st.collective_issues = \
            passes.collective_schedule(closed)
        st.collective_bytes = sum(e["bytes"] for e in st.collectives)
        if "train" in st.tags and "donate_argnums" not in spec.jit_kwargs:
            st.donation = passes.donation_opportunities(closed)

        # -- retrace sentinel (PTA010) --------------------------------------
        counter = {"n": 0}

        def _counting(*a):
            counter["n"] += 1
            return spec.fn(*a)

        jitted = jax.jit(_counting, **spec.jit_kwargs)
        with warnings.catch_warnings():
            # CPU ignores donate_argnums with a warning; irrelevant here
            warnings.simplefilter("ignore")
            jitted(*spec.make_args(0))
            jitted(*spec.make_args(1))
            # record BEFORE the lowers below: .lower() traces again
            st.trace_count = counter["n"]

            # executable fingerprint per variant — same program must lower
            # to byte-identical StableHLO when only array values change
            # (.lower() re-traces on every call regardless of the cache)
            fresh = jax.jit(spec.fn, **spec.jit_kwargs)
            for variant in (0, 1):
                text = fresh.lower(*spec.make_args(variant)).as_text()
                st.fingerprints.append(
                    hashlib.sha1(text.encode()).hexdigest()[:16])
            st.fingerprint_stable = (st.fingerprints[0]
                                     == st.fingerprints[1])

            # -- post-XLA census (fusion/copy stats) ------------------------
            compiled = fresh.lower(*spec.make_args(0)).compile()
            st.hlo = passes.parse_hlo_stats(compiled.as_text())
    except Exception:
        st.error = traceback.format_exc(limit=3)
    return st


def audit_entrypoint(name: str, ep) -> EntrypointStats:
    try:
        spec = ep.build()
    except Exception:
        st = EntrypointStats(name=name, tags=tuple(ep.tags), path=ep.path,
                             line=ep.line)
        st.error = traceback.format_exc(limit=3)
        return st
    return audit_spec(name, spec, tags=ep.tags, path=ep.path, line=ep.line)
