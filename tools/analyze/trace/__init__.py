"""Trace-level audit runner: the dynamic half of ``tools.analyze``.

The AST tier reads source text; this tier runs the *programs*. It imports
the repo's registered auditable entrypoints (``paddle_tpu.core.audit`` —
hapi train step, static Executor step, serving predict, LLM
prefill/decode), captures each one's jaxpr and lowered HLO under
``JAX_PLATFORMS=cpu``, and records per-entrypoint stats that the trace
rules (PTA009 fusion/transfer audit, PTA010 retrace sentinel) turn into
findings anchored at the registration site.

The audit compiles real code, so it only runs when a trace rule is
selected explicitly (``--only PTA009,PTA010``) and its result is memoized
per process — both rules read one report. ``PTA_TRACE_ENTRYPOINTS``
(comma-separated names) restricts which entrypoints run, for CI shards
and focused debugging.
"""
from __future__ import annotations

import hashlib
import os
import sys
import traceback
import warnings
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import passes


@dataclass
class EntrypointStats:
    """Everything the audit learned about one entrypoint."""
    name: str
    tags: Tuple[str, ...] = ()
    path: str = ""   # registration site (repo-relative)
    line: int = 0
    error: str = ""  # build/trace failure — other fields are then partial
    trace_count: int = -1           # jit traces across the two variants
    fingerprints: List[str] = field(default_factory=list)
    fingerprint_stable: bool = True
    transfers: List[str] = field(default_factory=list)
    large_consts: List[Dict[str, Any]] = field(default_factory=list)
    donation: Optional[Dict[str, Any]] = None  # set when check applies
    hlo: Dict[str, int] = field(default_factory=dict)
    # collective-schedule audit (PTA012): ordered per-rank schedule,
    # total wire bytes per step, and any invariant violations
    collectives: List[Dict[str, Any]] = field(default_factory=list)
    collective_bytes: int = 0
    collective_issues: List[Dict[str, Any]] = field(default_factory=list)
    # fusion-miss audit (PTA014): region count, HBM bytes crossing
    # unfused elementwise/dot/norm boundaries, ranked worst offenders
    fusion_regions: int = 0
    unfused_boundary_bytes: int = 0
    top_fusion_misses: List[Dict[str, Any]] = field(default_factory=list)

    def payload(self) -> Dict[str, Any]:
        return {
            "tags": list(self.tags), "path": self.path, "line": self.line,
            "error": self.error, "trace_count": self.trace_count,
            "fingerprints": self.fingerprints,
            "fingerprint_stable": self.fingerprint_stable,
            "transfers": self.transfers,
            "large_consts": self.large_consts,
            "donation": self.donation, "hlo": self.hlo,
            "collectives": self.collectives,
            "collective_bytes": self.collective_bytes,
            "collective_issues": self.collective_issues,
            "fusion_regions": self.fusion_regions,
            "unfused_boundary_bytes": self.unfused_boundary_bytes,
            "top_fusion_misses": self.top_fusion_misses,
        }


@dataclass
class TraceReport:
    platform: str
    entrypoint_stats: Dict[str, EntrypointStats]
    error: str = ""  # registry-level failure (jax/paddle_tpu unimportable)

    def stats_payload(self) -> Dict[str, Any]:
        return {
            "version": 1,
            "platform": self.platform,
            "error": self.error,
            "entrypoints": {n: s.payload()
                            for n, s in sorted(
                                self.entrypoint_stats.items())},
        }


_LAST: Optional[TraceReport] = None

#: entrypoint scope installed by the driver (--changed-only): None = all,
#: [] = none. Wins over PTA_TRACE_ENTRYPOINTS; an explicit run_audit
#: names argument wins over both.
_SCOPE: Optional[List[str]] = None


def set_audit_scope(names: Optional[List[str]]) -> None:
    """Restrict which entrypoints the memoized audit runs (the
    --changed-only seam). Invalidates any memoized report so the scope
    takes effect even after a prior full run."""
    global _SCOPE, _LAST
    _SCOPE = names
    _LAST = None


def last_report() -> Optional[TraceReport]:
    return _LAST


def get_report() -> TraceReport:
    """Run the audit once per process; PTA009 and PTA010 share it."""
    global _LAST
    if _LAST is None:
        _LAST = run_audit()
    return _LAST


def _reset_for_tests() -> None:
    global _LAST
    _LAST = None


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))


def run_audit(names: Optional[List[str]] = None) -> TraceReport:
    """Build + trace every registered entrypoint. Never raises: failures
    are recorded per-entrypoint (or report-level for import failures) so
    one broken entrypoint doesn't hide the rest."""
    # must win the race with the first jax import: tracing on an
    # accelerator would make the audit a TPU job
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    root = _repo_root()
    if root not in sys.path:
        sys.path.insert(0, root)
    try:
        import jax
        try:
            # some images install accelerator plugins that override the
            # env var; the config knob wins if no backend is live yet
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass  # backend already initialized — platform field records it
        from paddle_tpu.core import audit as _audit
        eps = _audit.load_default_entrypoints()
        platform = jax.default_backend()
    except Exception:
        return TraceReport(platform="unavailable", entrypoint_stats={},
                           error=traceback.format_exc(limit=3))

    if names is None:
        names = _SCOPE
    if names is None:
        env = os.environ.get("PTA_TRACE_ENTRYPOINTS", "")
        names = [n.strip() for n in env.split(",") if n.strip()] or None
    stats: Dict[str, EntrypointStats] = {}
    for name, ep in sorted(eps.items()):
        if names is not None and name not in names:
            continue
        stats[name] = audit_entrypoint(name, ep)
    return TraceReport(platform=platform, entrypoint_stats=stats)


def audit_spec(name: str, spec, tags: Tuple[str, ...] = (),
               path: str = "", line: int = 0) -> EntrypointStats:
    """Audit one already-built AuditSpec (the test seam: fixtures hand in
    synthetic specs without touching the registry)."""
    import jax

    st = EntrypointStats(name=name, tags=tuple(tags), path=path, line=line)
    try:
        # -- static program analysis (jaxpr level) -------------------------
        mj_kwargs = {}
        if "static_argnums" in spec.jit_kwargs:
            mj_kwargs["static_argnums"] = spec.jit_kwargs["static_argnums"]
        closed = jax.make_jaxpr(spec.fn, **mj_kwargs)(*spec.make_args(0))
        st.transfers = passes.scan_transfers(closed)
        st.large_consts = passes.scan_large_consts(closed)
        st.collectives, st.collective_issues = \
            passes.collective_schedule(closed)
        st.collective_bytes = sum(e["bytes"] for e in st.collectives)
        if "train" in st.tags and "donate_argnums" not in spec.jit_kwargs:
            st.donation = passes.donation_opportunities(closed)

        # -- retrace sentinel (PTA010) --------------------------------------
        counter = {"n": 0}

        def _counting(*a):
            counter["n"] += 1
            return spec.fn(*a)

        jitted = jax.jit(_counting, **spec.jit_kwargs)
        with warnings.catch_warnings():
            # CPU ignores donate_argnums with a warning; irrelevant here
            warnings.simplefilter("ignore")
            jitted(*spec.make_args(0))
            jitted(*spec.make_args(1))
            # record BEFORE the lowers below: .lower() traces again
            st.trace_count = counter["n"]

            # executable fingerprint per variant — same program must lower
            # to byte-identical StableHLO when only array values change
            # (.lower() re-traces on every call regardless of the cache)
            fresh = jax.jit(spec.fn, **spec.jit_kwargs)
            for variant in (0, 1):
                text = fresh.lower(*spec.make_args(variant)).as_text()
                st.fingerprints.append(
                    hashlib.sha1(text.encode()).hexdigest()[:16])
            st.fingerprint_stable = (st.fingerprints[0]
                                     == st.fingerprints[1])

            # -- post-XLA census (fusion/copy stats + fusion misses) --------
            compiled = fresh.lower(*spec.make_args(0)).compile()
            hlo_text = compiled.as_text()
            st.hlo = passes.parse_hlo_stats(hlo_text)
            fus = passes.fusion_miss_report(hlo_text)
            st.fusion_regions = fus["fusion_regions"]
            st.unfused_boundary_bytes = fus["unfused_boundary_bytes"]
            st.top_fusion_misses = fus["top_fusion_misses"]
    except Exception:
        st.error = traceback.format_exc(limit=3)
    return st


def _resolve_module(root: str, dotted: str) -> Optional[str]:
    """Root-relative path of a dotted module under ``root``, or None."""
    base = dotted.replace(".", "/")
    for cand in (base + ".py", base + "/__init__.py"):
        if os.path.isfile(os.path.join(root, cand)):
            return cand
    return None


def _resolve_reexport(root: str, init_relpath: str, name: str,
                      depth: int = 0) -> List[str]:
    """Resolve a name re-exported by a package ``__init__.py`` to the
    submodule(s) that define it, chasing chained re-exports a few hops.
    Keeps --changed-only scoping precise without traversing the whole
    hub: ``from paddle_tpu.nn import Linear`` maps to nn/layers.py, not
    to everything nn's __init__ imports."""
    import ast

    if depth > 4:
        return []
    try:
        with open(os.path.join(root, init_relpath), "rb") as f:
            tree = ast.parse(f.read().decode("utf-8", errors="replace"))
    except (OSError, SyntaxError):
        return []
    pkg_parts = init_relpath.replace(os.sep, "/").split("/")[:-1]
    for node in ast.walk(tree):
        if not isinstance(node, ast.ImportFrom):
            continue
        mod = _absolute_module(pkg_parts, node)
        if not mod:
            continue
        for alias in node.names:
            if (alias.asname or alias.name) != name or alias.name == "*":
                continue
            p = _resolve_module(root, f"{mod}.{alias.name}")
            if p:
                return [p]
            p = _resolve_module(root, mod)
            if p and p.endswith("__init__.py"):
                return [p] + _resolve_reexport(root, p, alias.name,
                                               depth + 1)
            if p:
                return [p]
    return []


def _absolute_module(pkg_parts: List[str], node) -> str:
    """Absolute dotted module of an ImportFrom, resolving relative
    levels against the importing file's package."""
    if node.level:
        # `from ..ops import x` in pkg/a/b.py: level 1 anchors at pkg/a,
        # each extra level walks one package up
        base_parts = pkg_parts[:len(pkg_parts) - (node.level - 1)]
        prefix = ".".join(base_parts)
        return f"{prefix}.{node.module}" if node.module else prefix
    return node.module or ""


def _file_imports(root: str, relpath: str) -> List[str]:
    """Root-relative paths this file statically imports (module- and
    function-level), restricted to modules that live under ``root``.
    Names pulled from package ``__init__.py`` hubs resolve through
    :func:`_resolve_reexport` to their defining submodules."""
    import ast

    try:
        with open(os.path.join(root, relpath), "rb") as f:
            tree = ast.parse(f.read().decode("utf-8", errors="replace"))
    except (OSError, SyntaxError):
        return []
    pkg_parts = relpath.replace(os.sep, "/").split("/")[:-1]
    out: List[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                p = _resolve_module(root, alias.name)
                if p:
                    out.append(p)
        elif isinstance(node, ast.ImportFrom):
            mod = _absolute_module(pkg_parts, node)
            for alias in node.names:
                p = _resolve_module(root, f"{mod}.{alias.name}") \
                    if mod else None
                if p:
                    out.append(p)
                    continue
                p = _resolve_module(root, mod) if mod else None
                if not p:
                    continue
                out.append(p)
                if p.endswith("__init__.py") and alias.name != "*":
                    out.extend(_resolve_reexport(root, p, alias.name))
    return [p for p in out if p]


#: files that belong to every closure but whose own imports are NOT
#: followed: the audit registry's load_default_entrypoints() imports all
#: registration modules, so traversing through it would make every
#: entrypoint's closure total and defeat the --changed-only scoping
_CLOSURE_BARRIERS = ("paddle_tpu/core/audit.py",)


def _is_barrier(relpath: str) -> bool:
    """Files whose imports are not traversed: the audit registry and
    package ``__init__.py`` hubs. Hubs stay closure *members* (editing
    one re-traces its importers) but names pulled through them resolve
    per-name via :func:`_resolve_reexport` instead of dragging in every
    submodule the hub touches."""
    return (relpath in _CLOSURE_BARRIERS
            or relpath.endswith("__init__.py"))


def _import_closure(root: str, relpath: str,
                    cache: Dict[str, set]) -> set:
    """Transitive static import closure of one file (memoized BFS)."""
    if relpath in cache:
        return cache[relpath]
    closure = {relpath}
    cache[relpath] = closure  # placed before BFS: cycles terminate
    frontier = [relpath]
    while frontier:
        cur = frontier.pop()
        if _is_barrier(cur) and cur != relpath:
            continue
        for dep in _file_imports(root, cur):
            if dep not in closure:
                closure.add(dep)
                frontier.append(dep)
    return closure


def scope_entrypoints(root: str, changed_relpaths) -> List[str]:
    """Registered entrypoint names whose static import closure touches
    any changed file — the --changed-only trace scope. An entrypoint's
    closure starts at its registration file (``ep.path``); an empty
    result means no entrypoint is affected and the trace tier can skip
    compiling entirely."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if root not in sys.path:
        sys.path.insert(0, root)
    from paddle_tpu.core import audit as _audit
    eps = _audit.load_default_entrypoints()
    changed = {p.replace(os.sep, "/") for p in changed_relpaths}
    cache: Dict[str, set] = {}
    out = []
    for name, ep in sorted(eps.items()):
        if ep.path and _import_closure(root, ep.path, cache) & changed:
            out.append(name)
    return out


def audit_entrypoint(name: str, ep) -> EntrypointStats:
    try:
        spec = ep.build()
    except Exception:
        st = EntrypointStats(name=name, tags=tuple(ep.tags), path=ep.path,
                             line=ep.line)
        st.error = traceback.format_exc(limit=3)
        return st
    return audit_spec(name, spec, tags=ep.tags, path=ep.path, line=ep.line)
