"""paddle-tpu-analyze: rule-based static analysis for the jit-era codebase.

The reference enforces correctness natively at the C++ layer
(PADDLE_ENFORCE / platform/errors.h); a pure-Python JAX port has no such
guardrail, so tracer leaks, hidden host syncs and API-surface drift only
surface at runtime.  This package is the static gate: a small `ast`-based
framework (stdlib only — it must run before anything heavy imports) with

- per-rule enable/disable (``--rule`` / ``--skip``),
- inline ``# noqa: PTA###`` suppressions,
- a checked-in baseline (tools/analyze/baseline.json) so pre-existing
  findings don't block CI while newly introduced ones do,
- ``--json`` machine output and check_bench_regression-style exit codes
  (0 clean, 1 new findings, 2 internal error).

Rules (see docs/static_analysis.md):

========  ==============================================================
PTA001    tracer-safety: host-forcing ops inside jit-reachable functions
PTA002    host sync in hot-path directories (ops/, optimizer/, amp/, ...)
PTA003    silent except in resilience-critical paths
PTA004    op registry <-> tools/op_catalog.txt consistency
PTA005    API hygiene: mutable default args, missing future annotations
========  ==============================================================

Run: ``python -m tools.analyze [--json] [--baseline FILE] [--rule NAME]
[paths...]``
"""
from .core import (  # noqa: F401
    Finding, Project, SourceFile,
    load_baseline, split_findings, baseline_payload, write_baseline,
    run_rules, filter_noqa,
)

__version__ = "1.0"
