"""Attribute-aware call graph over the analyzed files.

Three rule families need to know "what can call what":

- PTA001 needs the functions that can execute *under a JAX trace*
  (anything reachable from ``jax.jit`` / ``pjit`` / ``to_static``);
- PTA006 needs the methods that can execute *on a non-main thread*
  (``threading.Thread(target=...)``, ``Thread``/``Process`` subclasses'
  ``run``, ``executor.submit(fn)``, and signal callbacks);
- PTA007 needs the functions that can execute *in signal-handler
  context* (installed via ``signal.signal`` or ``ChainedSignalHandler``).

Full python call resolution is undecidable; this graph resolves what is
statically evident and degrades deliberately for the rest:

edges (attribute-aware)
    - ``f()`` → the local/nested def, else the imported symbol (aliased
      imports and relative ``from ..pkg import mod`` are followed through
      the project's module map), else every def named ``f``;
    - ``self.m()`` / ``cls.m()`` → the method in the enclosing class (MRO
      walked through project-local bases), falling back to every method
      named ``m`` only when the class doesn't define it;
    - ``mod.f()`` → the def in the resolved module file; calls into
      *external* modules (``np.concatenate``) produce no edge;
    - ``obj.m()`` → methods of ``obj``'s inferred class(es). Types come
      from local assignments (``x = Class()``), parameter/variable
      annotations (``Optional``/``Union`` unwrapped), return annotations
      of resolved callees, and per-class ``self.attr`` assignment scans;
    - ``Class().m()`` → ``Class.m``; bare ``Class()`` → ``Class.__init__``.

Unresolvable dynamic dispatch stays *conservative* in two different
directions, matching each client's failure cost: the jit walk
(``reachable_from``) falls back to every same-named method so a tracer
leak is never missed, while the thread/signal walks
(``thread_reachable_from`` / ``signal_reachable_from``) drop the edge so
a concurrency finding is never hallucinated through a name collision.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .core import Project, SourceFile, dotted_name

#: decorator names (last dotted component) that enter a trace
JIT_DECORATORS = {"jit", "pjit", "to_static"}

#: callables whose function-valued arguments are traced
TRACE_WRAPPERS = {
    "jit", "pjit", "vjp", "jvp", "grad", "value_and_grad", "pmap",
    "checkpoint", "remat", "scan", "while_loop", "fori_loop", "cond",
    "switch", "custom_vjp", "custom_jvp", "eval_shape", "make_jaxpr",
    "shard_map", "xmap", "pallas_call", "associated_scan", "vmap",
}

#: constructors whose ``target=`` argument runs on its own thread/process
THREAD_CTORS = {"Thread", "Process"}

#: jax.lax collective vocabulary: the last dotted component of a call
#: that IS a cross-rank collective wherever it appears (no op in the
#: repo shares these names, so bare-name matching is safe)
LAX_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle",
    "all_gather", "all_to_all", "psum_scatter", "pbroadcast",
}

#: public wrappers in distributed/collective.py whose *call* is a
#: collective even though the lax primitive hides behind the dynamic
#: ``_run(op, tensor, raw_fn)`` dispatch the precise edge walk cannot
#: follow. Seeded by file+name, propagated to callers by the collective
#: walk.
COLLECTIVE_WRAPPER_FILE = "distributed/collective.py"
COLLECTIVE_WRAPPER_NAMES = {
    "all_reduce", "all_gather", "broadcast", "reduce", "scatter",
    "reduce_scatter", "alltoall", "alltoall_single", "send", "recv",
    "isend", "irecv", "p2p_exchange", "barrier", "wait",
    "compressed_allreduce", "compressed_grad_sync",
}

#: wrapper names distinctive enough to match without resolution even
#: through an external attribute base (``dist.all_reduce`` where ``dist``
#: is outside the analyzed paths). Short generic names (send, reduce,
#: wait...) stay out: they collide with tensor ops and futures.
COLLECTIVE_UNAMBIGUOUS_NAMES = {
    "all_reduce", "alltoall", "alltoall_single", "reduce_scatter",
    "p2p_exchange", "compressed_allreduce", "compressed_grad_sync",
}

#: Optional/Union wrappers unwrapped during annotation inference
_UNION_WRAPPERS = {"Optional", "Union"}


class FuncInfo:
    __slots__ = ("file", "node", "name", "qualname", "is_method", "cls",
                 "root_via", "reachable_from",
                 "thread_root_via", "thread_reachable_from",
                 "signal_root_via", "signal_reachable_from",
                 "collective_via")

    def __init__(self, file: SourceFile, node, qualname: str,
                 is_method: bool, cls: Optional["ClassInfo"] = None):
        self.file = file
        self.node = node
        self.name = getattr(node, "name", "<lambda>")
        self.qualname = qualname
        self.is_method = is_method
        self.cls = cls
        self.root_via: Optional[str] = None        # why it is a jit root
        self.reachable_from: Optional[str] = None  # jit provenance
        self.thread_root_via: Optional[str] = None
        self.thread_reachable_from: Optional[str] = None
        self.signal_root_via: Optional[str] = None
        self.signal_reachable_from: Optional[str] = None
        #: why this function issues a collective (directly or through a
        #: precise-edge callee chain); None = provably collective-free
        #: as far as the precise walk can see
        self.collective_via: Optional[str] = None


class ClassInfo:
    __slots__ = ("file", "node", "name", "qualname", "bases", "methods",
                 "_attr_types")

    def __init__(self, file: SourceFile, node: ast.ClassDef, qualname: str):
        self.file = file
        self.node = node
        self.name = node.name
        self.qualname = qualname
        self.bases = [dotted_name(b) for b in node.bases]
        self.methods: Dict[str, FuncInfo] = {}
        self._attr_types: Optional[Dict[str, List["ClassInfo"]]] = None


def _module_name(relpath: str) -> Optional[str]:
    if not relpath.endswith(".py"):
        return None
    p = relpath[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


def _package_of(relpath: str) -> str:
    mod = _module_name(relpath) or ""
    if relpath.endswith("__init__.py"):
        return mod
    return mod.rpartition(".")[0]


class CallGraph:
    def __init__(self, project: Project):
        self.project = project
        self.functions: List[FuncInfo] = []
        self.by_name: Dict[str, List[FuncInfo]] = {}
        self.methods_by_name: Dict[str, List[FuncInfo]] = {}
        self.per_file_by_name: Dict[str, Dict[str, List[FuncInfo]]] = {}
        self.classes: List[ClassInfo] = []
        self.classes_by_name: Dict[str, List[ClassInfo]] = {}
        self.modules: Dict[str, str] = {}        # module name -> relpath
        self.file_imports: Dict[str, Dict[str, tuple]] = {}
        self.roots: List[FuncInfo] = []          # jit roots
        self.thread_roots: List[FuncInfo] = []
        self.signal_roots: List[FuncInfo] = []
        self._env_cache: Dict[int, Dict[str, List[ClassInfo]]] = {}
        self._edge_cache: Dict[Tuple[int, bool], List[FuncInfo]] = {}
        #: id(FuncInfo) -> (mesh axis-name tuple or None, wrap-site str)
        #: for functions handed to shard_map; axes are None when the mesh
        #: expression could not be resolved to a literal declaration
        self.shard_map_axes: Dict[int, Tuple[Optional[tuple], str]] = {}
        #: axis names declared anywhere in the project: Mesh(...) /
        #: make_mesh/build_mesh axis tuples or dict keys, PartitionSpec
        #: literals, and string defaults of axis/axis_name parameters
        self.declared_axes: set = set()

    # -- reachability views ---------------------------------------------------
    def reachable(self) -> List[FuncInfo]:
        """jit-reachable (PTA001)."""
        return [f for f in self.functions if f.reachable_from is not None]

    def thread_reachable(self) -> List[FuncInfo]:
        return [f for f in self.functions
                if f.thread_reachable_from is not None]

    def signal_reachable(self) -> List[FuncInfo]:
        return [f for f in self.functions
                if f.signal_reachable_from is not None]

    # -- symbol resolution ----------------------------------------------------
    def _toplevel_symbol(self, relpath: str, name: str):
        for fi in self.per_file_by_name.get(relpath, {}).get(name, []):
            if fi.qualname == name:
                return fi
        for ci in self.classes_by_name.get(name, []):
            if ci.file.relpath == relpath and ci.qualname == name:
                return ci
        return None

    def resolve_symbol(self, sf: SourceFile, name: str, _depth: int = 0):
        """``name`` in ``sf``'s namespace → FuncInfo | ClassInfo |
        ("module", relpath) | ("extmodule", dotted) | None."""
        if _depth > 4:
            return None
        sym = self._toplevel_symbol(sf.relpath, name)
        if sym is not None:
            return sym
        ent = self.file_imports.get(sf.relpath, {}).get(name)
        if ent is None:
            return None
        if ent[0] == "module":
            rel = self.modules.get(ent[1])
            return ("module", rel) if rel else ("extmodule", ent[1])
        _, base, orig = ent
        rel = self.modules.get(f"{base}.{orig}" if base else orig)
        if rel is not None:
            return ("module", rel)
        rel = self.modules.get(base)
        if rel is not None:
            target = self.project.by_relpath.get(rel)
            if target is not None:
                return self.resolve_symbol(target, orig, _depth + 1)
            return None
        return ("extmodule", f"{base}.{orig}" if base else orig)

    def resolve_dotted(self, sf: SourceFile, dotted: str):
        """Resolve ``a.b.c`` starting from ``sf``'s namespace."""
        parts = dotted.split(".")
        cur = self.resolve_symbol(sf, parts[0])
        for p in parts[1:]:
            if isinstance(cur, tuple) and cur[0] == "module":
                target = self.project.by_relpath.get(cur[1])
                if target is None:
                    return None
                sub = self.modules.get((_module_name(cur[1]) or "") + "." + p)
                nxt = self.resolve_symbol(target, p)
                cur = nxt if nxt is not None else (
                    ("module", sub) if sub else None)
            elif isinstance(cur, tuple) and cur[0] == "extmodule":
                cur = ("extmodule", cur[1] + "." + p)
            else:
                return None
        return cur

    # -- type inference -------------------------------------------------------
    def annotation_classes(self, sf: SourceFile, ann,
                           _depth: int = 0) -> List[ClassInfo]:
        if ann is None or _depth > 3:
            return []
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return []
        if isinstance(ann, ast.Subscript):
            if dotted_name(ann.value).rpartition(".")[2] in _UNION_WRAPPERS:
                sl = ann.slice
                elts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
                out: List[ClassInfo] = []
                for e in elts:
                    out.extend(self.annotation_classes(sf, e, _depth + 1))
                return out
            return []
        if isinstance(ann, (ast.Name, ast.Attribute)):
            d = dotted_name(ann)
            sym = (self.resolve_dotted(sf, d) if "." in d
                   else self.resolve_symbol(sf, d))
            if isinstance(sym, ClassInfo):
                return [sym]
            if sym is None:
                # unique-name fallback: one project class with this name
                cands = self.classes_by_name.get(d.rpartition(".")[2], [])
                if len(cands) == 1:
                    return list(cands)
        return []

    def expr_classes(self, sf: SourceFile, expr,
                     fi: Optional[FuncInfo] = None,
                     _depth: int = 0) -> List[ClassInfo]:
        """Classes an expression's *value* may be an instance of."""
        if _depth > 3:
            return []
        if isinstance(expr, ast.BoolOp):
            out: List[ClassInfo] = []
            for v in expr.values:
                out.extend(self.expr_classes(sf, v, fi, _depth + 1))
            return out
        if isinstance(expr, ast.IfExp):
            return (self.expr_classes(sf, expr.body, fi, _depth + 1)
                    + self.expr_classes(sf, expr.orelse, fi, _depth + 1))
        if isinstance(expr, ast.Call):
            f = expr.func
            d = dotted_name(f)
            sym = None
            if isinstance(f, ast.Name):
                sym = self.resolve_symbol(sf, f.id)
            elif isinstance(f, ast.Attribute) and d:
                sym = self.resolve_dotted(sf, d)
            if isinstance(sym, ClassInfo):
                return [sym]
            if isinstance(sym, FuncInfo):
                ret = getattr(sym.node, "returns", None)
                return self.annotation_classes(sym.file, ret, _depth + 1)
            return []
        if isinstance(expr, ast.Name) and fi is not None:
            return self.local_env(fi).get(expr.id, [])
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and fi is not None and fi.cls is not None):
            return self.class_attr_types(fi.cls).get(expr.attr, [])
        return []

    def class_attr_types(self, ci: ClassInfo) -> Dict[str, List[ClassInfo]]:
        """``self.attr`` → inferred classes, scanned over all methods."""
        if ci._attr_types is not None:
            return ci._attr_types
        ci._attr_types = {}  # set first: cycles terminate
        out = ci._attr_types
        for m in ci.methods.values():
            if isinstance(m.node, ast.Lambda):
                continue
            ann_params = {}
            a = m.node.args
            for arg in a.posonlyargs + a.args + a.kwonlyargs:
                if arg.annotation is not None:
                    ann_params[arg.arg] = arg.annotation
            for node in _walk_own(m.node):
                tgt, val, ann = None, None, None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt, val = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    tgt, val, ann = node.target, node.value, node.annotation
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                classes: List[ClassInfo] = []
                if ann is not None:
                    classes = self.annotation_classes(ci.file, ann)
                if not classes and isinstance(val, ast.Name) \
                        and val.id in ann_params:
                    classes = self.annotation_classes(
                        ci.file, ann_params[val.id])
                if not classes and val is not None:
                    classes = self.expr_classes(ci.file, val, m)
                if classes:
                    cur = out.setdefault(tgt.attr, [])
                    for c in classes:
                        if c not in cur:
                            cur.append(c)
        return out

    def local_env(self, fi: FuncInfo) -> Dict[str, List[ClassInfo]]:
        """Parameter/assignment name → inferred classes, flow-insensitive."""
        env = self._env_cache.get(id(fi))
        if env is not None:
            return env
        env = self._env_cache[id(fi)] = {}
        node = fi.node
        if not isinstance(node, ast.Lambda):
            a = node.args
            for arg in a.posonlyargs + a.args + a.kwonlyargs:
                if arg.annotation is not None:
                    cs = self.annotation_classes(fi.file, arg.annotation)
                    if cs:
                        env[arg.arg] = cs
            for sub in _walk_own(node):
                tgt, val, ann = None, None, None
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    tgt, val = sub.targets[0], sub.value
                elif isinstance(sub, ast.AnnAssign):
                    tgt, val, ann = sub.target, sub.value, sub.annotation
                if not isinstance(tgt, ast.Name):
                    continue
                cs = self.annotation_classes(fi.file, ann) if ann else []
                if not cs and val is not None:
                    cs = self.expr_classes(fi.file, val, fi)
                if cs:
                    env.setdefault(tgt.id, [])
                    for c in cs:
                        if c not in env[tgt.id]:
                            env[tgt.id].append(c)
        return env

    # -- method lookup with project-local MRO ---------------------------------
    def lookup_method(self, ci: ClassInfo, name: str,
                      _seen=None) -> Optional[FuncInfo]:
        if _seen is None:
            _seen = set()
        if id(ci) in _seen:
            return None
        _seen.add(id(ci))
        m = ci.methods.get(name)
        if m is not None:
            return m
        for base in ci.bases:
            sym = (self.resolve_dotted(ci.file, base) if "." in base
                   else self.resolve_symbol(ci.file, base))
            if isinstance(sym, ClassInfo):
                m = self.lookup_method(sym, name, _seen)
                if m is not None:
                    return m
        return None

    def base_classes_of(self, fi: FuncInfo, base_expr) -> List[ClassInfo]:
        """Inferred classes of a call receiver expression."""
        if isinstance(base_expr, ast.Name):
            return self.local_env(fi).get(base_expr.id, [])
        if isinstance(base_expr, ast.Call):
            return self.expr_classes(fi.file, base_expr, fi)
        if (isinstance(base_expr, ast.Attribute)
                and isinstance(base_expr.value, ast.Name)
                and base_expr.value.id == "self" and fi.cls is not None):
            return self.class_attr_types(fi.cls).get(base_expr.attr, [])
        return []

    def _ctor(self, ci: ClassInfo) -> List[FuncInfo]:
        init = self.lookup_method(ci, "__init__")
        return [init] if init is not None else []

    # -- edges ----------------------------------------------------------------
    def callee_targets(self, fi: FuncInfo, call: ast.Call,
                       precise_only: bool) -> List[FuncInfo]:
        """Resolve one call site. ``precise_only=True`` (thread/signal
        walks) drops unresolvable calls; ``False`` (jit walk) falls back
        to the name-based over-approximation."""
        f = call.func
        sf = fi.file
        file_map = self.per_file_by_name.get(sf.relpath, {})
        if isinstance(f, ast.Name):
            if f.id in file_map:
                return list(file_map[f.id])
            sym = self.resolve_symbol(sf, f.id)
            if isinstance(sym, FuncInfo):
                return [sym]
            if isinstance(sym, ClassInfo):
                # constructor edges only on the precise walks: the jit
                # walk keeps its legacy name-based reach — a Layer()
                # built inside a reachable helper is setup-time, and
                # flagging every __init__ would bury the real leaks
                return self._ctor(sym) if precise_only else []
            if sym is not None or precise_only:
                return []
            return list(self.by_name.get(f.id, []))
        if not isinstance(f, ast.Attribute):
            return []
        m = f.attr
        base = f.value
        if (isinstance(base, ast.Name) and base.id in ("self", "cls")
                and fi.cls is not None):
            tgt = self.lookup_method(fi.cls, m)
            if tgt is not None:
                return [tgt]
            return [] if precise_only else list(
                self.methods_by_name.get(m, []))
        d = dotted_name(base)
        if d and "?" not in d:
            sym = self.resolve_dotted(sf, d)
            if isinstance(sym, tuple) and sym[0] == "module":
                target = self.project.by_relpath.get(sym[1])
                s2 = self.resolve_symbol(target, m) if target else None
                if isinstance(s2, FuncInfo):
                    return [s2]
                if isinstance(s2, ClassInfo):
                    return self._ctor(s2) if precise_only else []
                return []
            if isinstance(sym, tuple) and sym[0] == "extmodule":
                return []          # np.concatenate(...) etc: external
            if isinstance(sym, ClassInfo):
                tgt = self.lookup_method(sym, m)   # Class.method(obj, ...)
                return [tgt] if tgt else []
        owners = self.base_classes_of(fi, base)
        if owners:
            out = []
            for c in owners:
                tgt = self.lookup_method(c, m)
                if tgt is not None and tgt not in out:
                    out.append(tgt)
            if out:
                return out
            return [] if precise_only else list(
                self.methods_by_name.get(m, []))
        return [] if precise_only else list(self.methods_by_name.get(m, []))

    def edges(self, fi: FuncInfo, precise_only: bool) -> List[FuncInfo]:
        key = (id(fi), precise_only)
        cached = self._edge_cache.get(key)
        if cached is not None:
            return cached
        out: List[FuncInfo] = []
        for call in _own_body_calls(fi.node):
            for tgt in self.callee_targets(fi, call, precise_only):
                if tgt not in out:
                    out.append(tgt)
        self._edge_cache[key] = out
        return out

    def resolve_func_ref(self, sf: SourceFile, expr,
                         ctx: Optional[FuncInfo]) -> List[FuncInfo]:
        """Resolve a function *reference* (``target=X``, handler args).
        Lambdas become synthetic FuncInfos so walks can enter them."""
        if isinstance(expr, ast.Lambda):
            owner = ctx.qualname if ctx is not None else "<module>"
            fi = FuncInfo(sf, expr, f"{owner}.<lambda>:{expr.lineno}",
                          False, ctx.cls if ctx is not None else None)
            self.functions.append(fi)
            return [fi]
        if isinstance(expr, ast.Name):
            fis = self.per_file_by_name.get(sf.relpath, {}).get(expr.id)
            if fis:
                return list(fis)
            sym = self.resolve_symbol(sf, expr.id)
            if isinstance(sym, FuncInfo):
                return [sym]
            return []
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if (isinstance(base, ast.Name) and base.id in ("self", "cls")
                    and ctx is not None and ctx.cls is not None):
                tgt = self.lookup_method(ctx.cls, expr.attr)
                return [tgt] if tgt else []
            owners = (self.base_classes_of(ctx, base)
                      if ctx is not None else [])
            out = []
            for c in owners:
                tgt = self.lookup_method(c, expr.attr)
                if tgt is not None:
                    out.append(tgt)
            if out:
                return out
            # unique-name fallback: a single project def with this name
            cands = self.by_name.get(expr.attr, [])
            if len(cands) == 1:
                return list(cands)
        return []

    # -- collective walk ------------------------------------------------------
    def collective_issuers(self) -> List[FuncInfo]:
        return [f for f in self.functions if f.collective_via is not None]

    def collective_call_via(self, fi: Optional[FuncInfo],
                            call: ast.Call) -> Optional[str]:
        """Why this call site issues a collective, or None.

        Recognizes the direct lax vocabulary and the unambiguous wrapper
        names by name alone; everything else goes through the precise
        call resolution so a name collision can never invent a deadlock
        (same asymmetry as the thread/signal walks)."""
        d = dotted_name(call.func)
        last = d.rpartition(".")[2]
        if last in LAX_COLLECTIVES or last in COLLECTIVE_UNAMBIGUOUS_NAMES:
            return f"`{d}`"
        if fi is None:
            return None
        for tgt in self.callee_targets(fi, call, precise_only=True):
            if tgt.collective_via is not None:
                return f"`{tgt.qualname}` → {tgt.collective_via}"
        return None


# -- AST walking helpers ------------------------------------------------------

def _walk_own(func_node):
    """Nodes of a function's own body, stopping at nested defs/classes."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _own_body_calls(func_node):
    for node in _walk_own(func_node):
        if isinstance(node, ast.Call):
            yield node


def _iter_calls_with_context(graph: CallGraph, sf: SourceFile):
    """Yield (call, enclosing FuncInfo or None) for every call in a file."""
    fis = [fi for fi in graph.functions
           if fi.file is sf and not isinstance(fi.node, ast.Lambda)]
    for fi in fis:
        for call in _own_body_calls(fi.node):
            yield call, fi
    # module/class level: everything not inside a def
    stack = [(sf.tree, None)]
    while stack:
        node, _ = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(child, ast.Call):
                yield child, None
            stack.append((child, None))


# -- graph construction -------------------------------------------------------

def _collect_defs(graph: CallGraph, sf: SourceFile):
    file_map: Dict[str, List[FuncInfo]] = {}
    graph.per_file_by_name[sf.relpath] = file_map

    def visit(node, qual: str, cls: Optional[ClassInfo]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{qual}.{child.name}" if qual else child.name
                fi = FuncInfo(sf, child, q, cls is not None, cls)
                graph.functions.append(fi)
                graph.by_name.setdefault(child.name, []).append(fi)
                file_map.setdefault(child.name, []).append(fi)
                if cls is not None:
                    graph.methods_by_name.setdefault(child.name,
                                                     []).append(fi)
                    cls.methods.setdefault(child.name, fi)
                visit(child, q, None)
            elif isinstance(child, ast.ClassDef):
                q = f"{qual}.{child.name}" if qual else child.name
                ci = ClassInfo(sf, child, q)
                graph.classes.append(ci)
                graph.classes_by_name.setdefault(child.name, []).append(ci)
                visit(child, q, ci)
            else:
                visit(child, qual, cls)

    visit(sf.tree, "", None)


def _collect_imports(graph: CallGraph, sf: SourceFile):
    imp: Dict[str, tuple] = {}
    pkg = _package_of(sf.relpath)
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    imp[a.asname] = ("module", a.name)
                else:
                    top = a.name.split(".")[0]
                    imp.setdefault(top, ("module", top))
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                parts = pkg.split(".") if pkg else []
                up = node.level - 1
                parts = parts[: len(parts) - up] if up <= len(parts) else []
                base = ".".join(parts + ([node.module] if node.module
                                         else []))
            for a in node.names:
                if a.name == "*":
                    continue
                imp[a.asname or a.name] = ("from", base, a.name)
    graph.file_imports[sf.relpath] = imp


def _decorator_is_jit(dec: ast.AST) -> bool:
    base = dec.func if isinstance(dec, ast.Call) else dec
    if dotted_name(base).rpartition(".")[2] in JIT_DECORATORS:
        return True
    # functools.partial(jax.jit, ...) and friends: look one level into args
    if isinstance(dec, ast.Call):
        for a in dec.args:
            if dotted_name(a).rpartition(".")[2] in JIT_DECORATORS:
                return True
    return False


def _mark_jit_roots(graph: CallGraph, sf: SourceFile):
    file_map = graph.per_file_by_name[sf.relpath]
    for fi in graph.functions:
        if fi.file is not sf or isinstance(fi.node, ast.Lambda):
            continue
        for dec in fi.node.decorator_list:
            if _decorator_is_jit(dec):
                fi.root_via = f"decorator @{dotted_name(dec if not isinstance(dec, ast.Call) else dec.func) or 'jit'}"
                graph.roots.append(fi)
                break
    # named functions handed to trace-entering wrappers
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        wrapper = dotted_name(node.func).rpartition(".")[2]
        if wrapper not in TRACE_WRAPPERS:
            continue
        cand = list(node.args) + [kw.value for kw in node.keywords]
        for a in cand:
            if isinstance(a, ast.Name) and a.id in file_map:
                for fi in file_map[a.id]:
                    if fi.root_via is None:
                        fi.root_via = f"passed to {dotted_name(node.func)}()"
                        graph.roots.append(fi)


def _thread_target_arg(call: ast.Call):
    for kw in call.keywords:
        if kw.arg == "target":
            return kw.value
    # threading.Thread(group, target, ...): target is the 2nd positional
    if len(call.args) >= 2:
        return call.args[1]
    return None


def _mark_concurrency_roots(graph: CallGraph, sf: SourceFile):
    def add(kind: str, fis: List[FuncInfo], via: str):
        roots = graph.thread_roots if kind == "thread" else graph.signal_roots
        attr = kind + "_root_via"
        for fi in fis:
            if getattr(fi, attr) is None:
                setattr(fi, attr, via)
                roots.append(fi)

    for call, ctx in _iter_calls_with_context(graph, sf):
        f = call.func
        callee = dotted_name(f)
        last = callee.rpartition(".")[2]
        if last in THREAD_CTORS:
            tgt = _thread_target_arg(call)
            if tgt is not None:
                add("thread", graph.resolve_func_ref(sf, tgt, ctx),
                    f"{callee}(target=...) at {sf.relpath}:{call.lineno}")
        elif isinstance(f, ast.Attribute) and f.attr in ("submit", "map") \
                and call.args:
            # executor.submit(fn, ...): only a *resolved function* first
            # arg makes a root (engine.submit(arrays) resolves to nothing)
            fis = graph.resolve_func_ref(sf, call.args[0], ctx)
            if fis:
                add("thread", fis,
                    f"submitted to executor at {sf.relpath}:{call.lineno}")
        elif callee == "signal.signal" or callee.endswith(".signal.signal"):
            if len(call.args) >= 2:
                add("signal", graph.resolve_func_ref(sf, call.args[1], ctx),
                    f"signal.signal() at {sf.relpath}:{call.lineno}")
        elif last == "ChainedSignalHandler":
            handler = call.args[0] if call.args else None
            for kw in call.keywords:
                if kw.arg == "callback":
                    handler = kw.value
            if handler is not None:
                add("signal", graph.resolve_func_ref(sf, handler, ctx),
                    f"ChainedSignalHandler at {sf.relpath}:{call.lineno}")

    # Thread/Process subclasses: run() is the entry point
    for ci in graph.classes:
        if ci.file is not sf:
            continue
        if any(b.rpartition(".")[2] in THREAD_CTORS for b in ci.bases):
            run = ci.methods.get("run")
            if run is not None:
                add("thread", [run],
                    f"{ci.qualname}.run (Thread subclass)")


def _axis_literals(node) -> List[str]:
    """String axis names in an expression: "dp", ("dp", "sp"), ["dp"]."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            out.extend(_axis_literals(e))
        return out
    return []


#: constructors/factories whose arguments declare mesh axis names
_MESH_CTORS = {"Mesh", "AbstractMesh", "make_mesh", "build_mesh",
               "ensure_mesh"}


def _mesh_call_axes(call: ast.Call) -> List[str]:
    """Axis names declared by a Mesh(...)-style call: the axis-names
    tuple (2nd positional or axis_names=) or a {"pp": 4} shape dict."""
    out: List[str] = []
    cand = list(call.args[1:2])
    for kw in call.keywords:
        if kw.arg in ("axis_names", "axis_name"):
            cand.append(kw.value)
    for a in list(call.args[:1]) + [kw.value for kw in call.keywords
                                    if kw.arg in (None, "shape", "axes")]:
        if isinstance(a, ast.Dict):
            for k in a.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out.append(k.value)
    for c in cand:
        out.extend(_axis_literals(c))
    return out


def _resolve_mesh_axes(graph: CallGraph, sf: SourceFile, expr,
                       ctx: Optional[FuncInfo]) -> Optional[tuple]:
    """Literal axis names of a ``mesh=`` argument, or None when the mesh
    flows in from somewhere the symbol tables cannot see (a parameter, a
    runtime registry)."""
    if isinstance(expr, ast.Call):
        last = dotted_name(expr.func).rpartition(".")[2]
        if last in _MESH_CTORS:
            axes = _mesh_call_axes(expr)
            return tuple(axes) if axes else None
        return None
    if isinstance(expr, ast.Name):
        # nearest literal assignment: the enclosing function first, then
        # module level of the same file
        scopes = []
        if ctx is not None and not isinstance(ctx.node, ast.Lambda):
            scopes.append(_walk_own(ctx.node))
        scopes.append(ast.iter_child_nodes(sf.tree))
        for scope in scopes:
            for node in scope:
                if (isinstance(node, ast.Assign)
                        and any(isinstance(t, ast.Name)
                                and t.id == expr.id
                                for t in node.targets)):
                    got = _resolve_mesh_axes(graph, sf, node.value, ctx)
                    if got is not None:
                        return got
    return None


def _collect_axis_declarations(graph: CallGraph, sf: SourceFile):
    """Project-wide declared-axis set: mesh constructions, PartitionSpec
    literals, and axis-parameter string defaults. The axis-hygiene check
    only trusts this set when a collective's enclosing shard_map mesh
    cannot be resolved precisely."""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            last = dotted_name(node.func).rpartition(".")[2]
            if last in _MESH_CTORS:
                graph.declared_axes.update(_mesh_call_axes(node))
            elif last in ("P", "PartitionSpec", "NamedSharding"):
                for a in list(node.args) + [k.value for k in node.keywords]:
                    graph.declared_axes.update(_axis_literals(a))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            params = a.posonlyargs + a.args + a.kwonlyargs
            defaults = ([None] * (len(a.posonlyargs + a.args)
                                  - len(a.defaults)) + list(a.defaults)
                        + list(a.kw_defaults))
            for arg, dflt in zip(params, defaults):
                if dflt is not None and arg.arg in (
                        "axis", "axis_name", "batch_axis", "batch_axes"):
                    graph.declared_axes.update(_axis_literals(dflt))


def _collect_shard_map_wraps(graph: CallGraph, sf: SourceFile):
    """Record the mesh axes of every function handed to shard_map, so
    the axis-hygiene check can validate literal axis names inside the
    wrapped body against the enclosing mesh declaration."""
    fis = [fi for fi in graph.functions
           if fi.file is sf and not isinstance(fi.node, ast.Lambda)]
    sites = [(call, fi) for fi in fis
             for call in _own_body_calls(fi.node)]
    sites.extend(
        (call, None) for call, ctx in _iter_calls_with_context(graph, sf)
        if ctx is None)
    for call, ctx in sites:
        if dotted_name(call.func).rpartition(".")[2] != "shard_map":
            continue
        fn_expr = call.args[0] if call.args else None
        for kw in call.keywords:
            if kw.arg in ("f", "fun"):
                fn_expr = kw.value
        if fn_expr is None:
            continue
        mesh_expr = call.args[1] if len(call.args) > 1 else None
        for kw in call.keywords:
            if kw.arg == "mesh":
                mesh_expr = kw.value
        axes = (None if mesh_expr is None
                else _resolve_mesh_axes(graph, sf, mesh_expr, ctx))
        via = f"shard_map at {sf.relpath}:{call.lineno}"
        for fi in graph.resolve_func_ref(sf, fn_expr, ctx):
            prev = graph.shard_map_axes.get(id(fi))
            # several wrap sites: keep resolved axes over unresolved,
            # drop to None when two sites resolve to different meshes
            if prev is None or (prev[0] is None and axes is not None):
                graph.shard_map_axes[id(fi)] = (axes, via)
            elif prev[0] is not None and axes is not None \
                    and set(axes) != set(prev[0]):
                graph.shard_map_axes[id(fi)] = (None, via)


def _mark_collective_seeds(graph: CallGraph):
    for fi in graph.functions:
        if isinstance(fi.node, ast.Lambda):
            continue
        if (not fi.is_method
                and fi.file.relpath.endswith(COLLECTIVE_WRAPPER_FILE)
                and fi.name in COLLECTIVE_WRAPPER_NAMES):
            fi.collective_via = (f"collective wrapper "
                                 f"{fi.file.relpath}:{fi.node.lineno}")
            continue
        for call in _own_body_calls(fi.node):
            d = dotted_name(call.func)
            if d.rpartition(".")[2] in LAX_COLLECTIVES:
                fi.collective_via = (f"calls `{d}` at "
                                     f"{fi.file.relpath}:{call.lineno}")
                break


def _collective_walk(graph: CallGraph):
    """Reverse BFS from the seeds over precise edges: mark every function
    from which a collective call is reachable. Precise-only, like the
    thread/signal walks — a deadlock finding must never be invented
    through a name collision."""
    callers: Dict[int, List[FuncInfo]] = {}
    for fi in graph.functions:
        for callee in graph.edges(fi, precise_only=True):
            callers.setdefault(id(callee), []).append(fi)
    queue = [fi for fi in graph.functions
             if fi.collective_via is not None]
    while queue:
        callee = queue.pop(0)
        for caller in callers.get(id(callee), []):
            if caller.collective_via is None:
                caller.collective_via = (f"calls `{callee.qualname}` → "
                                         f"{callee.collective_via}")
                queue.append(caller)


def _bfs(graph: CallGraph, roots: List[FuncInfo], mark_attr: str,
         precise_only: bool):
    queue = []
    for r in roots:
        if getattr(r, mark_attr) is None:
            setattr(r, mark_attr, r.qualname)
            queue.append(r)
    while queue:
        fi = queue.pop(0)
        for callee in graph.edges(fi, precise_only):
            if getattr(callee, mark_attr) is None:
                setattr(callee, mark_attr, getattr(fi, mark_attr))
                queue.append(callee)


def build(project: Project) -> CallGraph:
    graph = CallGraph(project)
    for sf in project.files:
        if sf.tree is not None:
            mod = _module_name(sf.relpath)
            if mod:
                graph.modules[mod] = sf.relpath
            _collect_defs(graph, sf)
    for sf in project.files:
        if sf.tree is not None:
            _collect_imports(graph, sf)
    for sf in project.files:
        if sf.tree is not None:
            _mark_jit_roots(graph, sf)
            _mark_concurrency_roots(graph, sf)
            _collect_axis_declarations(graph, sf)
            _collect_shard_map_wraps(graph, sf)

    # jit walk keeps the name-based over-approximation (never miss a
    # tracer leak); thread/signal walks are precise (never invent a race)
    _bfs(graph, graph.roots, "reachable_from", precise_only=False)
    _bfs(graph, graph.thread_roots + graph.signal_roots,
         "thread_reachable_from", precise_only=True)
    _bfs(graph, graph.signal_roots, "signal_reachable_from",
         precise_only=True)
    # collective walk (PTA011): seed direct lax calls + the
    # distributed/collective.py wrappers, then propagate to callers over
    # the same precise edges the thread walk trusts
    _mark_collective_seeds(graph)
    _collective_walk(graph)
    return graph
