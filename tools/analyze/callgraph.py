"""Conservative call-graph over the analyzed files for jit-reachability.

PTA001 needs to know which functions can execute *under a JAX trace*: a
host sync that is perfectly fine in eager code is a tracer leak inside
``jax.jit`` / ``pjit`` / ``to_static``. Full python call resolution is
undecidable, so this walks a name-based over-approximation:

roots
    - defs decorated with jit / pjit / to_static (bare, dotted or called:
      ``@jax.jit``, ``@to_static(input_spec=...)``, ``@functools.partial(
      jax.jit, static_argnums=...)``),
    - named functions passed as arguments to trace-entering wrappers
      (``jax.jit(f)``, ``jax.lax.scan(f, ...)``, ``jax.vjp``, ``pmap``,
      ``shard_map``, ``checkpoint`` ...).

edges
    - ``f()`` links to every def named ``f`` (same file preferred),
    - ``obj.m()`` / ``self.m()`` links to every *method* named ``m``.

Calls through variables, dicts or ``fn(*args)`` parameters are invisible;
in exchange the reachable set is small and high-precision (the dispatch
funnel internals, optimizer ``_update`` rules, scan/cond branch bodies),
which keeps PTA001 findings actionable rather than noisy.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional

from .core import Project, SourceFile, dotted_name

#: decorator names (last dotted component) that enter a trace
JIT_DECORATORS = {"jit", "pjit", "to_static"}

#: callables whose function-valued arguments are traced
TRACE_WRAPPERS = {
    "jit", "pjit", "vjp", "jvp", "grad", "value_and_grad", "pmap",
    "checkpoint", "remat", "scan", "while_loop", "fori_loop", "cond",
    "switch", "custom_vjp", "custom_jvp", "eval_shape", "make_jaxpr",
    "shard_map", "xmap", "pallas_call", "associated_scan", "vmap",
}


class FuncInfo:
    __slots__ = ("file", "node", "name", "qualname", "is_method",
                 "root_via", "reachable_from")

    def __init__(self, file: SourceFile, node, qualname: str,
                 is_method: bool):
        self.file = file
        self.node = node
        self.name = node.name
        self.qualname = qualname
        self.is_method = is_method
        self.root_via: Optional[str] = None       # why it is a root
        self.reachable_from: Optional[str] = None  # provenance root qualname


class CallGraph:
    def __init__(self):
        self.functions: List[FuncInfo] = []
        self.by_name: Dict[str, List[FuncInfo]] = {}
        self.methods_by_name: Dict[str, List[FuncInfo]] = {}
        self.per_file_by_name: Dict[str, Dict[str, List[FuncInfo]]] = {}
        self.roots: List[FuncInfo] = []

    def reachable(self) -> List[FuncInfo]:
        return [f for f in self.functions if f.reachable_from is not None]


def _collect_defs(graph: CallGraph, sf: SourceFile):
    file_map: Dict[str, List[FuncInfo]] = {}
    graph.per_file_by_name[sf.relpath] = file_map

    def visit(node, qual: str, in_class: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{qual}.{child.name}" if qual else child.name
                fi = FuncInfo(sf, child, q, in_class)
                graph.functions.append(fi)
                graph.by_name.setdefault(child.name, []).append(fi)
                file_map.setdefault(child.name, []).append(fi)
                if in_class:
                    graph.methods_by_name.setdefault(child.name,
                                                     []).append(fi)
                visit(child, q, False)
            elif isinstance(child, ast.ClassDef):
                q = f"{qual}.{child.name}" if qual else child.name
                visit(child, q, True)
            else:
                visit(child, qual, in_class)

    visit(sf.tree, "", False)


def _decorator_is_jit(dec: ast.AST) -> bool:
    base = dec.func if isinstance(dec, ast.Call) else dec
    if dotted_name(base).rpartition(".")[2] in JIT_DECORATORS:
        return True
    # functools.partial(jax.jit, ...) and friends: look one level into args
    if isinstance(dec, ast.Call):
        for a in dec.args:
            if dotted_name(a).rpartition(".")[2] in JIT_DECORATORS:
                return True
    return False


def _mark_roots(graph: CallGraph, sf: SourceFile):
    file_map = graph.per_file_by_name[sf.relpath]
    for fi in graph.functions:
        if fi.file is not sf:
            continue
        for dec in fi.node.decorator_list:
            if _decorator_is_jit(dec):
                fi.root_via = f"decorator @{dotted_name(dec if not isinstance(dec, ast.Call) else dec.func) or 'jit'}"
                graph.roots.append(fi)
                break
    # named functions handed to trace-entering wrappers
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        wrapper = dotted_name(node.func).rpartition(".")[2]
        if wrapper not in TRACE_WRAPPERS:
            continue
        cand = list(node.args) + [kw.value for kw in node.keywords]
        for a in cand:
            if isinstance(a, ast.Name) and a.id in file_map:
                for fi in file_map[a.id]:
                    if fi.root_via is None:
                        fi.root_via = f"passed to {dotted_name(node.func)}()"
                        graph.roots.append(fi)


def _own_body_calls(func_node):
    """Call nodes in a function body, including nested defs' bodies only via
    their own FuncInfo (we stop at nested defs here) but including lambdas."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _edges(graph: CallGraph, fi: FuncInfo) -> List[FuncInfo]:
    out: List[FuncInfo] = []
    file_map = graph.per_file_by_name[fi.file.relpath]
    for call in _own_body_calls(fi.node):
        f = call.func
        if isinstance(f, ast.Name):
            targets = file_map.get(f.id) or graph.by_name.get(f.id) or []
            out.extend(targets)
        elif isinstance(f, ast.Attribute):
            out.extend(graph.methods_by_name.get(f.attr, []))
    return out


def build(project: Project) -> CallGraph:
    graph = CallGraph()
    for sf in project.files:
        if sf.tree is not None:
            _collect_defs(graph, sf)
    for sf in project.files:
        if sf.tree is not None:
            _mark_roots(graph, sf)

    # BFS with provenance
    queue = []
    for r in graph.roots:
        if r.reachable_from is None:
            r.reachable_from = r.qualname
            queue.append(r)
    while queue:
        fi = queue.pop(0)
        for callee in _edges(graph, fi):
            if callee.reachable_from is None:
                callee.reachable_from = fi.reachable_from
                queue.append(callee)
    return graph
