"""Rule registry. Each rule module exposes a single ``RULE`` instance."""
from __future__ import annotations

from .pta001_tracer_safety import RULE as PTA001    # noqa: F401
from .pta002_host_sync import RULE as PTA002        # noqa: F401
from .pta003_silent_except import RULE as PTA003    # noqa: F401
from .pta004_op_registry import RULE as PTA004      # noqa: F401
from .pta005_api_hygiene import RULE as PTA005      # noqa: F401
from .pta006_lock_discipline import RULE as PTA006  # noqa: F401
from .pta007_signal_safety import RULE as PTA007    # noqa: F401
from .pta008_recompile_risk import RULE as PTA008   # noqa: F401
from .pta009_trace_fusion import RULE as PTA009     # noqa: F401
from .pta010_retrace_sentinel import RULE as PTA010  # noqa: F401
from .pta011_spmd_divergence import RULE as PTA011  # noqa: F401
from .pta012_collective_schedule import RULE as PTA012  # noqa: F401
from .pta013_pallas_safety import RULE as PTA013     # noqa: F401
from .pta014_fusion_miss import RULE as PTA014       # noqa: F401

# PTA009/PTA010/PTA012/PTA014 are tier="trace": they compile registered
# entrypoints and run only when selected via --only (__main__.select_rules)
ALL_RULES = [PTA001, PTA002, PTA003, PTA004, PTA005, PTA006, PTA007,
             PTA008, PTA009, PTA010, PTA011, PTA012, PTA013, PTA014]


def rules_by_code():
    return {r.code: r for r in ALL_RULES}
