"""PTA013: Pallas kernel-safety lint.

The Pallas surface (ops/pallas_attention.py fwd+bwd, ops/paged_attention
.py, the ring lanes in distributed/fleet/sequence_parallel.py) carries
safety invariants that nothing enforced until now — they lived in code
review convention. This rule walks every ``pl.pallas_call`` /
``pl.BlockSpec`` site and flags:

- **unguarded grid division** (error): a grid dimension computed as
  ``length // block`` where ``block`` is a dynamic name with neither a
  divisibility guard (``if length % block: raise``) nor provenance from
  a ``*sanitize*`` helper (the ``_sanitize_block`` /
  ``_sanitize_ring_blocks`` / ``_sanitize_block_h`` idiom). A
  non-dividing block makes the grid floor-divide and silently drop the
  tail rows/keys.
- **VMEM-busting block shapes** (error): constant BlockSpec shapes whose
  combined footprint (``paddle_tpu/tuner/space.py:blockspec_vmem_bytes``)
  exceeds ``VMEM_BUDGET``; plus — in :meth:`finalize` — every committed
  ``default_winners.json`` entry checked against the family VMEM model
  (``flash_vmem_bytes`` / ``paged_attn_vmem_bytes``), so a stale
  hand-edited winner fails lint instead of OOMing Mosaic on a TPU.
- **low-precision accumulator** (error): reduction accumulators or VMEM
  scratch (``pl.when``-initialized ``acc``/``m``/``l`` style) declared
  below f32 — ``jnp.zeros(..., jnp.bfloat16)`` in a kernel body or
  ``pltpu.VMEM(shape, jnp.float16)`` scratch. Online-softmax statistics
  accumulated in bf16 lose the exactness contract; integer masks are
  fine.
- **no interpret lane** (warning): a ``pl.pallas_call`` without an
  ``interpret=`` keyword — the kernel is unreachable off-TPU, so CPU
  tier-1 can never cover its math (ops/custom.py register_pallas_op
  convention requires the lane).

The VMEM cost models are imported from ``paddle_tpu/tuner/space.py`` via
``importlib`` file loading (the module is pure stdlib; importing the
*package* would pull jax, and the AST tier must stay stdlib-only).
"""
from __future__ import annotations

import ast
import importlib.util
import os
from typing import Dict, List, Optional, Tuple

from .base import Rule
from ..core import Finding, Project, SourceFile, dotted_name, walk_own_body

WINNERS_PATH = "paddle_tpu/tuner/default_winners.json"
SPACE_PATH = "paddle_tpu/tuner/space.py"

#: float dtypes below f32 — illegal for kernel accumulators/scratch.
#: Integer dtypes (NMS index masks) and f32/f64 never match.
_LOW_PRECISION = {"bfloat16", "float16", "half"}

_ITEMSIZE = {"bfloat16": 2, "float16": 2, "float32": 4, "float64": 8}

#: allocation calls whose result is a fresh array an accumulator is
#: typically initialized from
_ACC_ALLOCATORS = {"zeros", "ones", "full", "empty",
                   "zeros_like", "ones_like", "full_like", "empty_like"}

_SPACE_CACHE: Dict[str, object] = {}


def _load_space(root: str):
    """Load paddle_tpu/tuner/space.py as a standalone module (NOT through
    the package, whose __init__ imports jax — the AST tier must run
    without jax installed)."""
    path = os.path.join(root, SPACE_PATH)
    mod = _SPACE_CACHE.get(path)
    if mod is None:
        spec = importlib.util.spec_from_file_location("_pta013_space", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _SPACE_CACHE[path] = mod
    return mod


def _low_precision_dtype(node: Optional[ast.AST]) -> Optional[str]:
    """'bfloat16'/'float16' when the expression names a sub-f32 float
    dtype (``jnp.bfloat16``, ``"float16"``), else None."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value
    else:
        name = dotted_name(node).rsplit(".", 1)[-1]
    if name in _LOW_PRECISION or name.startswith("float8"):
        return name
    return None


def _const_shape(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """The tuple of ints when ``node`` is an all-constant shape tuple."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    dims = []
    for e in node.elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
            dims.append(e.value)
        else:
            return None
    return tuple(dims)


def _call_name(node: ast.Call) -> str:
    """Last attribute segment of the callee: pl.pallas_call -> pallas_call."""
    return dotted_name(node.func).rsplit(".", 1)[-1]


def parse_winner_key(key: str) -> Optional[Dict[str, object]]:
    """Decode a default_winners.json key into its model parameters.

    ``flash_fwd|tpu|bfloat16|d64|q4096|k4096|c1`` ->
    ``{"family": "flash_fwd", "dtype": "bfloat16", "d": 64, ...}``.
    Returns None for families without a VMEM model (nms, compress).
    """
    parts = key.split("|")
    family = parts[0]
    if not (family.startswith("flash") or family.startswith("ring_flash")
            or family == "paged_attn"):
        return None
    out: Dict[str, object] = {"family": family, "dtype": parts[2]}
    for p in parts[3:]:
        if len(p) > 1 and p[0] in "dqkhpc" and p[1:].isdigit():
            out[p[0]] = int(p[1:])
    return out


def iter_winner_footprints(root: str):
    """Yield ``(key, family, vmem_bytes, budget)`` for every committed
    winner that has a VMEM model. Shared by the rule's finalize and the
    tier-1 fail-fast test (tests/test_pallas_lint.py)."""
    import json
    space = _load_space(root)
    with open(os.path.join(root, WINNERS_PATH)) as f:
        entries = json.load(f).get("entries", {})
    for key, entry in sorted(entries.items()):
        params = parse_winner_key(key)
        if params is None:
            continue
        cfg = entry.get("config", {})
        itemsize = _ITEMSIZE.get(str(params["dtype"]), 4)
        family = str(params["family"])
        if family == "paged_attn":
            bytes_ = space.paged_attn_vmem_bytes(
                int(cfg.get("block_h", 1)), int(params.get("p", 16)),
                int(params.get("d", 64)), itemsize)
        else:
            bytes_ = space.flash_vmem_bytes(
                int(cfg.get("block_q", 16)), int(cfg.get("block_k", 16)),
                int(params.get("k", params.get("q", 16))),
                int(params.get("d", 64)), itemsize)
        yield key, family, bytes_, space.VMEM_BUDGET


class PallasSafetyRule(Rule):
    code = "PTA013"
    name = "pallas-kernel-safety"
    description = ("Pallas kernel-safety lint: unguarded grid divisions "
                   "(no divisibility check or sanitize-helper "
                   "provenance), VMEM-budget-busting BlockSpec shapes "
                   "and committed tuner winners, sub-f32 kernel "
                   "accumulators/scratch, pallas_call without an "
                   "interpret= lane")
    severity = "error"

    def visit_file(self, sf: SourceFile, project: Project) -> List[Finding]:
        if "pallas" not in sf.text:
            return []
        findings: List[Finding] = []
        space = None
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_function(sf, node))
            elif isinstance(node, ast.Call):
                name = _call_name(node)
                if name == "pallas_call":
                    findings.extend(self._check_interpret_lane(sf, node))
                    if space is None:
                        space = _load_space(project.root)
                    findings.extend(
                        self._check_blockspec_vmem(sf, node, space))
                elif name == "VMEM":
                    findings.extend(self._check_vmem_scratch(sf, node))
        return findings

    def finalize(self, project: Project) -> List[Finding]:
        """Committed tuner winners must fit the family VMEM model — a
        stale hand-edited entry should fail lint in CI, not OOM Mosaic
        on the first TPU run."""
        if not os.path.isfile(os.path.join(project.root, WINNERS_PATH)):
            return []
        winners_sf = project.read_rootfile(WINNERS_PATH)
        findings: List[Finding] = []
        for key, family, bytes_, budget in iter_winner_footprints(
                project.root):
            if bytes_ <= budget:
                continue
            line = next((i for i, ln in enumerate(
                winners_sf.lines, 1) if key in ln), 1)
            findings.append(Finding(
                self.code, WINNERS_PATH, line, 0,
                f"committed winner `{key}` needs {bytes_} VMEM bytes "
                f"({bytes_ / (1 << 20):.1f} MiB) by the `{family}` cost "
                f"model — over the {budget} byte budget; this entry "
                f"would OOM Mosaic on real hardware, re-tune it",
                anchor=f"pallas:winner:{key}", severity="error"))
        return findings

    # -- (a) unguarded grid division -----------------------------------------

    def _check_function(self, sf: SourceFile,
                        fn: ast.AST) -> List[Finding]:
        findings: List[Finding] = []
        calls = [n for n in walk_own_body(fn) if isinstance(n, ast.Call)]
        grid_tuples = []
        for call in calls:
            if _call_name(call) not in ("pallas_call",
                                        "PrefetchScalarGridSpec"):
                continue
            for kw in call.keywords:
                if kw.arg == "grid" and isinstance(kw.value,
                                                   (ast.Tuple, ast.List)):
                    grid_tuples.append(kw.value)
        if grid_tuples:
            guarded = self._guarded_divisors(fn)
            sanitized = self._sanitized_names(fn)
            for tup in grid_tuples:
                for elt in tup.elts:
                    findings.extend(self._check_grid_elt(
                        sf, elt, guarded, sanitized))
        findings.extend(self._check_kernel_accumulators(sf, fn))
        return findings

    def _guarded_divisors(self, fn: ast.AST) -> set:
        """Names that appear as the right operand of a `%` inside an
        `if` test whose body raises — the explicit divisibility guard
        (`if s_pad % bq or kv_pad % bk: raise ValueError(...)`)."""
        guarded = set()
        for node in walk_own_body(fn):
            if not isinstance(node, ast.If):
                continue
            if not any(isinstance(b, ast.Raise) for b in node.body):
                continue
            for sub in ast.walk(node.test):
                if (isinstance(sub, ast.BinOp)
                        and isinstance(sub.op, ast.Mod)
                        and isinstance(sub.right, ast.Name)):
                    guarded.add(sub.right.id)
        return guarded

    def _sanitized_names(self, fn: ast.AST) -> set:
        """Names bound (anywhere in the function) from a call to a
        ``*sanitize*`` helper — the sanctioned provenance
        (`block_h = _sanitize_block_h(block_h, num_heads)`)."""
        names = set()
        for node in walk_own_body(fn):
            if not isinstance(node, ast.Assign):
                continue
            val = node.value
            is_sanitize = (isinstance(val, ast.Call)
                           and "sanitize" in dotted_name(val.func).lower())
            if not is_sanitize and isinstance(val, (ast.Tuple, ast.List)):
                continue
            if not is_sanitize:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
                elif isinstance(tgt, (ast.Tuple, ast.List)):
                    names.update(e.id for e in tgt.elts
                                 if isinstance(e, ast.Name))
        return names

    def _check_grid_elt(self, sf: SourceFile, elt: ast.AST,
                        guarded: set, sanitized: set) -> List[Finding]:
        findings: List[Finding] = []
        for sub in ast.walk(elt):
            if not (isinstance(sub, ast.BinOp)
                    and isinstance(sub.op, ast.FloorDiv)):
                continue
            div = sub.right
            if not isinstance(div, ast.Name):
                continue  # constant or attribute divisors: shape-static
            if div.id in guarded or div.id in sanitized:
                continue
            findings.append(sf.finding(
                self.code, sub,
                f"grid dimension floor-divides by dynamic block "
                f"`{div.id}` with no divisibility guard — a "
                f"non-dividing block silently drops the tail "
                f"rows/keys; add `if length % {div.id}: raise` or "
                f"bind it through a `_sanitize_*` helper "
                f"(ops/pallas_attention.py idiom)"))
        return findings

    # -- (b) VMEM footprint ---------------------------------------------------

    def _check_blockspec_vmem(self, sf: SourceFile, call: ast.Call,
                              space) -> List[Finding]:
        """Sum the constant-shape BlockSpec blocks of one pallas_call; a
        footprint over budget is a finding even though dynamic shapes are
        skipped — the constant blocks alone are a lower bound."""
        shapes = []
        for sub in ast.walk(call):
            if not (isinstance(sub, ast.Call)
                    and _call_name(sub) == "BlockSpec" and sub.args):
                continue
            shape = _const_shape(sub.args[0])
            if shape:
                shapes.append(shape)
        if not shapes:
            return []
        bytes_ = space.blockspec_vmem_bytes(shapes)
        if bytes_ <= space.VMEM_BUDGET:
            return []
        return [sf.finding(
            self.code, call,
            f"pallas_call BlockSpecs pin {bytes_} bytes "
            f"({bytes_ / (1 << 20):.1f} MiB) of VMEM at f32 — over the "
            f"{space.VMEM_BUDGET} byte budget "
            f"(paddle_tpu/tuner/space.py); shrink the blocks or tile "
            f"the long axis through the grid",
            anchor=f"pallas:vmem:{sf.line_text(call.lineno)}")]

    # -- (c) low-precision accumulators/scratch -------------------------------

    def _check_kernel_accumulators(self, sf: SourceFile,
                                   fn: ast.AST) -> List[Finding]:
        args = getattr(fn, "args", None)
        if args is None or not any(a.arg.endswith("_ref")
                                   for a in args.posonlyargs + args.args):
            return []
        findings: List[Finding] = []
        for node in walk_own_body(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name not in _ACC_ALLOCATORS:
                continue
            dtype_node = next((kw.value for kw in node.keywords
                               if kw.arg == "dtype"), None)
            if dtype_node is None and node.args:
                # positional dtype: zeros(shape, dtype) / full(shape,
                # fill, dtype); *_like(x, dtype) also lands at args[1]
                idx = 2 if name == "full" else 1
                if len(node.args) > idx:
                    dtype_node = node.args[idx]
            low = _low_precision_dtype(dtype_node)
            if low:
                findings.append(sf.finding(
                    self.code, node,
                    f"kernel accumulator allocated as {low} via "
                    f"`{name}` — online-softmax/reduction statistics "
                    f"must accumulate in f32 (declare f32 and cast on "
                    f"the final store, ops/pallas_attention.py idiom)"))
        return findings

    def _check_vmem_scratch(self, sf: SourceFile,
                            call: ast.Call) -> List[Finding]:
        dtype_node = None
        if len(call.args) > 1:
            dtype_node = call.args[1]
        else:
            dtype_node = next((kw.value for kw in call.keywords
                               if kw.arg == "dtype"), None)
        low = _low_precision_dtype(dtype_node)
        if not low:
            return []
        return [sf.finding(
            self.code, call,
            f"VMEM scratch declared {low} — scratch accumulators carry "
            f"running statistics across grid steps and must stay f32 "
            f"(the output cast happens once, on the final store)")]

    # -- (d) interpret lane ---------------------------------------------------

    def _check_interpret_lane(self, sf: SourceFile,
                              call: ast.Call) -> List[Finding]:
        if any(kw.arg == "interpret" for kw in call.keywords):
            return []
        return [sf.finding(
            self.code, call,
            "pallas_call without an `interpret=` keyword — the kernel "
            "is unreachable off-TPU, so CPU tier-1 can never cover its "
            "math; thread an interpret flag through "
            "(ops/custom.py convention)",
            severity="warning")]


RULE = PallasSafetyRule()
