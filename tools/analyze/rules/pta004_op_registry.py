"""PTA004: op-registry <-> catalog consistency.

The dispatch funnel (``paddle_tpu/ops/dispatch.py``) is the single place
every framework op goes through, and ``tools/op_catalog.txt`` is the
audited list of reference forward ops (``tools/op_coverage.py`` maps each
entry to an implementation / absorption / ADR). Those two surfaces drift
silently: an op registered under a name the catalog never heard of is
invisible to the coverage audit, and a catalog entry nothing claims is a
parity hole that looks "done".

Static cross-check, both directions:

- **registration side**: every string-literal op name passed to
  ``apply(...)`` / ``apply_raw(...)`` / ``defop(...)`` /
  ``@register_op(...)`` (plus the keys of table-driven op dicts like
  ``_UNARY`` in ops modules) must be claimed by the catalog — directly,
  through an ``ALIASES`` / ``MANUAL_IMPL`` mapping in op_coverage.py, or
  via the catalog's ``_v2``/trailing-``2`` variants.
- **catalog side**: every catalog entry must be claimed by a registered
  op name, a def/class of that name somewhere in the analyzed tree, or an
  op_coverage.py status table (MANUAL_IMPL / ABSORBED / ADR / NA).
- catalog hygiene: entries sorted, unique, non-empty (``#`` comments ok).
- ``# native: <name>`` comment lines claim tpu-native / internal ops that
  have no reference catalog entry; a native claim whose op no longer
  exists is flagged as stale.
- every ops module documents its parity target with a ``reference:`` line
  in the module docstring.
- ops registered inside module-private helpers that nothing calls or
  re-exports are flagged: the public surface can't reach them, so they
  are dead registrations.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from .base import Rule
from ..core import Finding, Project, SourceFile

CATALOG_RELPATH = "tools/op_catalog.txt"
COVERAGE_RELPATH = "tools/op_coverage.py"
OPS_DIR = "paddle_tpu/ops/"

REGISTER_FUNCS = {"apply", "apply_raw", "_apply", "defop", "register_op"}
COVERAGE_TABLES = {"ALIASES", "MANUAL_IMPL", "ABSORBED", "ADR", "NA"}

_OP_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_TABLE_NAME_RE = re.compile(r"^_[A-Z][A-Z_]*$")


def _literal_str_keys(d: ast.Dict) -> List[Tuple[str, int]]:
    out = []
    for k, v in zip(d.keys, d.values):
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            out.append((k.value, k.lineno))
        elif k is None and isinstance(v, ast.DictComp):
            # `{**{k: v for k in [...]}, ...}` — the ADR table pattern
            it = v.generators[0].iter
            if isinstance(it, (ast.List, ast.Tuple)):
                for e in it.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value,
                                                                  str):
                        out.append((e.value, e.lineno))
    return out


def _collect_registered(project: Project) -> Dict[str, List[Tuple[SourceFile,
                                                                  int, str]]]:
    """op name -> [(file, line, enclosing_toplevel_def)] for every static
    registration site in the analyzed files."""
    reg: Dict[str, List[Tuple[SourceFile, int, str]]] = {}

    def add(name, sf, lineno, encl):
        reg.setdefault(name, []).append((sf, lineno, encl))

    for sf in project.files:
        if sf.tree is None:
            continue
        # registration calls, with enclosing top-level def tracked
        def walk(node, encl: str):
            for child in ast.iter_child_nodes(node):
                child_encl = encl
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    child_encl = encl or child.name
                if isinstance(child, ast.Call):
                    f = child.func
                    fname = f.attr if isinstance(f, ast.Attribute) else (
                        f.id if isinstance(f, ast.Name) else "")
                    if (fname in REGISTER_FUNCS and child.args
                            and isinstance(child.args[0], ast.Constant)
                            and isinstance(child.args[0].value, str)):
                        add(child.args[0].value, sf, child.lineno,
                            child_encl)
                walk(child, child_encl)
        walk(sf.tree, "")

        # table-driven op dicts (ops modules only): _UNARY = {"abs": ...}
        if OPS_DIR in sf.relpath:
            for node in sf.tree.body:
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and _TABLE_NAME_RE.match(node.targets[0].id)
                        and isinstance(node.value, ast.Dict)):
                    for name, lineno in _literal_str_keys(node.value):
                        if _OP_NAME_RE.match(name):
                            add(name, sf, lineno, "")
    return reg


def _collect_coverage_claims(project: Project) -> Tuple[Set[str], Set[str]]:
    """(catalog-side claim keys, our-side claimed names) from the status
    tables in tools/op_coverage.py. Missing file -> empty sets."""
    sf = project.read_rootfile(COVERAGE_RELPATH)
    keys: Set[str] = set()
    ours: Set[str] = set()
    if sf is None or sf.tree is None:
        return keys, ours
    for node in sf.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id in COVERAGE_TABLES
                and isinstance(node.value, ast.Dict)):
            continue
        tbl = node.targets[0].id
        for k, _ in _literal_str_keys(node.value):
            keys.add(k)
        if tbl == "ALIASES":
            for v in node.value.values:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    ours.add(v.value)
        elif tbl == "MANUAL_IMPL":
            for v in node.value.values:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    # "module:attr[.attr]" — the attr is our-side name
                    attr = v.value.partition(":")[2]
                    if attr:
                        ours.add(attr.split(".")[-1])
    return keys, ours


def _collect_used_names(project: Project) -> Set[str]:
    """Names that are called or re-exported somewhere in the analyzed
    tree — a registration inside a private helper is only *dead* when
    nothing uses the helper."""
    used: Set[str] = set()
    for sf in project.files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                    and node.id.startswith("_")):
                used.add(node.id)  # called, aliased, or put in a table
            elif isinstance(node, ast.Attribute) and node.attr.startswith("_"):
                used.add(node.attr)
    return used


def _collect_defnames(project: Project) -> Set[str]:
    names: Set[str] = set()
    for sf in project.files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                names.add(node.name)
    return names


def _catalog_candidates(name: str, aliases: Dict[str, str]) -> List[str]:
    """Mirror op_coverage.resolve()'s candidate generation, statically."""
    cands = [name]
    if name in aliases:
        cands.append(aliases[name])
    if name.endswith("_v2"):
        cands.append(name[:-3])
        if name[:-3] in aliases:
            cands.append(aliases[name[:-3]])
    elif name.endswith("2") and not name.endswith("v2"):
        cands.append(name[:-1])
    return cands


def _collect_aliases(project: Project) -> Dict[str, str]:
    sf = project.read_rootfile(COVERAGE_RELPATH)
    out: Dict[str, str] = {}
    if sf is None or sf.tree is None:
        return out
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "ALIASES"
                and isinstance(node.value, ast.Dict)):
            for k, v in zip(node.value.keys, node.value.values):
                if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)):
                    out[k.value] = v.value
    return out


class OpRegistryRule(Rule):
    code = "PTA004"
    name = "op-registry-consistency"
    description = ("dispatch registrations, tools/op_catalog.txt and "
                   "tools/op_coverage.py status tables must agree")

    def finalize(self, project: Project) -> List[Finding]:
        if not any(OPS_DIR in sf.relpath for sf in project.files):
            return []  # nothing op-shaped in the analyzed paths
        findings: List[Finding] = []

        catalog_sf = project.read_rootfile(CATALOG_RELPATH)
        if catalog_sf is None:
            return []  # mini-repos without a catalog: nothing to check
        entries: List[Tuple[str, int]] = []
        native: Dict[str, int] = {}  # `# native: name` claims
        for i, ln in enumerate(catalog_sf.lines, 1):
            s = ln.strip()
            if s.startswith("#"):
                m = re.match(r"#\s*native:\s*([a-z][a-z0-9_]*)\s*$", s)
                if m:
                    native.setdefault(m.group(1), i)
            elif s:
                entries.append((s, i))

        # hygiene: sorted + unique
        seen: Dict[str, int] = {}
        prev = ""
        for name, lineno in entries:
            if name in seen:
                findings.append(catalog_sf.finding(
                    self.code, lineno,
                    f"duplicate catalog entry '{name}' "
                    f"(first at line {seen[name]})", anchor=f"dup:{name}"))
            else:
                seen[name] = lineno
            if name < prev:
                findings.append(catalog_sf.finding(
                    self.code, lineno,
                    f"catalog entry '{name}' breaks sort order "
                    f"(after '{prev}')", anchor=f"sort:{name}"))
            prev = name

        catalog = set(seen)
        registered = _collect_registered(project)
        coverage_keys, coverage_ours = _collect_coverage_claims(project)
        aliases = _collect_aliases(project)
        alias_rev: Dict[str, List[str]] = {}
        for k, v in aliases.items():
            alias_rev.setdefault(v, []).append(k)
        defnames = _collect_defnames(project)
        used_names = _collect_used_names(project)

        # registration side: every registered name must be claimed
        catalog_variants = set(catalog)
        for c in catalog:
            if c.endswith("_v2"):
                catalog_variants.add(c[:-3])
            elif c.endswith("2") and not c.endswith("v2"):
                catalog_variants.add(c[:-1])
        for name, sites in sorted(registered.items()):
            claimed = (name in catalog_variants
                       or name in native
                       or name in coverage_ours
                       or any(a in catalog for a in alias_rev.get(name, ())))
            if not claimed:
                sf, lineno, _encl = sites[0]
                findings.append(sf.finding(
                    self.code, lineno,
                    f"op '{name}' is registered through dispatch but has "
                    f"no entry in {CATALOG_RELPATH} and no ALIASES/"
                    f"MANUAL_IMPL mapping in {COVERAGE_RELPATH}",
                    anchor=f"unlisted:{name}"))
            # dead registration inside a private helper nothing uses
            for sf, lineno, encl in sites:
                if (OPS_DIR in sf.relpath and encl.startswith("_")
                        and not encl.startswith("__")
                        and encl not in used_names):
                    findings.append(sf.finding(
                        self.code, lineno,
                        f"op '{name}' is registered inside module-private "
                        f"helper `{encl}` — unreachable from the public "
                        f"API surface", anchor=f"private:{name}:{encl}"))

        # catalog side: every entry must be claimed by something real
        for name, lineno in entries:
            if name in coverage_keys:
                continue
            cands = _catalog_candidates(name, aliases)
            if any(c in registered or c in defnames for c in cands):
                continue
            findings.append(catalog_sf.finding(
                self.code, lineno,
                f"catalog entry '{name}' is claimed by nothing: no "
                f"registered op, no def/class of that name, no status "
                f"table in {COVERAGE_RELPATH} — implement it or record "
                f"an ADR/absorbed/na status", anchor=f"stale:{name}"))

        # native claims must still exist on our side
        for name, lineno in sorted(native.items()):
            if name not in registered and name not in defnames:
                findings.append(catalog_sf.finding(
                    self.code, lineno,
                    f"`# native: {name}` claims an op that is no longer "
                    f"registered anywhere — delete the claim or restore "
                    f"the op", anchor=f"stale-native:{name}"))

        # ops modules must state their parity target
        for sf in project.files:
            if (OPS_DIR in sf.relpath and sf.tree is not None
                    and not sf.relpath.endswith("__init__.py")):
                doc = ast.get_docstring(sf.tree) or ""
                if "reference" not in doc.lower():
                    findings.append(sf.finding(
                        self.code, 1,
                        "ops module docstring lacks a `reference:` line "
                        "naming its parity target in the reference "
                        "codebase", anchor="no-reference-line"))
        return findings


RULE = OpRegistryRule()
