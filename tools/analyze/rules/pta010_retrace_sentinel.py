"""PTA010: retrace sentinel.

For every registered auditable entrypoint, the trace runner jits the RAW
step under a counting wrapper (with the entrypoint's own jit kwargs) and
calls it twice with value-perturbed but shape/dtype-identical arguments.
A correct step traces exactly once; a second trace means the jit cache
key depends on something it shouldn't — a python scalar that changes per
batch, an unhashed container identity, a fresh closure per call. This is
the measured counterpart of PTA008 (which flags the *source patterns*
that cause retraces), and the regression guard for the class of bug PR 6
fixed in the LLM decode path.

The runner also lowers each variant and hashes the StableHLO text: a
stable trace count with an unstable executable fingerprint means the
program itself changed between calls (e.g. a captured constant differs),
which would recompile on a real device even when the python-level cache
hits.

Compiles code — runs only when selected (``--only PTA010``).
"""
from __future__ import annotations

from typing import List

from .base import Rule
from ..core import Finding, Project


class RetraceSentinelRule(Rule):
    code = "PTA010"
    name = "retrace-sentinel"
    tier = "trace"
    description = ("compile each registered entrypoint twice with value-"
                   "perturbed same-shape inputs; fail on a second trace "
                   "or an unstable executable fingerprint (runs only via "
                   "--only)")
    severity = "error"

    def finalize(self, project: Project) -> List[Finding]:
        from ..trace import get_report
        report = get_report()
        findings: List[Finding] = []
        if report.error:
            findings.append(Finding(
                self.code, "tools/analyze/trace/__init__.py", 1, 0,
                f"retrace sentinel could not run (jax/paddle_tpu import "
                f"failed): {report.error.strip().splitlines()[-1]}",
                anchor="trace:runner:unavailable", severity="error"))
            return findings
        for name, st in sorted(report.entrypoint_stats.items()):
            loc = (st.path or "tools/analyze/trace/__init__.py",
                   st.line or 1)
            if st.error:
                findings.append(Finding(
                    self.code, loc[0], loc[1], 0,
                    f"entrypoint `{name}` failed to build/trace: "
                    f"{st.error.strip().splitlines()[-1]}",
                    anchor=f"trace:{name}:error", severity="error"))
                continue
            if st.trace_count != 1:
                findings.append(Finding(
                    self.code, loc[0], loc[1], 0,
                    f"entrypoint `{name}` traced {st.trace_count}x "
                    f"across two calls with identical shapes/dtypes — "
                    f"the jit cache key is unstable (python-scalar "
                    f"argument, per-call closure, or unhashable static); "
                    f"expected exactly 1 trace",
                    anchor=f"trace:{name}:retrace", severity="error"))
            elif not st.fingerprint_stable:
                findings.append(Finding(
                    self.code, loc[0], loc[1], 0,
                    f"entrypoint `{name}` lowers to different programs "
                    f"for value-perturbed same-shape inputs "
                    f"({st.fingerprints[0]} vs {st.fingerprints[1]}) — "
                    f"an input value is being baked into the executable "
                    f"as a constant",
                    anchor=f"trace:{name}:fingerprint", severity="error"))
        return findings


RULE = RetraceSentinelRule()
