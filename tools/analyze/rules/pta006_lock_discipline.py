"""PTA006: lock-discipline — racy access to lock-guarded attributes.

An attribute the class itself protects (written at least once under
``with self._lock:`` — Condition variables alias into their underlying
lock, see tools/analyze/concurrency.py) must be protected *everywhere it
can race*. Flagged, in functions reachable from a thread entry point
(``threading.Thread(target=...)``, ``Thread``/``Process`` subclasses'
``run``, ``executor.submit``, signal callbacks — signal handlers
interleave with the interrupted code exactly like a thread):

- reads or writes of a guarded ``self.<attr>`` without the guarding lock
  held (``unguarded-access``);
- compound check-then-act: an ``if``/``while`` tests a guarded attribute
  and its body mutates it, with the lock held separately on each side —
  each access is individually locked but the compound is not atomic
  (``check-then-act``);
- cross-object access to another class's guarded attribute
  (``engine._queue.some_counter`` when ``some_counter`` is guarded
  inside ``BatchQueue``) without that object's lock.

Suppress provably single-threaded cases with ``# noqa: PTA006 -- <why
no second thread can observe this attribute>``.
"""
from __future__ import annotations

import ast
from typing import List

from .base import Rule
from ..concurrency import ConcurrencyModel, attr_accesses, nodes_under
from ..core import Finding, Project, dotted_name

_SKIP_METHODS = {"__init__", "__new__", "__del__"}


def _via(fi) -> str:
    if fi.thread_root_via is not None:
        return f"[thread entry: {fi.thread_root_via}]"
    return f"[thread-reachable via {fi.thread_reachable_from}]"


class LockDisciplineRule(Rule):
    code = "PTA006"
    name = "lock-discipline"
    description = ("reads/writes of lock-guarded attributes without the "
                   "lock held, in thread-reachable code")
    severity = "error"

    def finalize(self, project: Project) -> List[Finding]:
        graph = project.callgraph
        model = ConcurrencyModel(graph)
        findings: List[Finding] = []
        for fi in graph.thread_reachable():
            if fi.name in _SKIP_METHODS:
                continue
            findings.extend(self._check_function(graph, model, fi))
        return findings

    def _check_function(self, graph, model, fi) -> List[Finding]:
        sf = fi.file
        cl = model.locks_for(fi.cls)
        hm = model.held_map(fi)
        via = _via(fi)
        findings: List[Finding] = []
        accesses = attr_accesses(fi)

        # -- check-then-act: test and mutation locked separately ------------
        subsumed = set()   # access nodes explained by a check-then-act
        if cl is not None:
            for stmt in self._own_stmts(fi.node):
                if not isinstance(stmt, (ast.If, ast.While)):
                    continue
                held_at = hm.get(id(stmt), frozenset())
                test_ids = nodes_under(stmt.test)
                body_ids = nodes_under(*(stmt.body + stmt.orelse))
                for attr, groups in cl.guarded.items():
                    if any(f"self.{g}" in held_at for g in groups):
                        continue   # whole statement inside the lock: atomic
                    t_reads = [a for a in accesses
                               if a.attr == attr and id(a.node) in test_ids
                               and self._is_self(a)]
                    b_writes = [a for a in accesses
                                if a.attr == attr and a.is_write
                                and id(a.node) in body_ids
                                and self._is_self(a)]
                    if not t_reads or not b_writes:
                        continue
                    relocked = [a for a in b_writes
                                if any(f"self.{g}" in
                                       hm.get(id(a.node), frozenset())
                                       for g in groups)]
                    if not relocked:
                        continue   # both sides unguarded: plain findings
                    lock = sorted(groups)[0]
                    kind = ("while" if isinstance(stmt, ast.While)
                            else "if")
                    findings.append(sf.finding(
                        self.code, stmt,
                        f"check-then-act on `self.{attr}` (guarded by "
                        f"`self.{lock}` in `{fi.cls.name}`): the `{kind}` "
                        f"test and the mutation hold the lock separately, "
                        f"so the attribute can change between them — hoist "
                        f"the test inside the locked block {via}",
                        severity=self.severity))
                    for a in t_reads:
                        subsumed.add(id(a.node))

        # -- plain unguarded access ------------------------------------------
        for acc in accesses:
            if id(acc.node) in subsumed:
                continue
            held = hm.get(id(acc.node), frozenset())
            if self._is_self(acc):
                if cl is None or acc.attr not in cl.guarded:
                    continue
                groups = cl.guarded[acc.attr]
                if any(f"self.{g}" in held for g in groups):
                    continue
                lock = sorted(groups)[0]
                action = "written" if acc.is_write else "read"
                findings.append(sf.finding(
                    self.code, acc.node,
                    f"`self.{acc.attr}` is guarded by `self.{lock}` "
                    f"elsewhere in `{fi.cls.name}` but {action} here "
                    f"without it {via}",
                    severity=self.severity))
            else:
                findings.extend(self._cross_class(graph, model, fi, acc,
                                                  held, via))
        return findings

    def _cross_class(self, graph, model, fi, acc, held, via) -> List[Finding]:
        recv = dotted_name(acc.base)
        if not recv or "?" in recv or recv in ("cls",):
            return []
        owners = graph.base_classes_of(fi, acc.base)
        out: List[Finding] = []
        for ci in owners:
            if acc.attr in ci.methods:       # property/method, not data
                continue
            ocl = model.locks_for(ci)
            if ocl is None or acc.attr not in ocl.guarded:
                continue
            groups = ocl.guarded[acc.attr]
            if any(f"{recv}.{g}" in held for g in groups):
                continue
            lock = sorted(groups)[0]
            action = "written" if acc.is_write else "read"
            out.append(fi.file.finding(
                self.code, acc.node,
                f"`{recv}.{acc.attr}` is lock-guarded inside "
                f"`{ci.name}` (by `{lock}`) but {action} here without "
                f"holding it — expose it through a locked property "
                f"instead {via}",
                severity=self.severity))
        return out

    @staticmethod
    def _is_self(acc) -> bool:
        return isinstance(acc.base, ast.Name) and acc.base.id == "self"

    @staticmethod
    def _own_stmts(func_node):
        stack = list(ast.iter_child_nodes(func_node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))


RULE = LockDisciplineRule()
