"""Minimal rule interface: per-file visit plus a project-wide finalize."""
from __future__ import annotations

from typing import List

from ..core import Finding, Project, SourceFile


class Rule:
    code: str = "PTA000"
    name: str = "base"
    description: str = ""
    #: default severity for this rule's findings ("error" | "warning");
    #: individual findings may override via SourceFile.finding(severity=...)
    severity: str = "error"
    #: "ast" rules run by default (fast, stdlib-only); "trace" rules
    #: compile code under JAX_PLATFORMS=cpu and only run when selected
    #: explicitly via --only/--rule (see tools/analyze/trace/)
    tier: str = "ast"

    def visit_file(self, sf: SourceFile, project: Project) -> List[Finding]:
        return []

    def finalize(self, project: Project) -> List[Finding]:
        return []
