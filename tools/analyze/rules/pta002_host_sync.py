"""PTA002: host synchronization inside hot-path directories.

XLA only fuses what it can see in one program; a device->host round-trip
(``.numpy()``, ``.item()``, ``np.asarray`` on device values,
``block_until_ready``) in per-op or per-step code serializes the pipeline
and breaks fusion across the sync point (cf. arxiv 2301.13062 on
fusion-breaking host round-trips). ROADMAP's "as fast as the hardware
allows" means the op library, the optimizers and the training loop must
stay sync-free except where semantics *require* a concrete value (shape
arguments, dygraph control flow, end-of-step metric reporting).

Scope: ``paddle_tpu/ops/``, ``paddle_tpu/optimizer/``, ``paddle_tpu/amp/``
and the hapi training loop. Intentional syncs carry
``# noqa: PTA002 -- <why a concrete value is semantically required>``.
"""
from __future__ import annotations

import ast
from typing import List

from .base import Rule
from ..core import (Finding, Project, SourceFile, dotted_name,
                    is_static_host_expr)

HOT_PREFIXES = (
    "paddle_tpu/ops/",
    "paddle_tpu/optimizer/",
    "paddle_tpu/amp/",
    "paddle_tpu/hapi/model.py",
    # the sentinel's hot half: probe + policy run inside every guarded
    # optimizer step (its quarantine/rollback modules are cold anomaly
    # paths where host copies are deliberate)
    "paddle_tpu/sentinel/guard.py",
    "paddle_tpu/sentinel/policy.py",
    # LLM serving decode tick: every token of every request flows through
    # here, so an accidental sync multiplies by tokens/sec. The two
    # sanctioned fetches (per-tick token vector, admission-time first
    # token) carry noqa justifications.
    "paddle_tpu/serving/llm/",
    # redundant with the parent prefix, listed so the paged-KV tick
    # (block-table updates run every token) stays covered even if the
    # parent entry is ever narrowed
    "paddle_tpu/serving/llm/paged/",
    # replica router dispatch path: submit/_pick run per request and the
    # health sweep runs continuously; a host sync here stalls admission
    # for every replica at once
    "paddle_tpu/serving/router.py",
    "paddle_tpu/serving/replica.py",
    # the telemetry layer sits INSIDE every hot path above (span enter/
    # exit runs per step / per tick) — a host sync here taxes everything
    "paddle_tpu/observability/",
    # the async checkpointer's save() runs on the step path by design —
    # its whole value is that the fetch and the file I/O happen elsewhere.
    # Besides the device-fetch checks, this file gets the blocking-I/O
    # sub-check below; writer-thread internals carry noqa justifications.
    "paddle_tpu/incubate/checkpoint/async_ckpt.py",
    # quantized hot paths (docs/quantization.md): Int8Linear.forward runs
    # per serving request and PTQ observers run per training batch — a
    # host sync in either multiplies by step rate
    "paddle_tpu/quantization/",
    # compressed gradient allreduce runs once per optimizer step over
    # every gradient byte; eager group bookkeeping carries noqa
    # justifications
    "paddle_tpu/distributed/collective.py",
    # fleet control plane (autoscaler / hot-swap / replay): by contract it
    # adds ZERO host syncs to serving hot paths — all reads are registry
    # snapshots. The one sanctioned copy (the swap rollback snapshot)
    # carries a noqa justification.
    "paddle_tpu/serving/fleet/",
    # zero-loss serving (redundant with the parent prefix, listed so the
    # migration plane stays covered even if the parent entry is ever
    # narrowed): SequenceJournal.note runs once per decode tick — it must
    # stay an O(1) reference enqueue — and the page fetch in the export
    # path is a sanctioned once-per-migration transfer carrying a noqa
    # justification at the pool read site
    "paddle_tpu/serving/fleet/migrate.py",
    # host-loss control plane: watchdog arm/disarm runs inside every
    # guarded train step and the heartbeat sender's notify_step is on the
    # same path — the acceptance contract is zero additional host syncs
    # per step (clock reads + lock sections only; sockets live on the
    # beacon thread, never the step path)
    "paddle_tpu/distributed/elastic_runtime/",
)

SYNC_METHODS = {"numpy", "item", "tolist", "block_until_ready"}
NP_MATERIALIZERS = {"asarray", "array", "ascontiguousarray", "copy"}

#: files where *blocking file I/O* is itself a hot-path finding (the async
#: checkpointer promises an I/O-free step path); dotted call -> why
BLOCKING_IO_FILES = ("paddle_tpu/incubate/checkpoint/async_ckpt.py",)
BLOCKING_IO_CALLS = {
    ("os", "replace"), ("os", "fsync"), ("os", "makedirs"),
    ("os", "remove"), ("os", "rename"), ("os", "open"),
    ("shutil", "rmtree"),
    ("np", "savez"), ("numpy", "savez"),
    ("np", "savez_compressed"), ("numpy", "savez_compressed"),
    ("time", "sleep"),
}


def _is_static_literal(node: ast.AST) -> bool:
    """Provably-host expressions (literals, ``.shape`` reads, ``len()``
    results, arithmetic over those) can't be device values — the shared
    static-shape-numpy heuristic from core (no local-name context at this
    per-file walk, so only syntactically-evident static values pass)."""
    return is_static_host_expr(node)


class HostSyncRule(Rule):
    code = "PTA002"
    name = "host-sync-in-hot-path"
    description = ("device->host syncs (.numpy()/.item()/np.asarray/"
                   "block_until_ready) in ops/, optimizer/, amp/ and the "
                   "training loop")

    def visit_file(self, sf: SourceFile, project: Project) -> List[Finding]:
        if not sf.relpath.startswith(HOT_PREFIXES):
            return []
        check_io = sf.relpath in BLOCKING_IO_FILES
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if check_io:
                io_name = None
                if isinstance(f, ast.Name) and f.id == "open":
                    io_name = "open"
                elif (isinstance(f, ast.Attribute)
                        and (dotted_name(f.value), f.attr)
                        in BLOCKING_IO_CALLS):
                    io_name = f"{dotted_name(f.value)}.{f.attr}"
                if io_name is not None:
                    findings.append(sf.finding(
                        self.code, node,
                        f"{io_name}() is blocking I/O in the async "
                        f"checkpointer — the step-path save() must stay "
                        f"I/O-free; writer-thread calls need "
                        f"`# noqa: PTA002 -- reason`"))
                    continue
            if isinstance(f, ast.Attribute):
                if f.attr == "block_until_ready":
                    findings.append(sf.finding(
                        self.code, node,
                        "block_until_ready() stalls the dispatch pipeline"))
                elif f.attr in SYNC_METHODS and not node.args:
                    findings.append(sf.finding(
                        self.code, node,
                        f".{f.attr}() is a device->host sync in a hot path "
                        f"— hoist it out of the per-step path or justify "
                        f"with `# noqa: PTA002 -- reason`"))
                else:
                    base = dotted_name(f.value)
                    if (base in ("np", "numpy")
                            and f.attr in NP_MATERIALIZERS
                            and node.args
                            and not _is_static_literal(node.args[0])):
                        findings.append(sf.finding(
                            self.code, node,
                            f"np.{f.attr}() on a possibly-device value "
                            f"forces a host transfer (use jnp.{f.attr} to "
                            f"stay on device)"))
        return findings


RULE = HostSyncRule()
