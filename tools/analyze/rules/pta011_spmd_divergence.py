"""PTA011: SPMD collective divergence lint.

A multi-host SPMD program deadlocks the moment one rank issues a
collective its peers never reach: everyone else parks in the ring/
all-reduce and the job hangs until the elastic watchdog (PR 16) kills it
at runtime — minutes into a run instead of seconds at analysis time.
This rule walks the collective call graph (``callgraph.py``'s collective
walk: the ``lax.psum``/``ppermute``/... vocabulary, the
``distributed/collective.py`` wrappers, and every function they are
reachable from over precise edges) and flags the four static shapes of
that bug:

- **rank-gated collective** (error): a collective reachable only under
  rank-/process-dependent control flow — ``if jax.process_index() ==
  0:``, ``if dist.get_rank() == 0:``, or a test over an env-derived rank
  variable (``PADDLE_TRAINER_ID``/``RANK``). The gated ranks issue the
  collective; the rest never join it.
- **swallowed collective** (error): a collective inside a ``try:`` whose
  ``except`` continues execution. One rank catches (an OOM, a
  preemption), returns, and its peers hang in the collective forever —
  the except must re-raise so the whole cohort fails together.
- **axis-name hygiene** (error): a literal axis name passed to a
  collective that is not declared by the enclosing ``shard_map``'s mesh
  (resolved through the symbol tables) nor anywhere in the project — a
  typo that surfaces as an unbound-axis trace error at best, a
  wrong-axis reduction at worst.
- **per-host loop trip count** (error): a collective inside a loop whose
  iteration count derives from a rank/per-host value — ranks run
  different numbers of collective rounds and the first extra round
  deadlocks.

Traced-value rank reads (``lax.axis_index``) are deliberately NOT rank
sources here: a python ``if`` over a tracer fails at trace time on its
own, and the ``jnp.where(rank == ..., ...)``/``lax.switch`` idioms the
fleet code uses keep every rank inside every collective (uniform
schedule, divergent *data* — exactly right). Deliberately rank-gated
collectives (a sanctioned drain barrier) take a
``# noqa: PTA011 -- reason``.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from .base import Rule
from ..core import (Finding, Project, _binding_target_names, dotted_name,
                    mentions_any_name)

#: host-side calls whose result is this process's rank (last dotted
#: component). ``axis_index`` is excluded on purpose — it is a traced
#: value, not a host value (see module docstring).
RANK_CALL_TAILS = {"process_index", "get_rank", "local_rank", "node_rank",
                   "get_group_rank", "get_rank_from_stage"}

#: substrings of environment-variable names that hold a per-host rank
RANK_ENV_MARKERS = ("RANK", "TRAINER_ID")


def _env_key_is_rank(key: Optional[str]) -> bool:
    return bool(key) and any(m in key.upper() for m in RANK_ENV_MARKERS)


def _rank_source(node: ast.AST) -> Optional[str]:
    """A description of the host-rank read inside ``node``, or None."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            d = dotted_name(n.func)
            last = d.rpartition(".")[2]
            if last in RANK_CALL_TAILS:
                return f"`{d}()`"
            if last in ("get", "getenv") and n.args:
                a0 = n.args[0]
                if (isinstance(a0, ast.Constant)
                        and isinstance(a0.value, str)
                        and _env_key_is_rank(a0.value)):
                    return f"env `{a0.value}`"
        elif isinstance(n, ast.Subscript):
            base = dotted_name(n.value)
            if base.endswith("environ"):
                sl = n.slice
                if (isinstance(sl, ast.Constant)
                        and isinstance(sl.value, str)
                        and _env_key_is_rank(sl.value)):
                    return f"env `{sl.value}`"
    return None


def _rank_tainted_names(func_node: ast.AST) -> dict:
    """name -> provenance, for locals transitively bound from a host-rank
    read. Fixpoint over simple bindings (same walker the tracer-taint
    analysis uses), seeded by rank-source expressions."""
    from ..core import walk_own_body
    bindings: List[Tuple[list, ast.AST]] = []
    for node in walk_own_body(func_node):
        if isinstance(node, ast.Assign):
            # `rank, world = process_index(), process_count()`: bind
            # element-wise so `world` does not inherit rank taint
            if (len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Tuple)
                    and isinstance(node.value, ast.Tuple)
                    and len(node.targets[0].elts) == len(node.value.elts)):
                for t, v in zip(node.targets[0].elts, node.value.elts):
                    bindings.append((list(_binding_target_names(t)), v))
                continue
            names = [n for t in node.targets
                     for n in _binding_target_names(t)]
            bindings.append((names, node.value))
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            bindings.append(
                (list(_binding_target_names(node.target)), node.value))
        elif isinstance(node, ast.AugAssign):
            bindings.append(
                (list(_binding_target_names(node.target)), node.value))
        elif isinstance(node, ast.NamedExpr):
            bindings.append(
                (list(_binding_target_names(node.target)), node.value))
    tainted: dict = {}
    for _ in range(len(bindings) + 1):
        grew = False
        for names, rhs in bindings:
            if all(n in tainted for n in names):
                continue
            src = _rank_source(rhs)
            if src is None and mentions_any_name(rhs, set(tainted)):
                hit = next((n.id for n in ast.walk(rhs)
                            if isinstance(n, ast.Name)
                            and n.id in tainted), None)
                src = tainted.get(hit)
            if src is not None:
                for n in names:
                    tainted.setdefault(n, src)
                grew = True
        if not grew:
            break
    return tainted


def _handler_continues(handler: ast.ExceptHandler) -> bool:
    """True when the except body can fall through (no unconditional
    re-raise as its last statement)."""
    body = handler.body
    return not (body and isinstance(body[-1], ast.Raise))


def _swallowing_handler(node: ast.Try) -> Optional[ast.ExceptHandler]:
    for h in node.handlers:
        if _handler_continues(h):
            return h
    return None


def _iter_guarded_calls(stmts, guards):
    """Yield (call, guards-at-call) walking a statement list, tracking the
    enclosing rank-gated / swallowing-try / rank-loop contexts. Stops at
    nested function/class defs (they are analyzed as their own units)."""
    for st in stmts:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue
        if isinstance(st, ast.If):
            g = st._pta_rank_guard if hasattr(st, "_pta_rank_guard") \
                else None
            inner = guards + ([g] if g else [])
            yield from _iter_guarded_calls(st.body, inner)
            yield from _iter_guarded_calls(st.orelse, inner)
            continue
        if isinstance(st, ast.While):
            g = getattr(st, "_pta_rank_guard", None)
            inner = guards + ([g] if g else [])
            yield from _iter_guarded_calls(st.body, inner)
            yield from _iter_guarded_calls(st.orelse, guards)
            continue
        if isinstance(st, (ast.For, ast.AsyncFor)):
            g = getattr(st, "_pta_rank_guard", None)
            inner = guards + ([g] if g else [])
            yield from _iter_guarded_calls(st.body, inner)
            yield from _iter_guarded_calls(st.orelse, guards)
            continue
        if isinstance(st, ast.Try):
            h = _swallowing_handler(st)
            g = (("swallow", st, h) if h is not None else None)
            inner = guards + ([g] if g else [])
            yield from _iter_guarded_calls(st.body, inner)
            for handler in st.handlers:
                yield from _iter_guarded_calls(handler.body, guards)
            yield from _iter_guarded_calls(st.orelse, inner)
            yield from _iter_guarded_calls(st.finalbody, guards)
            continue
        if isinstance(st, (ast.With, ast.AsyncWith)):
            yield from _iter_guarded_calls(st.body, guards)
            for item in st.items:
                for n in ast.walk(item.context_expr):
                    if isinstance(n, ast.Call):
                        yield n, guards
            continue
        # plain statement: every call in it runs under the current
        # guards. Prune def/lambda subtrees — their bodies do not
        # execute at this statement.
        stack = [st]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                yield n, guards
            stack.extend(ast.iter_child_nodes(n))


def _collective_axis_args(call: ast.Call) -> List[str]:
    """Literal axis-name strings this collective call names. Positional
    convention: lax collectives take the axis as the 2nd argument."""
    out: List[str] = []
    cand = list(call.args[1:2])
    for kw in call.keywords:
        if kw.arg in ("axis", "axis_name"):
            cand.append(kw.value)
    for c in cand:
        if isinstance(c, ast.Constant) and isinstance(c.value, str):
            out.append(c.value)
        elif isinstance(c, (ast.Tuple, ast.List)):
            out.extend(e.value for e in c.elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, str))
    return out


class SpmdDivergenceRule(Rule):
    code = "PTA011"
    name = "spmd-divergence"
    description = ("collectives under rank-dependent control flow, "
                   "inside exception-swallowing try blocks, with "
                   "undeclared axis names, or in per-host-length loops "
                   "— each one a multi-host deadlock or wrong-axis "
                   "reduction")
    severity = "error"

    def finalize(self, project: Project) -> List[Finding]:
        graph = project.callgraph
        findings: List[Finding] = []
        for fi in graph.functions:
            if isinstance(fi.node, (ast.Lambda,)):
                continue
            findings.extend(self._check_function(graph, fi))
        findings.sort(key=lambda f: (f.path, f.line))
        return findings

    # -- per-function analysis ------------------------------------------------
    def _check_function(self, graph, fi) -> List[Finding]:
        sf = fi.file
        node = fi.node
        rank_names = _rank_tainted_names(node)

        def rank_reason(test) -> Optional[str]:
            src = _rank_source(test)
            if src is not None:
                return src
            hit = next((n.id for n in ast.walk(test)
                        if isinstance(n, ast.Name) and n.id in rank_names),
                       None)
            if hit is not None:
                return f"`{hit}` (from {rank_names[hit]})"
            return None

        # annotate control statements with their guard kind before the
        # guarded walk reads them. Always overwrite/clear: ast.walk also
        # touches nested defs, and those are re-annotated (with their own
        # taint sets) when their FuncInfo is processed later.
        for st in ast.walk(node):
            if isinstance(st, (ast.If, ast.While)):
                r = rank_reason(st.test)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                r = rank_reason(st.iter)
            else:
                continue
            if r:
                kind = "loop" if isinstance(st, (ast.For, ast.AsyncFor)) \
                    else "rank"
                st._pta_rank_guard = (kind, st, r)
            elif hasattr(st, "_pta_rank_guard"):
                del st._pta_rank_guard

        out: List[Finding] = []
        seen = set()
        for call, guards in _iter_guarded_calls(node.body, []):
            via = graph.collective_call_via(fi, call)
            if via is None:
                continue
            self._check_axes(graph, fi, call, via, out)
            if not guards or id(call) in seen:
                continue
            seen.add(id(call))
            kind, gnode, detail = guards[-1]
            if kind == "rank":
                stmt = ("if" if isinstance(gnode, ast.If) else "while")
                out.append(sf.finding(
                    self.code, call,
                    f"collective {via} is reachable only under rank-"
                    f"dependent control flow (`{stmt}` at line "
                    f"{gnode.lineno} tests {detail}) — ranks that skip "
                    f"the branch never join the collective and the job "
                    f"deadlocks; issue it unconditionally and mask with "
                    f"`jnp.where` instead",
                    anchor=f"spmd:rank-gated:{fi.qualname}:"
                           f"{sf.line_text(call.lineno)}"))
            elif kind == "loop":
                out.append(sf.finding(
                    self.code, call,
                    f"collective {via} runs inside a loop whose trip "
                    f"count derives from a per-host value ({detail}, "
                    f"line {gnode.lineno}) — ranks run different "
                    f"numbers of collective rounds and the first extra "
                    f"round deadlocks; make the trip count a global "
                    f"constant",
                    anchor=f"spmd:host-loop:{fi.qualname}:"
                           f"{sf.line_text(call.lineno)}"))
            elif kind == "swallow":
                handler = guards[-1][2]
                htype = (dotted_name(handler.type)
                         if handler.type is not None else "bare")
                out.append(sf.finding(
                    self.code, call,
                    f"collective {via} sits in a `try:` whose `except "
                    f"{htype}` (line {handler.lineno}) continues "
                    f"execution — one rank swallows the failure and "
                    f"returns while its peers hang in the collective; "
                    f"re-raise so the whole cohort fails together",
                    anchor=f"spmd:swallowed:{fi.qualname}:"
                           f"{sf.line_text(call.lineno)}"))
        return out

    def _check_axes(self, graph, fi, call: ast.Call, via: str,
                    out: List[Finding]) -> None:
        # only direct collective calls carry an axis argument we can read
        d = dotted_name(call.func)
        from ..callgraph import LAX_COLLECTIVES
        if d.rpartition(".")[2] not in LAX_COLLECTIVES:
            return
        axes = _collective_axis_args(call)
        if not axes:
            return
        wrap = graph.shard_map_axes.get(id(fi))
        declared, where = None, ""
        if wrap is not None and wrap[0] is not None:
            declared = set(wrap[0])
            where = f"the enclosing {wrap[1]} (mesh axes {wrap[0]})"
        elif graph.declared_axes:
            declared = set(graph.declared_axes)
            where = "any mesh/PartitionSpec declaration in the project"
        if declared is None:
            return
        for ax in axes:
            if ax not in declared:
                out.append(fi.file.finding(
                    self.code, call,
                    f"collective `{d}` names axis '{ax}', which is not "
                    f"declared by {where} — a typo here is an unbound-"
                    f"axis trace error at best, a wrong-axis reduction "
                    f"at worst",
                    anchor=f"spmd:axis:{fi.qualname}:{ax}"))


RULE = SpmdDivergenceRule()
