"""PTA001: host-forcing operations inside jit-reachable functions.

Inside a traced region (anything reachable from ``jax.jit`` / ``pjit`` /
``to_static`` — see callgraph.py) a value is a Tracer, and forcing it to a
concrete host value either raises ``TracerError`` at runtime or, worse,
silently inserts a device->host round-trip that splits the XLA program
(cf. the LazyTensor eager/compiled boundary analysis, arxiv 2102.13267).

Flagged inside jit-reachable functions:

- ``x.item()`` / ``x.numpy()`` / ``x.tolist()`` / ``x.block_until_ready()``
- ``np.*(x)`` — numpy materializes its arguments (allowlist for the
  handful of np attributes that are type-level, not value-level)
- ``bool(x)`` / ``float(x)`` / ``int(x)`` where ``x`` derives from a
  function parameter (parameters are the traced values in a jitted fn)
- ``if`` / ``while`` whose test contains any of the above (branching on a
  traced value — the classic tracer leak)

Suppress intentional cases with ``# noqa: PTA001 -- <why this value is
static at trace time>``.
"""
from __future__ import annotations

import ast
from typing import List, Set

from .base import Rule
from ..core import (Finding, Project, SourceFile, dotted_name,
                    is_static_host_expr, mentions_any_name,
                    static_local_names, tainted_local_names)

HOST_METHODS = {"item", "numpy", "tolist", "block_until_ready"}

#: np.<attr> that never materialize array values
NP_SAFE_ATTRS = {
    "dtype", "issubdtype", "result_type", "promote_types", "can_cast",
    "finfo", "iinfo", "errstate", "ndim", "newaxis", "pi", "e", "inf",
    "nan", "float16", "float32", "float64", "int8", "int16", "int32",
    "int64", "uint8", "bool_", "generic", "ndarray", "integer",
    "floating", "complexfloating", "inexact", "number",
}

CASTS = {"bool", "float", "int"}


def _param_names(func_node) -> Set[str]:
    a = func_node.args
    names = {x.arg for x in (a.posonlyargs + a.args + a.kwonlyargs)}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    names.discard("self")
    names.discard("cls")
    return names


def _host_forcing(node: ast.AST, params: Set[str],
                  static_names=frozenset(),
                  tainted=None) -> str:
    """Return a description if ``node`` is a host-forcing call, else ''."""
    if not isinstance(node, ast.Call):
        return ""
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr in HOST_METHODS and not node.args:
            return f".{f.attr}() host-materializes a traced value"
        base = dotted_name(f.value)
        if base in ("np", "numpy") and f.attr not in NP_SAFE_ATTRS:
            # static-shape-numpy heuristic: np math is only host-forcing
            # when an argument may hold a *traced* value — i.e. derives
            # from the function's parameters (taint) and is not a
            # provably-static host expression (.shape/.ndim reads,
            # len()/int() results, arithmetic over those). Closure
            # variables are python constants under trace, so
            # `np.sqrt(ar)` over an enclosing-scope aspect-ratio list and
            # `np.sqrt(self.head_dim)` stay clean, while `np.asarray(x)`
            # on a parameter still flags.
            taint_set = params if tainted is None else tainted
            def _risky(a):
                return (not is_static_host_expr(a, static_names)
                        and mentions_any_name(a, taint_set))
            if (any(_risky(a) for a in node.args)
                    or any(_risky(k.value) for k in node.keywords)):
                return (f"np.{f.attr}() materializes its arguments on host "
                        f"(use jnp inside traced code)")
            return ""
    elif isinstance(f, ast.Name) and f.id in CASTS and len(node.args) == 1:
        arg = node.args[0]
        if not isinstance(arg, ast.Constant):
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Name) and sub.id in params:
                    return (f"{f.id}() on parameter-derived value forces a "
                            f"concrete host value under trace")
    return ""


class TracerSafetyRule(Rule):
    code = "PTA001"
    name = "tracer-safety"
    description = ("host-forcing calls / branches inside functions "
                   "reachable from jit, pjit or to_static")

    def finalize(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        graph = project.callgraph
        for fi in graph.reachable():
            sf = fi.file
            params = _param_names(fi.node)
            statics = static_local_names(fi.node, params)
            tainted = tainted_local_names(fi.node, params, statics)
            via = (f" [jit-reachable via {fi.reachable_from}]"
                   if fi.reachable_from != fi.qualname
                   else " [jit entry point]")
            flagged_calls = set()

            # branch tests first: more specific message, dedup the call
            for node in self._own_body(fi.node):
                if isinstance(node, (ast.If, ast.While)):
                    for sub in ast.walk(node.test):
                        why = _host_forcing(sub, params, statics, tainted)
                        if why:
                            flagged_calls.add(id(sub))
                            kind = ("while" if isinstance(node, ast.While)
                                    else "if")
                            findings.append(sf.finding(
                                self.code, node,
                                f"`{kind}` branches on a host-forced "
                                f"value in `{fi.qualname}`: {why}{via}"))
                            break
            for node in self._own_body(fi.node):
                if id(node) in flagged_calls:
                    continue
                why = _host_forcing(node, params, statics, tainted)
                if why:
                    findings.append(sf.finding(
                        self.code, node,
                        f"{why} in jit-reachable `{fi.qualname}`{via}"))
        return findings

    @staticmethod
    def _own_body(func_node):
        stack = list(ast.iter_child_nodes(func_node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))


RULE = TracerSafetyRule()
