"""PTA008: recompile-risk lint — the static half of the trace tier.

``jax.jit`` retraces whenever the cache key changes: a new input shape, a
new static-argument value, or a brand-new wrapped function object. The
trace tier's PTA010 sentinel *measures* retraces; this rule flags the
source patterns that cause them (the bug class PR 6 fixed by hand in the
LLM decode path — see docs/static_analysis.md "Trace-level analysis"):

- **shape-branch**: an ``if``/``while`` in a jit *entry* function whose
  test reads a traced parameter's ``.shape``/``.ndim``/``len()`` — every
  distinct shape traces a new executable, so shape-dependent control flow
  in a step function multiplies executables under batch churn (warning;
  rank dispatch in shared helpers deeper in the call tree is deliberate
  and not flagged). A ``while`` on shapes anywhere jit-reachable is
  flagged too: it unrolls at trace time.
- **scalar-feed loop**: a host-side ``for``/``while`` loop that both
  calls a jitted entry function and coerces device values
  (``.item()``/``int()``/``float()``) per iteration — the per-token sync
  pattern (warning).
- **jit-in-loop**: ``jax.jit(...)`` (or a ``@jit``-decorated ``def``)
  inside a loop body — each iteration creates a fresh function object
  with its own trace cache, so nothing is ever reused (error).
- **static-argnums hygiene**: computed ``static_argnums``/
  ``static_argnames`` values, and call sites passing unhashable literals
  (``list``/``dict``/``set``) in a static position — unhashables raise
  at the cache lookup; a fresh object per call retraces every call
  (error).

Suppress intentional cases with ``# noqa: PTA008 -- <why the trace-cache
key is stable here>``.
"""
from __future__ import annotations

import ast
from typing import List, Set

from .base import Rule
from ..core import (Finding, Project, SourceFile, dotted_name,
                    tainted_local_names, walk_own_body)

#: callables whose invocation inside a loop body builds a new traced
#: function object per iteration
_JIT_BUILDERS = {"jit", "pjit"}

_COERCIONS = {"int", "float"}

_SHAPE_ATTRS = {"shape", "ndim"}


def _param_names(func_node) -> Set[str]:
    a = func_node.args
    names = {x.arg for x in (a.posonlyargs + a.args + a.kwonlyargs)}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    names.discard("self")
    names.discard("cls")
    return names


def _shape_read_on(node: ast.AST, tainted) -> str:
    """'x.shape'-style description if ``node`` reads a traced value's
    shape, else ''."""
    if (isinstance(node, ast.Attribute) and node.attr in _SHAPE_ATTRS
            and isinstance(node.value, ast.Name)
            and node.value.id in tainted):
        return f"{node.value.id}.{node.attr}"
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == "len" and len(node.args) == 1
            and isinstance(node.args[0], ast.Name)
            and node.args[0].id in tainted):
        return f"len({node.args[0].id})"
    return ""


def _is_coercion(node: ast.AST) -> str:
    """Definite device->host reads (`.item()`/`.numpy()`/`.tolist()`).
    Bare ``float()``/``int()`` are NOT flagged here: on host-side loops
    they usually coerce python config values; the traced-value variants
    are PTA001's cast check."""
    if not isinstance(node, ast.Call):
        return ""
    f = node.func
    if isinstance(f, ast.Attribute) and not node.args \
            and f.attr in ("item", "numpy", "tolist"):
        return f".{f.attr}()"
    if isinstance(f, ast.Name) and f.id in _COERCIONS \
            and len(node.args) == 1:
        inner = node.args[0]
        # float(x.item()) / int(np.asarray(loss)) — coercion of an
        # explicit materialization
        if isinstance(inner, ast.Call) and _is_coercion(inner):
            return f"{f.id}()"
    return ""


def _single_pass_loop(loop) -> bool:
    """`while True: ... break` — the labeled-break/"single-pass try"
    idiom; the body runs at most once, so per-iteration churn does not
    apply."""
    if not isinstance(loop, ast.While):
        return False
    test_true = (isinstance(loop.test, ast.Constant)
                 and loop.test.value is True)
    return test_true and isinstance(loop.body[-1],
                                    (ast.Break, ast.Return, ast.Raise))


def _static_positions(call: ast.Call):
    """(argnums, ok) for a jit/pjit call's static_argnums keyword; ok is
    False when the value is computed (not a literal int / tuple-of-int)."""
    for kw in call.keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        v = kw.value
        if isinstance(v, ast.Constant):
            if isinstance(v.value, int) and kw.arg == "static_argnums":
                return [v.value], True
            return [], isinstance(v.value, (int, str))
        if isinstance(v, (ast.Tuple, ast.List)):
            if all(isinstance(e, ast.Constant) for e in v.elts):
                if kw.arg == "static_argnums":
                    return [e.value for e in v.elts
                            if isinstance(e.value, int)], True
                return [], True
            return [], False
        if isinstance(v, ast.Name):
            # a named module-level constant — unverifiable but common
            return [], True
        return [], False
    return [], True


def _is_unhashable_literal(node: ast.AST) -> str:
    if isinstance(node, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(node, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "set"
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("list", "dict", "set"):
        return node.func.id
    return ""


class RecompileRiskRule(Rule):
    code = "PTA008"
    name = "recompile-risk"
    description = ("patterns that churn the jit trace cache: shape-"
                   "dependent branching in entry functions, per-iteration "
                   "host coercions feeding jitted calls, jit() inside "
                   "loops, unhashable/computed static_argnums")
    severity = "warning"

    # -- per-file checks: jit-in-loop + static_argnums hygiene ---------------

    def visit_file(self, sf: SourceFile, project: Project) -> List[Finding]:
        if sf.tree is None:
            return []
        findings: List[Finding] = []
        static_fns = {}  # local name -> static argnum positions
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.For, ast.While, ast.AsyncFor)) \
                    and not _single_pass_loop(node):
                findings.extend(self._check_loop_body(sf, node))
            if not isinstance(node, ast.Call):
                continue
            tail = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
            if tail not in _JIT_BUILDERS:
                continue
            positions, ok = _static_positions(node)
            if not ok:
                findings.append(sf.finding(
                    self.code, node,
                    f"computed static_argnums/static_argnames on "
                    f"`{dotted_name(node.func)}` — the static positions "
                    f"must be literal so readers (and this lint) can see "
                    f"which arguments key the trace cache",
                    severity="error"))
        # second pass: map `g = jax.jit(f, static_argnums=...)` to call
        # sites passing unhashable literals in a static slot
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                tail = (dotted_name(node.value.func) or "").rsplit(
                    ".", 1)[-1]
                if tail in _JIT_BUILDERS:
                    positions, ok = _static_positions(node.value)
                    if ok and positions:
                        static_fns[node.targets[0].id] = positions
        if static_fns:
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in static_fns):
                    continue
                for pos in static_fns[node.func.id]:
                    if pos < len(node.args):
                        kind = _is_unhashable_literal(node.args[pos])
                        if kind:
                            findings.append(sf.finding(
                                self.code, node.args[pos],
                                f"unhashable {kind} passed in static "
                                f"position {pos} of `{node.func.id}` — "
                                f"static arguments key the trace cache "
                                f"and must be hashable (use a tuple or "
                                f"a frozen dataclass)",
                                severity="error"))
        return findings

    def _check_loop_body(self, sf: SourceFile, loop) -> List[Finding]:
        findings: List[Finding] = []
        body = loop.body + getattr(loop, "orelse", [])
        for stmt in body:
            for node in ast.walk(stmt):
                jit_site = None
                if isinstance(node, ast.Call):
                    tail = (dotted_name(node.func) or "").rsplit(
                        ".", 1)[-1]
                    if tail in _JIT_BUILDERS and (
                            node.args or node.keywords):
                        jit_site = dotted_name(node.func)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        d = dec.func if isinstance(dec, ast.Call) else dec
                        if (dotted_name(d) or "").rsplit(
                                ".", 1)[-1] in _JIT_BUILDERS:
                            jit_site = f"@{dotted_name(d)} def {node.name}"
                if jit_site:
                    findings.append(sf.finding(
                        self.code, node,
                        f"`{jit_site}` inside a loop body creates a fresh "
                        f"traced function every iteration — its trace "
                        f"cache is never reused; hoist the jit() out of "
                        f"the loop",
                        severity="error"))
        return findings

    # -- callgraph checks: shape-branch + scalar-feed loops ------------------

    def finalize(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        graph = project.callgraph
        jit_root_names = {fi.qualname for fi in graph.functions
                          if fi.root_via is not None}

        for fi in graph.reachable():
            params = _param_names(fi.node)
            tainted = tainted_local_names(fi.node, params)
            is_root = fi.root_via is not None
            for node in walk_own_body(fi.node):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                reads = [r for sub in ast.walk(node.test)
                         for r in [_shape_read_on(sub, tainted)] if r]
                if not reads:
                    continue
                if isinstance(node, ast.While):
                    findings.append(fi.file.finding(
                        self.code, node,
                        f"`while` on `{reads[0]}` in jit-reachable "
                        f"`{fi.qualname}` unrolls at trace time — each "
                        f"iteration is inlined into the program; use "
                        f"lax.while_loop/fori_loop",
                        severity="error"))
                elif is_root:
                    findings.append(fi.file.finding(
                        self.code, node,
                        f"jit entry `{fi.qualname}` branches on "
                        f"`{reads[0]}` — every distinct input shape "
                        f"traces a new executable; pad/bucket shapes at "
                        f"the boundary or move the dispatch outside the "
                        f"jitted step", severity="warning"))

        # host-side loops that coerce device scalars while driving a
        # jitted callee: the per-token sync pattern
        for fi in graph.functions:
            if fi.reachable_from is not None:
                continue  # inside jit the coercion is PTA001's business
            for loop in walk_own_body(fi.node):
                if not isinstance(loop, (ast.For, ast.While)) \
                        or _single_pass_loop(loop):
                    continue
                calls_jit_root = None
                coercion = None
                for stmt in loop.body:
                    for node in ast.walk(stmt):
                        if isinstance(node, ast.Call):
                            for tgt in graph.callee_targets(
                                    fi, node, precise_only=True):
                                if tgt.qualname in jit_root_names:
                                    calls_jit_root = tgt.qualname
                            c = _is_coercion(node)
                            if c:
                                coercion = (node, c)
                if calls_jit_root and coercion:
                    node, what = coercion
                    findings.append(fi.file.finding(
                        self.code, node,
                        f"loop in `{fi.qualname}` coerces a device value "
                        f"with {what} every iteration while driving "
                        f"jitted `{calls_jit_root}` — each coercion is a "
                        f"host sync on the step path; batch the reads or "
                        f"keep the value on device", severity="warning"))
        return findings


RULE = RecompileRiskRule()
