"""PTA003: silently-swallowed failures in resilience-critical paths.

Absorbed from ``tools/lint_silent_except.py`` (which remains as a thin
shim): the elastic fault-tolerance runtime (docs/fault_tolerance.md)
depends on failures *propagating* — a swallowed exception in the launcher,
the elastic supervisor or the checkpoint layer turns a recoverable crash
into silent state corruption. Rejected, inside CHECKED_DIRS:

- bare ``except:`` handlers
- ``except Exception:`` / ``except BaseException:`` (alone or in a tuple)
  whose body does nothing (only ``pass`` / ``...``)

Catching Exception and then *acting* (logging, re-raising, returning an
explicit sentinel) is fine — the rule targets the do-nothing swallow.
"""
from __future__ import annotations

import ast
from typing import List, Tuple

from .base import Rule
from ..core import Finding, Project, SourceFile

#: directories where a silent swallow is a correctness bug, not a style nit
CHECKED_DIRS = (
    "paddle_tpu/distributed",
    "paddle_tpu/incubate/checkpoint",
    "paddle_tpu/sentinel",
    "paddle_tpu/serving",
    "paddle_tpu/utils",
)

_BROAD = {"Exception", "BaseException"}


def _names_in(expr):
    """Exception-class names referenced by an except clause's type expr."""
    if expr is None:
        return set()
    if isinstance(expr, ast.Name):
        return {expr.id}
    if isinstance(expr, ast.Attribute):
        return {expr.attr}
    if isinstance(expr, ast.Tuple):
        out = set()
        for elt in expr.elts:
            out |= _names_in(elt)
        return out
    return set()


def _body_is_noop(body):
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is Ellipsis):
            continue
        return False
    return True


def iter_offenders(tree: ast.AST) -> List[Tuple[int, str]]:
    """(lineno, message) pairs for every silent-except in ``tree``."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.append((node.lineno,
                        "bare 'except:' swallows everything incl. "
                        "SystemExit"))
        elif _names_in(node.type) & _BROAD and _body_is_noop(node.body):
            out.append((node.lineno,
                        "'except Exception: pass' silently swallows "
                        "failures"))
    return out


class SilentExceptRule(Rule):
    code = "PTA003"
    name = "silent-except"
    description = ("bare/broad do-nothing except handlers in "
                   "resilience-critical paths (launcher, elastic "
                   "supervisor, checkpoint layer)")

    def visit_file(self, sf: SourceFile, project: Project) -> List[Finding]:
        if not any(sf.relpath.startswith(d + "/") or sf.relpath == d
                   for d in CHECKED_DIRS):
            return []
        return [
            sf.finding(self.code, lineno,
                       msg + " (failures in resilience paths must "
                             "propagate; docs/fault_tolerance.md)")
            for lineno, msg in iter_offenders(sf.tree)
        ]


RULE = SilentExceptRule()
