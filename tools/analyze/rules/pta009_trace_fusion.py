"""PTA009: trace-level fusion & host-transfer audit.

Runs every registered auditable entrypoint (``paddle_tpu.core.audit``)
under ``JAX_PLATFORMS=cpu``, captures its jaxpr, and flags program
properties no AST rule can see:

- **host transfer in compiled region** (error): ``device_put``/
  ``pure_callback``/``io_callback`` primitives inside the traced step —
  each one stalls the device stream mid-program, the round-trip PTA002
  can only guess at from source text.
- **large closed-over constant** (warning): a ``while``/``cond``/``scan``
  body capturing a tensor of >= 16K elements as a trace constant — it is
  baked into every executable instead of flowing through as an argument
  or loop carry.
- **donation opportunity** (warning): a train-tagged step compiled
  without ``donate_argnums`` whose inputs are shape/dtype-matched by its
  outputs — the parameter set is double-buffered for no reason.
- **copy-split fusion** (warning): the compiled HLO is more than 20%
  ``copy`` instructions (min 50 instructions) — layout-changing copies
  are splitting what should be fused elementwise chains.

Findings anchor at the ``register_entrypoint`` site with stable
``trace:<name>:<check>`` fingerprints, so they baseline and noqa like any
AST finding. This tier compiles code: it only runs when selected
explicitly (``--only PTA009``).
"""
from __future__ import annotations

from typing import List

from .base import Rule
from ..core import Finding, Project


class TraceFusionRule(Rule):
    code = "PTA009"
    name = "trace-fusion-transfer"
    tier = "trace"
    description = ("trace-level audit of registered entrypoints: host "
                   "transfers inside compiled regions, large constants "
                   "captured by control-flow bodies, missed buffer-"
                   "donation opportunities (runs only via --only)")
    severity = "warning"

    def finalize(self, project: Project) -> List[Finding]:
        from ..trace import get_report
        report = get_report()
        findings: List[Finding] = []
        if report.error:
            findings.append(Finding(
                self.code, "tools/analyze/trace/__init__.py", 1, 0,
                f"trace audit could not run (jax/paddle_tpu import "
                f"failed): {report.error.strip().splitlines()[-1]}",
                anchor="trace:runner:unavailable", severity="error"))
            return findings
        for name, st in sorted(report.entrypoint_stats.items()):
            loc = (st.path or "tools/analyze/trace/__init__.py",
                   st.line or 1)
            if st.error:
                findings.append(Finding(
                    self.code, loc[0], loc[1], 0,
                    f"entrypoint `{name}` failed to build/trace: "
                    f"{st.error.strip().splitlines()[-1]}",
                    anchor=f"trace:{name}:error", severity="error"))
                continue
            for prim in sorted(set(st.transfers)):
                n = st.transfers.count(prim)
                findings.append(Finding(
                    self.code, loc[0], loc[1], 0,
                    f"entrypoint `{name}` has {n} `{prim}` "
                    f"primitive(s) inside its compiled region — a host "
                    f"round-trip on the step path; keep data on device "
                    f"or move the callback outside the jitted step",
                    anchor=f"trace:{name}:transfer:{prim}",
                    severity="error"))
            for lc in st.large_consts:
                findings.append(Finding(
                    self.code, loc[0], loc[1], 0,
                    f"entrypoint `{name}`: a `{lc['control_flow']}` body "
                    f"captures a {lc['dtype']}{lc['shape']} constant "
                    f"({lc['elements']} elements) — baked into every "
                    f"traced executable; pass it as an argument or loop "
                    f"carry instead",
                    anchor=(f"trace:{name}:large-const:"
                            f"{lc['control_flow']}:{lc['elements']}"),
                    severity="warning"))
            instrs = st.hlo.get("instructions", 0)
            copies = st.hlo.get("copies", 0)
            if instrs >= 50 and copies / instrs > 0.20:
                findings.append(Finding(
                    self.code, loc[0], loc[1], 0,
                    f"entrypoint `{name}` compiles to {copies} copy "
                    f"instructions out of {instrs} "
                    f"({100 * copies // instrs}%) — layout-changing "
                    f"copies are splitting fusions; check for transposes/"
                    f"reshapes between elementwise ops",
                    anchor=f"trace:{name}:copy-split",
                    severity="warning"))
            don = st.donation
            if don and don.get("donatable_inputs", 0) > 0:
                mib = don["donatable_bytes"] / (1024 * 1024)
                findings.append(Finding(
                    self.code, loc[0], loc[1], 0,
                    f"train entrypoint `{name}` donates no buffers but "
                    f"{don['donatable_inputs']} of "
                    f"{don['total_inputs']} inputs are shape/dtype-"
                    f"matched by outputs ({mib:.2f} MiB) — pass "
                    f"donate_argnums to reuse them in place",
                    anchor=f"trace:{name}:donation",
                    severity="warning"))
        return findings


RULE = TraceFusionRule()
