"""PTA012: trace-level collective-schedule audit.

For every registered auditable entrypoint, extract the ordered per-rank
collective schedule from the captured jaxpr — (primitive, axis names,
operand shape/dtype, ppermute permutation, all_to_all split/concat dims)
— and verify the SPMD invariants a multi-host mesh depends on:

- **rank-divergent cond** (error): a ``cond``/``switch`` whose branches
  carry different collective schedules. Branch selection can differ per
  rank at runtime, so one rank issues a collective its peers never join
  and the mesh deadlocks — the compiled-program analogue of what PTA011
  flags in source.
- **broken permutation** (error): a ppermute perm with duplicate or
  out-of-range endpoints, or one covering only a strict subset of the
  axis — the uncovered rank never participates while its peers cycle,
  which hangs the ring.
- **all_to_all pairing** (warning): consecutive all_to_alls on the same
  axis whose split/concat dims are not transposes of each other — the
  return trip does not undo the dispatch and tokens land scrambled
  (MoE dispatch/combine is the canonical pair).

The schedule also records estimated **wire bytes** per step (operand
bytes × enclosing scan trip counts), surfaced in the trace report as
``collective_bytes`` so ``check_audit_regression.py`` can gate comm
regressions the same way it gates copy fraction.

Findings anchor at the ``register_entrypoint`` site with stable
``trace:<name>:<check>`` fingerprints, so they baseline and noqa like any
AST finding. This tier compiles code: it only runs when selected
explicitly (``--only PTA012``).
"""
from __future__ import annotations

from typing import List

from .base import Rule
from ..core import Finding, Project


class CollectiveScheduleRule(Rule):
    code = "PTA012"
    name = "collective-schedule"
    tier = "trace"
    description = ("trace-level collective-schedule audit of registered "
                   "entrypoints: rank-divergent cond branches, broken "
                   "ppermute permutations, mismatched all_to_all pairs, "
                   "wire-byte accounting (runs only via --only)")
    severity = "error"

    def finalize(self, project: Project) -> List[Finding]:
        from ..trace import get_report
        report = get_report()
        findings: List[Finding] = []
        if report.error:
            findings.append(Finding(
                self.code, "tools/analyze/trace/__init__.py", 1, 0,
                f"trace audit could not run (jax/paddle_tpu import "
                f"failed): {report.error.strip().splitlines()[-1]}",
                anchor="trace:runner:unavailable", severity="error"))
            return findings
        for name, st in sorted(report.entrypoint_stats.items()):
            loc = (st.path or "tools/analyze/trace/__init__.py",
                   st.line or 1)
            if st.error:
                # PTA009 already reports the build failure; a second
                # finding here would double-count the same breakage
                continue
            for issue in st.collective_issues:
                kind = issue.get("kind", "?")
                if kind == "rank-divergent-cond":
                    scheds = issue.get("branch_schedules", [])
                    desc = " vs ".join(
                        "[" + ", ".join(s) + "]" for s in scheds) or "?"
                    findings.append(Finding(
                        self.code, loc[0], loc[1], 0,
                        f"entrypoint `{name}`: cond/switch branches carry "
                        f"different collective schedules ({desc}) — branch "
                        f"selection can differ per rank, so some ranks "
                        f"issue collectives their peers never join "
                        f"(deadlock); hoist the collectives out of the "
                        f"branches and select on data instead",
                        anchor=f"trace:{name}:rank-divergent-cond",
                        severity="error"))
                elif kind == "broken-permutation":
                    axis = issue.get("axis", "?")
                    size = issue.get("axis_size")
                    covered = issue.get("covered_ranks", [])
                    cls = issue.get("classification", "invalid")
                    findings.append(Finding(
                        self.code, loc[0], loc[1], 0,
                        f"entrypoint `{name}`: ppermute over axis "
                        f"`{axis}` (size {size}) has a {cls} permutation "
                        f"{issue.get('perm')} — ranks {covered} "
                        f"participate but the axis has "
                        f"{size if size is not None else '?'} ranks; the "
                        f"uncovered rank blocks forever while its peers "
                        f"cycle",
                        anchor=f"trace:{name}:broken-perm:{axis}",
                        severity="error"))
                elif kind == "alltoall-pairing":
                    axis = issue.get("axis", "?")
                    findings.append(Finding(
                        self.code, loc[0], loc[1], 0,
                        f"entrypoint `{name}`: paired all_to_alls on axis "
                        f"`{axis}` have non-transposed split/concat dims "
                        f"({issue.get('first')} then "
                        f"{issue.get('second')}) — the return trip does "
                        f"not undo the dispatch, so tokens land on the "
                        f"wrong expert/rank",
                        anchor=f"trace:{name}:alltoall-pairing:{axis}",
                        severity="warning"))
                else:
                    findings.append(Finding(
                        self.code, loc[0], loc[1], 0,
                        f"entrypoint `{name}`: collective-schedule issue "
                        f"`{kind}`: {issue}",
                        anchor=f"trace:{name}:{kind}",
                        severity="warning"))
        return findings


RULE = CollectiveScheduleRule()
