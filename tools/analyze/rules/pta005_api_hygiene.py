"""PTA005: public-API hygiene — mutable default args, missing
``from __future__ import annotations``.

Mutable defaults (``def f(x=[])``) are shared across calls; in an op
library they alias state between unrelated user calls — the reference
bans them outright in its python lint. And modules that use type
annotations without the ``__future__`` import evaluate them eagerly at
import time, which both slows cold import (ROADMAP: serving path) and
breaks under deferred / optional imports (e.g. annotations naming types
from gated optional deps).
"""
from __future__ import annotations

import ast
from typing import List

from .base import Rule
from ..core import _ALL_CODES, Finding, Project, SourceFile

API_PREFIX = "paddle_tpu/"

_MUTABLE_CALLS = {"list", "dict", "set", "defaultdict", "OrderedDict"}

#: span constructions that bypass the module-level ``_ENABLED`` gate in
#: paddle_tpu/observability/tracer.py — in a hot path they allocate a Span
#: (and run its enter/exit bookkeeping) even when tracing is disabled
_UNGATED_SPAN_CALLS = {"Span", "SpanTracer", "span_always"}

#: the tracer module itself owns the Span constructor
_SPAN_CHECK_EXEMPT = ("paddle_tpu/observability/tracer.py",)


def _is_mutable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(node, ast.Call):
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else "")
        return name in _MUTABLE_CALLS and not node.args and not node.keywords
    return False


def _has_annotations(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.returns is not None:
                return True
            a = node.args
            for arg in a.posonlyargs + a.args + a.kwonlyargs:
                if arg.annotation is not None:
                    return True
    return False


def _has_future_annotations(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            if any(alias.name == "annotations" for alias in node.names):
                return True
    return False


class ApiHygieneRule(Rule):
    code = "PTA005"
    name = "api-hygiene"
    description = ("mutable default arguments, missing `from __future__ "
                   "import annotations`, unjustified `# noqa: PTA002` / "
                   "`PTA013` / `PTA014` suppressions and ungated span "
                   "construction in hot-path modules")

    def visit_file(self, sf: SourceFile, project: Project) -> List[Finding]:
        if API_PREFIX not in sf.relpath:
            return []
        findings: List[Finding] = []
        findings.extend(self._check_noqa_justifications(sf))
        findings.extend(self._check_span_fastpath(sf))
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            a = node.args
            for default in list(a.defaults) + [d for d in a.kw_defaults
                                               if d is not None]:
                if _is_mutable_default(default):
                    findings.append(sf.finding(
                        self.code, default,
                        f"mutable default argument in `{node.name}` is "
                        f"shared across calls — use None and initialize "
                        f"inside the body"))
        if _has_annotations(sf.tree) and not _has_future_annotations(sf.tree):
            findings.append(sf.finding(
                self.code, 1,
                "module uses type annotations without `from __future__ "
                "import annotations` (eager evaluation at import time)",
                anchor="no-future-annotations"))
        return findings

    def _check_span_fastpath(self, sf: SourceFile) -> List[Finding]:
        """Spans opened in instrumented hot paths must go through the
        module-level ``observability.span()`` helper, whose disabled path
        is one list-index check and a shared no-op (mirroring
        ``profiler._ACTIVE``). Direct ``Span(...)`` construction, private
        ``SpanTracer(...)`` instances and ``span_always(...)`` all pay
        allocation + stack bookkeeping on every call even with tracing
        off — in a per-step/per-tick path that is a standing tax."""
        # local import: HOT_PREFIXES is owned by the host-sync rule
        from .pta002_host_sync import HOT_PREFIXES
        if (not sf.relpath.startswith(HOT_PREFIXES)
                or sf.relpath in _SPAN_CHECK_EXEMPT):
            return []
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            if name in _UNGATED_SPAN_CALLS:
                findings.append(sf.finding(
                    self.code, node,
                    f"`{name}(...)` in a hot path bypasses the tracer's "
                    f"zero-alloc disabled fast path — open spans via "
                    f"`observability.span(...)` (module-level _ENABLED "
                    f"gate)"))
        return findings

    def _check_noqa_justifications(self, sf: SourceFile) -> List[Finding]:
        """Every host-sync suppression in a hot-path module must say *why*
        the concrete value is semantically required: `# noqa: PTA002 --
        reason`. A bare `# noqa: PTA002` (or a codeless blanket `# noqa`)
        silently sanctions a pipeline stall for the next reader. The same
        mandatory-reason policy covers the kernel-safety/fusion tiers
        (PTA013/PTA014) in ANY analyzed module: suppressing a VMEM bust
        or an unguarded grid without saying why hides a hardware-only
        failure mode."""
        # local import: HOT_PREFIXES is owned by the host-sync rule
        from .pta002_host_sync import HOT_PREFIXES
        hot = sf.relpath.startswith(HOT_PREFIXES)
        findings: List[Finding] = []
        for line, codes in sorted(sf.noqa.items()):
            if sf.noqa_justified.get(line):
                continue
            if hot and _ALL_CODES in codes:
                findings.append(sf.finding(
                    self.code, line,
                    "blanket `# noqa` in a hot-path module — suppress the "
                    "specific rule with a justification: "
                    "`# noqa: PTA002 -- reason`",
                    anchor=f"noqa-hygiene:blanket:{sf.line_text(line)}"))
            elif hot and "PTA002" in codes:
                findings.append(sf.finding(
                    self.code, line,
                    "`# noqa: PTA002` without a justification — hot-path "
                    "host syncs must document why a concrete value is "
                    "required: `# noqa: PTA002 -- reason`",
                    anchor=f"noqa-hygiene:PTA002:{sf.line_text(line)}"))
            else:
                for code in ("PTA013", "PTA014"):
                    if code in codes:
                        findings.append(sf.finding(
                            self.code, line,
                            f"`# noqa: {code}` without a justification — "
                            f"kernel-safety/fusion suppressions hide "
                            f"TPU-only failure modes and must document "
                            f"why the pattern is safe: "
                            f"`# noqa: {code} -- reason`",
                            anchor=f"noqa-hygiene:{code}:"
                                   f"{sf.line_text(line)}"))
        return findings


RULE = ApiHygieneRule()
