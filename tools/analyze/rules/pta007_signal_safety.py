"""PTA007: async-signal-safety of code reachable from signal handlers.

CPython delivers signals on the main thread, *between bytecodes of
whatever that thread was doing*. Anything a handler (or code it calls —
the walk starts from every function installed via ``signal.signal`` or
``ChainedSignalHandler``) does that needs cooperation from the
interrupted frame can therefore deadlock or corrupt state:

- acquiring a non-reentrant lock the interrupted thread may already hold
  is a self-deadlock (error); an ``RLock`` only deadlocks cross-thread,
  so reentrant acquisition is a warning;
- ``logging`` takes module-level and handler locks internally — the
  classic "SIGTERM during a log call" hang (error);
- blocking calls (``time.sleep``, ``subprocess`` waits, ``.wait()`` /
  ``.communicate()`` / argument-less ``.join()``) stall the main thread
  inside the handler (warning);
- a ``raise`` escaping the handler unwinds whatever frame happened to be
  executing (warning; flagged in the installed handler itself).

The safe handler shape is flag-only: set an ``Event``, let the program's
normal control flow observe it (see PreemptionGuard). Suppress deliberate
exceptions (e.g. teardown-then-``sys.exit``) with ``# noqa: PTA007 --
<why blocking/raising here is the intended last act>``.
"""
from __future__ import annotations

import ast
from typing import List

from .base import Rule
from ..concurrency import ConcurrencyModel
from ..core import Finding, Project, dotted_name

LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
               "critical", "log"}

_LOG_RECEIVER_HINTS = ("log", "logger")

BLOCKING_DOTTED = {"time.sleep", "select.select", "os.waitpid",
                   "subprocess.run", "subprocess.call",
                   "subprocess.check_call", "subprocess.check_output"}


def _via(fi) -> str:
    if fi.signal_root_via is not None:
        return f"[installed: {fi.signal_root_via}]"
    return f"[signal-reachable via {fi.signal_reachable_from}]"


def _is_logging_call(call: ast.Call) -> bool:
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr not in LOG_METHODS:
        return False
    base = f.value
    if isinstance(base, ast.Call):  # logging.getLogger(...).info(...)
        return dotted_name(base.func).startswith("logging")
    d = dotted_name(base)
    if d == "logging" or d.startswith("logging."):
        return True
    last = d.rpartition(".")[2].lower()
    return any(h in last for h in _LOG_RECEIVER_HINTS)


def _blocking_reason(call: ast.Call) -> str:
    d = dotted_name(call.func)
    if d in BLOCKING_DOTTED:
        return f"`{d}()` blocks the main thread inside the handler"
    f = call.func
    if isinstance(f, ast.Attribute):
        if f.attr in ("wait", "communicate"):
            return (f"`.{f.attr}()` blocks inside the handler (the "
                    f"condition it waits on may need the interrupted "
                    f"frame to make progress)")
        if f.attr == "join" and not call.args:
            # str.join always has a positional argument; thread/process
            # joins are argument-less or timeout-kwarg only
            return "`.join()` blocks inside the handler"
    return ""


class SignalSafetyRule(Rule):
    code = "PTA007"
    name = "signal-safety"
    description = ("lock acquisition, logging, blocking calls and escaping "
                   "raises in signal-handler-reachable code")
    severity = "error"

    def finalize(self, project: Project) -> List[Finding]:
        graph = project.callgraph
        model = ConcurrencyModel(graph)
        findings: List[Finding] = []
        for fi in graph.signal_reachable():
            findings.extend(self._check_function(model, fi))
        return findings

    def _check_function(self, model, fi) -> List[Finding]:
        sf = fi.file
        cl = model.locks_for(fi.cls)
        mlocks = model.module_locks_of(sf)
        via = _via(fi)
        findings: List[Finding] = []

        for node in self._own_body(fi.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    findings.extend(self._lock_acquisition(
                        sf, cl, mlocks, item.context_expr, node, via))
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "acquire":
                    findings.extend(self._lock_acquisition(
                        sf, cl, mlocks, f.value, node, via))
                elif _is_logging_call(node):
                    findings.append(sf.finding(
                        self.code, node,
                        f"logging call in signal context — the logging "
                        f"module takes internal locks; a signal landing "
                        f"mid-log deadlocks {via}",
                        severity="error"))
                else:
                    why = _blocking_reason(node)
                    if why:
                        findings.append(sf.finding(
                            self.code, node,
                            f"{why}; handlers should only set flags {via}",
                            severity="warning"))

        if fi.signal_root_via is not None:
            findings.extend(self._escaping_raises(sf, fi, via))
        return findings

    def _lock_acquisition(self, sf, cl, mlocks, lock_expr, anchor,
                          via) -> List[Finding]:
        d = dotted_name(lock_expr)
        kind = None
        if isinstance(lock_expr, ast.Name):
            kind = mlocks.get(d)
        elif d.startswith("self.") and d.count(".") == 1 and cl is not None:
            attr = d[len("self."):]
            group = cl.groups.get(attr)
            if group is not None:
                kind = cl.kinds.get(group, "lock")
        if kind is None:
            return []
        if kind == "rlock":
            return [sf.finding(
                self.code, anchor,
                f"acquires reentrant `{d}` in signal context — safe only "
                f"if every other owner is the main thread {via}",
                severity="warning")]
        return [sf.finding(
            self.code, anchor,
            f"acquires `{d}` in signal context — if the interrupted "
            f"thread holds it the handler never returns (self-deadlock); "
            f"set a flag and do the locked work at a poll point {via}",
            severity="error")]

    def _escaping_raises(self, sf, fi, via) -> List[Finding]:
        findings: List[Finding] = []

        def visit(node, in_try: bool):
            if isinstance(node, ast.Raise):
                if not in_try:
                    findings.append(sf.finding(
                        self.code, node,
                        f"`raise` escaping a signal handler unwinds "
                        f"whatever frame the signal interrupted {via}",
                        severity="warning"))
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                return
            if isinstance(node, ast.Try):
                covered = in_try or bool(node.handlers)
                for stmt in node.body + node.orelse:
                    visit(stmt, covered)
                # finally blocks and except bodies re-raise outward
                for stmt in node.finalbody:
                    visit(stmt, in_try)
                for h in node.handlers:
                    for stmt in h.body:
                        visit(stmt, in_try)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, in_try)

        for child in ast.iter_child_nodes(fi.node):
            visit(child, False)
        return findings

    @staticmethod
    def _own_body(func_node):
        stack = list(ast.iter_child_nodes(func_node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))


RULE = SignalSafetyRule()
