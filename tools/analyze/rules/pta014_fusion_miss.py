"""PTA014: trace-level HLO fusion-miss audit.

For every registered auditable entrypoint, walk the *optimized* HLO the
way PTA009 does, segment the entry computation into fusion regions, and
rank the unfused elementwise->dot / dot->elementwise / norm->dot
boundaries by the HBM bytes crossing them
(``trace/passes.py:fusion_miss_report``). Each surviving boundary is a
round-trip through HBM that XLA's conservative producer/consumer fusion
declined to merge — "Operator Fusion in XLA" (PAPERS.md) shows exactly
these misses around matmuls are where GPT's single-digit MFU goes.

An entrypoint whose total ``unfused_boundary_bytes`` exceeds
:data:`FUSION_MISS_BYTES_THRESHOLD` gets a warning naming its heaviest
boundaries — the ranked work order for the ROADMAP item-1 megakernel PR
(ln+matmul, matmul+gelu+matmul, fused residual epilogues). Warnings
rather than errors because a miss is a perf target, not a correctness
bug; byte-level *regressions* are gated separately (±5%) by
``tools/check_audit_regression.py``.

Findings anchor at the ``register_entrypoint`` site with stable
``trace:<name>:fusion-miss`` fingerprints, so they baseline and noqa
like any AST finding. This tier compiles code: it only runs when
selected explicitly (``--only PTA014``).
"""
from __future__ import annotations

from typing import List

from .base import Rule
from ..core import Finding, Project

#: an entrypoint whose unfused boundary traffic is under 1 MiB per step
#: is not worth a megakernel; above it, the report names the targets
FUSION_MISS_BYTES_THRESHOLD = 1 << 20


class FusionMissRule(Rule):
    code = "PTA014"
    name = "fusion-miss"
    tier = "trace"
    description = ("trace-level HLO fusion-miss audit of registered "
                   "entrypoints: unfused elementwise->dot / "
                   "dot->elementwise / norm->dot boundaries ranked by "
                   "HBM bytes crossed — the megakernel target list "
                   "(runs only via --only)")
    severity = "warning"

    def finalize(self, project: Project) -> List[Finding]:
        from ..trace import get_report
        report = get_report()
        findings: List[Finding] = []
        if report.error:
            findings.append(Finding(
                self.code, "tools/analyze/trace/__init__.py", 1, 0,
                f"trace audit could not run (jax/paddle_tpu import "
                f"failed): {report.error.strip().splitlines()[-1]}",
                anchor="trace:runner:unavailable", severity="error"))
            return findings
        for name, st in sorted(report.entrypoint_stats.items()):
            if st.error:
                # PTA009 already reports the build failure; a second
                # finding here would double-count the same breakage
                continue
            if st.unfused_boundary_bytes <= FUSION_MISS_BYTES_THRESHOLD:
                continue
            top = ", ".join(
                f"{m['kind']} {m['producer']}->{m['consumer']} "
                f"({m['bytes']} B)"
                for m in st.top_fusion_misses[:3]) or "?"
            findings.append(Finding(
                self.code,
                st.path or "tools/analyze/trace/__init__.py",
                st.line or 1, 0,
                f"entrypoint `{name}`: {st.unfused_boundary_bytes} HBM "
                f"bytes cross unfused dot boundaries per step across "
                f"{st.fusion_regions} fusion regions; heaviest: {top} — "
                f"each is a megakernel candidate (ROADMAP item 1), see "
                f"--fusion-report for the full ranked table",
                anchor=f"trace:{name}:fusion-miss",
                severity="warning"))
        return findings


RULE = FusionMissRule()
