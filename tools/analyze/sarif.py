"""SARIF 2.1.0 serialization of analyzer findings.

One run, one driver (``paddle-tpu-analyze``); every selected rule is
listed in ``tool.driver.rules`` (so viewers can render rule metadata even
for rules with zero results) and each result carries ``ruleIndex`` into
that list, a ``level`` mapped from the finding severity, and
``baselineState`` ("new" vs "unchanged") so CI annotators can highlight
only the findings the current change introduced.
"""
from __future__ import annotations

from typing import List, Optional, Set

from .core import Finding

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")
SARIF_VERSION = "2.1.0"

_LEVELS = {"error": "error", "warning": "warning"}


def to_sarif(findings: List[Finding], rules, new_ids: Set[int],
             error: Optional[str] = None) -> dict:
    """``error`` marks the run as failed: the SARIF stays valid (possibly
    partial results) and the internal error travels as a tool-execution
    notification instead of poisoning the file — consumers never see a
    stale or truncated ``analysis.sarif``."""
    rule_index = {r.code: i for i, r in enumerate(rules)}
    results = []
    for f in findings:
        res = {
            "ruleId": f.rule,
            "level": _LEVELS.get(f.severity, "error"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": f.col + 1},
                },
            }],
            "partialFingerprints": {"pta/v1": f.fingerprint},
            "baselineState": "new" if id(f) in new_ids else "unchanged",
        }
        if f.rule in rule_index:
            res["ruleIndex"] = rule_index[f.rule]
        results.append(res)
    invocation: dict = {"executionSuccessful": error is None}
    if error is not None:
        invocation["toolExecutionNotifications"] = [{
            "level": "error",
            "message": {"text": error},
        }]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "paddle-tpu-analyze",
                "rules": [{
                    "id": r.code,
                    "name": r.name,
                    "shortDescription": {"text": r.description},
                    "defaultConfiguration": {
                        "level": _LEVELS.get(r.severity, "error")},
                } for r in rules],
            }},
            "invocations": [invocation],
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }
