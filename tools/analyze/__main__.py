"""Driver: ``python -m tools.analyze [options] [paths...]``.

Exit codes (check_bench_regression-style):
    0   clean — no findings beyond the baseline
    1   new findings (or --write-baseline wrote nothing because of an error)
    2   internal error in the analyzer itself

The default baseline is tools/analyze/baseline.json; pass ``--baseline
none`` to compare against nothing (every finding is then "new").
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import traceback

from .core import (Project, filter_noqa, load_baseline, run_rules,
                   split_findings, write_baseline)
from .rules import ALL_RULES, rules_by_code

DEFAULT_BASELINE = os.path.join("tools", "analyze", "baseline.json")


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="paddle-tpu-analyze: AST-based tracer-safety, "
                    "host-sync and API-surface analyzer")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to analyze (default: paddle_tpu)")
    ap.add_argument("--root", default=None,
                    help="repo root for relative paths and the baseline "
                         "(default: autodetected from this file)")
    ap.add_argument("--only", "--rule", action="append", default=None,
                    dest="only", metavar="PTA###[,PTA###]",
                    help="run only these rules (repeatable or "
                         "comma-separated). The slow trace tier "
                         "(PTA009/PTA010/PTA012/PTA014, compiles code) "
                         "ONLY runs when selected here.")
    ap.add_argument("--changed-only", nargs="?", const="HEAD",
                    default=None, metavar="BASE",
                    help="analyze only .py files changed vs BASE "
                         "(git diff --name-only BASE, plus untracked "
                         "files; default BASE: HEAD) that fall under the "
                         "given paths — the fast pre-commit lane. No "
                         "changed files is a clean exit. Also scopes the "
                         "trace tier: only entrypoints whose import "
                         "closure touches a changed file are re-traced.")
    ap.add_argument("--skip", action="append", default=[],
                    metavar="PTA###[,PTA###]", help="disable these rules "
                    "(repeatable or comma-separated)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help=f"baseline file relative to root (default: "
                         f"{DEFAULT_BASELINE}; 'none' disables)")
    ap.add_argument("--write-baseline", "--regen-baseline",
                    action="store_true", dest="write_baseline",
                    help="record all current findings as the new baseline "
                         "and exit 0")
    ap.add_argument("--json", action="store_const", const="json",
                    dest="format", help="shorthand for --format json")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text", dest="format",
                    help="output format (default: text)")
    ap.add_argument("--output", "-o", default=None, metavar="FILE",
                    help="write the json/sarif payload to FILE (a text "
                         "summary still goes to stdout)")
    ap.add_argument("--strict", action="store_true",
                    help="warnings gate the exit code too (default: only "
                         "error-severity findings do)")
    ap.add_argument("--trace-report", default=None, metavar="FILE",
                    help="write the trace tier's per-entrypoint audit "
                         "stats (trace counts, transfers, fusion stats, "
                         "collective schedules) to FILE as json — "
                         "requires selecting PTA009/PTA010/PTA012/PTA014 "
                         "via --only")
    ap.add_argument("--fusion-report", nargs="?", const="fusion_audit.json",
                    default=None, metavar="FILE",
                    help="write the PTA014 ranked fusion-miss table to "
                         "FILE as json (default FILE: fusion_audit.json, "
                         "gitignored). Written automatically whenever "
                         "PTA014 is selected, so `--only PTA014 --format "
                         "json` emits the standalone artifact.")
    ap.add_argument("--list-rules", action="store_true")
    return ap


def _split_codes(specs) -> list:
    out = []
    for spec in specs or []:
        out.extend(c.strip() for c in spec.split(",") if c.strip())
    return out


def select_rules(args) -> list:
    by_code = rules_by_code()
    only = _split_codes(args.only)
    if only:
        unknown = [c for c in only if c.upper() not in by_code]
        if unknown:
            raise SystemExit(f"unknown rule(s): {', '.join(unknown)} "
                             f"(known: {', '.join(sorted(by_code))})")
        rules = [by_code[c.upper()] for c in only]
    else:
        # default run = fast AST tier only; the trace tier compiles every
        # registered entrypoint and must be opted into explicitly
        rules = [r for r in ALL_RULES if r.tier == "ast"]
    skip = {c.upper() for c in _split_codes(args.skip)}
    return [r for r in rules if r.code not in skip]


def _changed_paths(root: str, base: str, scope: list) -> list:
    """Changed-vs-``base`` plus untracked .py files that fall under the
    requested analysis paths (the --changed-only pre-commit lane)."""
    def _git(*argv):
        res = subprocess.run(["git", *argv], cwd=root,
                             capture_output=True, text=True)
        if res.returncode != 0:
            raise SystemExit(f"--changed-only: git {' '.join(argv)} "
                             f"failed: {res.stderr.strip()}")
        return [ln.strip() for ln in res.stdout.splitlines() if ln.strip()]

    changed = _git("diff", "--name-only", base)
    changed += _git("ls-files", "--others", "--exclude-standard")
    prefixes = []
    for p in scope:
        rel = os.path.relpath(os.path.abspath(p), root) \
            if os.path.isabs(p) else p
        prefixes.append(rel.rstrip("/"))
    scoped = []
    for rel in dict.fromkeys(changed):
        if not rel.endswith(".py"):
            continue
        if not os.path.exists(os.path.join(root, rel)):
            continue  # deleted by the change
        if not any(rel == p or rel.startswith(p + "/") or p == "."
                   for p in prefixes):
            continue
        scoped.append(rel)
    return scoped


def _salvage_output(args, root, rules, tb: str) -> None:
    """Exit-2 path: never leave a stale payload file behind. Overwrite
    the requested --output with a valid empty-results document carrying
    the internal error (SARIF: as a tool-execution notification)."""
    if not args.output or args.format not in ("sarif", "json"):
        return
    try:
        if args.format == "sarif":
            from .sarif import to_sarif
            payload = to_sarif([], rules, set(), error=tb)
        else:
            payload = {"version": 1, "root": root, "error": tb,
                       "rules": [r.code for r in rules],
                       "counts": {}, "findings": []}
        out_path = (args.output if os.path.isabs(args.output)
                    else os.path.join(root, args.output))
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
            fh.write("\n")
        print(f"internal error recorded in {args.format} output "
              f"{os.path.relpath(out_path, root)}", file=sys.stderr)
    except Exception:
        traceback.print_exc()


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for r in ALL_RULES:
            tier = "" if r.tier == "ast" else f" [{r.tier} tier]"
            print(f"{r.code}  {r.name}{tier}: {r.description}")
        return 0

    root = os.path.abspath(args.root) if args.root else _repo_root()
    rules = select_rules(args)
    try:
        return _run(args, root, rules)
    except SystemExit:
        raise
    except Exception:
        traceback.print_exc()
        _salvage_output(args, root, rules, traceback.format_exc())
        return 2


def _run(args, root: str, rules: list) -> int:
    paths = args.paths or ["paddle_tpu"]
    if args.changed_only is not None:
        paths = _changed_paths(root, args.changed_only, paths)
        if not paths:
            print("--changed-only: no changed .py files under the "
                  "analyzed paths; clean")
            return 0
        if any(r.tier == "trace" for r in rules):
            # scope the trace tier too: only entrypoints whose static
            # import closure touches a changed file get re-traced
            from . import trace as trace_mod
            try:
                scope = trace_mod.scope_entrypoints(root, paths)
            except Exception:
                scope = None  # registry unimportable: run_audit records it
            trace_mod.set_audit_scope(scope)
            if scope is not None:
                print(f"--changed-only: trace tier scoped to "
                      f"{len(scope)} entrypoint(s)"
                      + (f": {', '.join(scope)}" if scope else ""))

    baseline_arg = args.baseline or DEFAULT_BASELINE
    baseline_path = (None if baseline_arg.lower() == "none"
                     else os.path.join(root, baseline_arg)
                     if not os.path.isabs(baseline_arg) else baseline_arg)

    project = Project(root, paths)
    findings = run_rules(project, rules)
    findings, suppressed = filter_noqa(project, findings)

    if args.trace_report:
        from .trace import last_report
        report = last_report()
        if report is None:
            print("--trace-report: no trace-tier rule ran (select PTA009/"
                  "PTA010 via --only)", file=sys.stderr)
        else:
            tr_path = (args.trace_report if os.path.isabs(args.trace_report)
                       else os.path.join(root, args.trace_report))
            with open(tr_path, "w") as fh:
                json.dump(report.stats_payload(), fh, indent=1,
                          sort_keys=True)
                fh.write("\n")
            print(f"wrote trace audit ({len(report.entrypoint_stats)} "
                  f"entrypoint(s)) to {os.path.relpath(tr_path, root)}")

    fusion_report = args.fusion_report
    if fusion_report is None and any(r.code == "PTA014" for r in rules):
        fusion_report = "fusion_audit.json"  # the standalone CI artifact
    if fusion_report:
        from .trace import last_report
        report = last_report()
        if report is None:
            print("--fusion-report: no trace-tier rule ran (select "
                  "PTA014 via --only)", file=sys.stderr)
        else:
            ranked = sorted(
                (st for st in report.entrypoint_stats.values()
                 if not st.error),
                key=lambda s: -s.unfused_boundary_bytes)
            fr_payload = {
                "version": 1,
                "platform": report.platform,
                "ranking": [st.name for st in ranked],
                "entrypoints": {
                    st.name: {
                        "fusion_regions": st.fusion_regions,
                        "unfused_boundary_bytes":
                            st.unfused_boundary_bytes,
                        "top_fusion_misses": st.top_fusion_misses,
                    } for st in ranked},
            }
            fr_path = (fusion_report if os.path.isabs(fusion_report)
                       else os.path.join(root, fusion_report))
            with open(fr_path, "w") as fh:
                json.dump(fr_payload, fh, indent=1, sort_keys=True)
                fh.write("\n")
            print(f"wrote fusion-miss audit ({len(ranked)} "
                  f"entrypoint(s)) to {os.path.relpath(fr_path, root)}")

    if args.write_baseline:
        if baseline_path is None:
            print("--write-baseline requires a baseline file", file=sys.stderr)
            return 1
        write_baseline(baseline_path, findings)
        print(f"wrote {len(findings)} finding(s) "
              f"({len({f.fingerprint for f in findings})} fingerprints) "
              f"to {os.path.relpath(baseline_path, root)}")
        return 0

    baseline = load_baseline(baseline_path) if baseline_path else {}
    # under --only/--skip, entries from unselected rules are invisible,
    # not expired — don't report them as stale
    selected_codes = {r.code for r in rules}
    baseline = {fp: e for fp, e in baseline.items()
                if e.get("rule") in selected_codes}
    new, baselined, expired = split_findings(findings, baseline)
    new_ids = {id(x) for x in new}
    # warnings only gate under --strict; errors always do
    gating = [f for f in new if args.strict or f.severity == "error"]

    payload = None
    if args.format == "json":
        payload = {
            "version": 1,
            "root": root,
            "rules": [r.code for r in rules],
            "counts": {"total": len(findings), "new": len(new),
                       "gating": len(gating),
                       "baselined": len(baselined),
                       "suppressed": len(suppressed),
                       "expired_baseline_entries": len(expired)},
            "findings": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "col": f.col, "message": f.message,
                 "severity": f.severity,
                 "fingerprint": f.fingerprint,
                 "status": "new" if id(f) in new_ids else "baselined"}
                for f in findings],
        }
    elif args.format == "sarif":
        from .sarif import to_sarif
        payload = to_sarif(findings, rules, new_ids)

    if payload is not None and args.output:
        out_path = (args.output if os.path.isabs(args.output)
                    else os.path.join(root, args.output))
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
            fh.write("\n")
        print(f"wrote {args.format} ({len(findings)} finding(s), "
              f"{len(new)} new) to {os.path.relpath(out_path, root)}")
    elif payload is not None:
        print(json.dumps(payload, indent=1))

    if payload is None or args.output:
        for f in new:
            sev = "" if f.severity == "error" else f" ({f.severity})"
            print(f.render() + sev)
        if baselined:
            print(f"[{len(baselined)} pre-existing finding(s) suppressed "
                  f"by baseline]")
        if suppressed:
            print(f"[{len(suppressed)} finding(s) suppressed by inline "
                  f"noqa]")
        if expired:
            print(f"[{len(expired)} baseline entr(ies) no longer match — "
                  f"run --regen-baseline to prune]")
        if new:
            gate_note = ("" if len(gating) == len(new) else
                         f" ({len(new) - len(gating)} warning(s) not "
                         f"gating; use --strict)")
            print(f"{len(new)} new finding(s){gate_note}; fix them, add "
                  f"`# noqa: PTA### -- reason`, or run --regen-baseline "
                  f"(docs/static_analysis.md)")
        else:
            print(f"clean: 0 new findings "
                  f"({len(baselined)} baselined, {len(suppressed)} noqa)")
    return 1 if gating else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except SystemExit:
        raise
    except Exception:
        traceback.print_exc()
        sys.exit(2)
