"""Shared lock/sync model for the concurrency rules (PTA006, PTA007).

Per-class inference, no annotations required:

lock groups
    ``self._lock = threading.Lock()`` declares a lock attribute.
    ``self._not_empty = threading.Condition(self._lock)`` *aliases* into
    ``_lock``'s group — ``with self._not_empty:`` holds the same
    underlying mutex (this is exactly BatchQueue's layout; without the
    aliasing every condition-guarded access would be a false positive).
    ``RLock`` is tracked with its kind so PTA007 can downgrade reentrant
    acquisition to a warning. ``Event``/``Barrier``/``Queue`` are sync
    primitives (never "guarded data") but not locks.

guarded attributes
    Any ``self.<attr>`` *written* at least once while a lock of the class
    is held is classified as guarded by that lock's group. Writes are
    assignments, augmented assignments, subscript stores/deletes, and
    mutating method calls (``.append``/``.pop``/``.update``/...).

held-lock annotation
    ``held_map`` maps every node of a function body to the frozenset of
    lock tokens held there (``"self.<group>"`` for instance locks,
    bare names for module-level locks, and the raw dotted receiver for
    cross-object locks like ``self._queue._lock``).
"""
from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from .callgraph import CallGraph, ClassInfo, FuncInfo, _walk_own
from .core import SourceFile, dotted_name

#: constructor (last dotted component) -> lock kind
LOCK_CTORS = {"Lock": "lock", "RLock": "rlock",
              "Semaphore": "lock", "BoundedSemaphore": "lock"}

#: sync primitives excluded from "guarded data" classification
OTHER_SYNC_CTORS = {"Condition", "Event", "Barrier", "Queue", "SimpleQueue",
                    "LifoQueue", "PriorityQueue", "JoinableQueue"}

#: method names that mutate their receiver in place
MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "discard", "clear", "update", "add",
    "setdefault", "sort", "reverse", "rotate",
}


class ClassLocks:
    """Lock layout + guarded-attribute map of one class."""

    __slots__ = ("cls", "groups", "kinds", "sync_attrs", "guarded")

    def __init__(self, cls: ClassInfo):
        self.cls = cls
        self.groups: Dict[str, str] = {}     # lock attr -> canonical group
        self.kinds: Dict[str, str] = {}      # group -> "lock" | "rlock"
        self.sync_attrs: Set[str] = set()    # every sync-primitive attr
        self.guarded: Dict[str, Set[str]] = {}  # data attr -> groups


class Access:
    __slots__ = ("node", "base", "attr", "is_write")

    def __init__(self, node: ast.AST, base: ast.AST, attr: str,
                 is_write: bool):
        self.node = node      # the node the finding anchors to
        self.base = base      # receiver expression (Name 'self', ...)
        self.attr = attr
        self.is_write = is_write


def _ctor_kind(value: ast.AST) -> Optional[str]:
    """'lock' | 'rlock' | 'condition' | 'sync' for a ctor call, else None."""
    if not isinstance(value, ast.Call):
        return None
    last = dotted_name(value.func).rpartition(".")[2]
    if last in LOCK_CTORS:
        return LOCK_CTORS[last]
    if last == "Condition":
        return "condition"
    if last in OTHER_SYNC_CTORS:
        return "sync"
    return None


def _self_attr_targets(stmt: ast.stmt) -> Iterator[Tuple[str, ast.AST]]:
    """(attr, value) pairs for ``self.X = <value>`` in one statement."""
    if isinstance(stmt, ast.Assign):
        pairs = [(t, stmt.value) for t in stmt.targets]
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        pairs = [(stmt.target, stmt.value)]
    else:
        return
    for tgt, val in pairs:
        if (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            yield tgt.attr, val


def module_locks(sf: SourceFile) -> Dict[str, str]:
    """Top-level ``NAME = threading.Lock()`` assignments: name -> kind."""
    out: Dict[str, str] = {}
    if sf.tree is None:
        return out
    for stmt in sf.tree.body:
        if isinstance(stmt, ast.Assign):
            kind = _ctor_kind(stmt.value)
            if kind in ("lock", "rlock"):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = kind
    return out


class ConcurrencyModel:
    """Caches per-class lock layouts and per-function held-lock maps."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        self._class_locks: Dict[int, ClassLocks] = {}
        self._module_locks: Dict[str, Dict[str, str]] = {}
        self._held: Dict[int, Dict[int, FrozenSet[str]]] = {}

    # -- lock layout ----------------------------------------------------------
    def locks_for(self, ci: Optional[ClassInfo]) -> Optional[ClassLocks]:
        if ci is None:
            return None
        cl = self._class_locks.get(id(ci))
        if cl is None:
            cl = self._class_locks[id(ci)] = self._build_class_locks(ci)
        return cl

    def _build_class_locks(self, ci: ClassInfo) -> ClassLocks:
        cl = ClassLocks(ci)
        methods = list(dict.fromkeys(ci.methods.values()))
        # pass 1: direct lock/sync ctors
        for m in methods:
            for stmt in _walk_own(m.node):
                for attr, val in _self_attr_targets(stmt):
                    kind = _ctor_kind(val)
                    if kind in ("lock", "rlock"):
                        cl.groups[attr] = attr
                        cl.kinds[attr] = kind
                        cl.sync_attrs.add(attr)
                    elif kind is not None:
                        cl.sync_attrs.add(attr)
        # pass 2: Condition(self._lock) aliases into the lock's group;
        # a bare Condition() owns its mutex and forms its own group
        for m in methods:
            for stmt in _walk_own(m.node):
                for attr, val in _self_attr_targets(stmt):
                    if _ctor_kind(val) != "condition":
                        continue
                    underlying = None
                    if isinstance(val, ast.Call) and val.args:
                        a0 = val.args[0]
                        if (isinstance(a0, ast.Attribute)
                                and isinstance(a0.value, ast.Name)
                                and a0.value.id == "self"):
                            underlying = a0.attr
                    if underlying is not None:
                        cl.groups[attr] = cl.groups.get(underlying,
                                                        underlying)
                    else:
                        cl.groups[attr] = attr
                        cl.kinds.setdefault(attr, "lock")
        # pass 3: guarded-attribute inference from locked writes
        for m in methods:
            hm = self.held_map_with(m, cl)
            for acc in attr_accesses(m):
                if not acc.is_write:
                    continue
                if not (isinstance(acc.base, ast.Name)
                        and acc.base.id == "self"):
                    continue
                if acc.attr in cl.sync_attrs:
                    continue
                held = hm.get(id(acc.node), frozenset())
                for tok in held:
                    if tok.startswith("self."):
                        cl.guarded.setdefault(acc.attr,
                                              set()).add(tok[len("self."):])
        return cl

    def module_locks_of(self, sf: SourceFile) -> Dict[str, str]:
        ml = self._module_locks.get(sf.relpath)
        if ml is None:
            ml = self._module_locks[sf.relpath] = module_locks(sf)
        return ml

    # -- held-lock annotation -------------------------------------------------
    def lock_tokens(self, expr: ast.AST, cl: Optional[ClassLocks],
                    mlocks: Dict[str, str]) -> List[str]:
        """Tokens a ``with <expr>:`` acquires; [] if not a known lock."""
        d = dotted_name(expr)
        if not d or "?" in d:
            return []
        if isinstance(expr, ast.Name):
            return [d] if d in mlocks else []
        if d.startswith("self.") and d.count(".") == 1 and cl is not None:
            attr = d[len("self."):]
            if attr in cl.groups:
                return [f"self.{cl.groups[attr]}"]
            return []
        # cross-object lock (e.g. `with self._queue._lock:`): keep the raw
        # dotted receiver form so cross-class access checks can match it
        if "." in d:
            return [d]
        return []

    def held_map(self, fi: FuncInfo) -> Dict[int, FrozenSet[str]]:
        hm = self._held.get(id(fi))
        if hm is None:
            hm = self._held[id(fi)] = self.held_map_with(
                fi, self.locks_for(fi.cls))
        return hm

    def held_map_with(self, fi: FuncInfo,
                      cl: Optional[ClassLocks]) -> Dict[int, FrozenSet[str]]:
        mlocks = self.module_locks_of(fi.file)
        out: Dict[int, FrozenSet[str]] = {}

        def annot(node, held: FrozenSet[str]):
            out[id(node)] = held
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = set(held)
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        out[id(sub)] = held
                    inner.update(self.lock_tokens(item.context_expr, cl,
                                                  mlocks))
                inner_f = frozenset(inner)
                for stmt in node.body:
                    annot(stmt, inner_f)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                return
            for child in ast.iter_child_nodes(node):
                annot(child, held)

        empty = frozenset()
        for child in ast.iter_child_nodes(fi.node):
            annot(child, empty)
        return out


def attr_accesses(fi: FuncInfo) -> List[Access]:
    """Attribute reads/writes in a function's own body.

    A receiver claimed by a write form (assignment target, augmented
    assignment, subscript store, mutating method call) is not double-
    reported as a read.
    """
    writes: List[Access] = []
    claimed: Set[int] = set()

    def claim_write(attr_node: ast.Attribute, anchor: ast.AST):
        writes.append(Access(anchor, attr_node.value, attr_node.attr, True))
        claimed.add(id(attr_node))

    def claim_target(tgt: ast.AST, anchor: ast.AST):
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                claim_target(e, anchor)
        elif isinstance(tgt, ast.Attribute):
            claim_write(tgt, anchor)
        elif isinstance(tgt, ast.Subscript) \
                and isinstance(tgt.value, ast.Attribute):
            claim_write(tgt.value, anchor)

    nodes = list(_walk_own(fi.node))
    for node in nodes:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                claim_target(t, node)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            claim_target(node.target, node)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                claim_target(t, node)
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in MUTATORS \
                    and isinstance(f.value, ast.Attribute):
                claim_write(f.value, node)

    reads = [Access(n, n.value, n.attr, False)
             for n in nodes
             if isinstance(n, ast.Attribute)
             and isinstance(n.ctx, ast.Load)
             and id(n) not in claimed]
    return writes + reads


def nodes_under(*roots: ast.AST) -> Set[int]:
    """ids of every node in the given subtrees (for region membership)."""
    out: Set[int] = set()
    for r in roots:
        for n in ast.walk(r):
            out.add(id(n))
    return out
