#!/usr/bin/env python
"""Pre-populate the Pallas autotuner winner cache for the bench shapes.

Searches block/grid configurations for the flash-attention family
(``paddle_tpu.ops.pallas_attention``, ring-flash chunks) and the
greedy-NMS kernel (``ops/custom.py``) by timing the real kernels, and
writes the winners into the on-disk cache (``PADDLE_TPU_TUNE_CACHE`` or
``~/.cache/paddle_tpu/tuning/``) that every kernel call consults — run
it once per platform/fleet and the searched configs are free forever
after.

    python tools/autotune.py                 # tune this platform's lane
    python tools/autotune.py --quick         # small shapes (CPU/CI lane)
    python tools/autotune.py --trials 9      # steadier medians
    python tools/autotune.py --emit-defaults # refresh the committed table

On TPU the shape list is the bench-model lane (GPT-small S=4096 in bf16
and f32, the S=8192 headroom shape, the Tl=512 ring chunk, NMS k=128).
Off-TPU Pallas runs in interpret mode, so the default lane shrinks to
``--quick`` shapes automatically — interpret-mode timings still order
candidates by memory traffic, which is what the committed CPU entries
capture.

``--emit-defaults`` rewrites ``paddle_tpu/tuner/default_winners.json``:
existing curated entries (and their notes) are preserved; winners tuned
in this run are merged in, so the table accretes per-platform coverage
instead of being clobbered.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

#: (q_len, kv_len, head_dim, dtype, causal, ring) — the bench lane
BENCH_FLASH_SHAPES = [
    (4096, 4096, 64, "bfloat16", True, False),   # GPT-small S=4096, amp
    (4096, 4096, 64, "float32", True, False),    # same, no autocast
    (8192, 8192, 64, "bfloat16", True, False),   # long-context headroom
    (512, 512, 64, "bfloat16", False, True),     # ring chunk Tl=512
]
BENCH_NMS_KS = [128]

#: backward-kernel block family (``--only flash-bwd``): the dQ/dKdV
#: recomputation grids are tuned independently of the forward — same
#: candidate space, different arithmetic intensity (5 matmuls vs 2)
BENCH_FLASH_BWD_SHAPES = [
    (4096, 4096, 64, "bfloat16", True, False),   # GPT-small S=4096, amp
    (4096, 4096, 64, "float32", True, False),    # same, no autocast
    (512, 512, 64, "bfloat16", False, True),     # ring chunk Tl=512
]

#: (num_seqs, num_heads, head_dim, page_size, dtype) — paged decode
#: attention (``ops/paged_attention.py``); the family key only uses
#: (heads, head_dim, page, dtype), num_seqs just sizes the search grid
BENCH_PAGED_SHAPES = [
    (48, 12, 64, 16, "bfloat16"),   # GPT-small paged serving lane, amp
    (48, 12, 64, 16, "float32"),    # same, no autocast
]

#: (nelems, wire_dtype) — gradient-size families for the compressed
#: allreduce quantize stage (pow2-bucketed by compress_key, so one entry
#: covers the whole bucket)
BENCH_COMPRESS_SIZES = [(1 << 20, "int8"), (1 << 24, "int8"),
                        (1 << 20, "bf16")]

#: small enough for interpret-mode Pallas (CPU/CI): seconds, not hours
QUICK_FLASH_SHAPES = [
    (128, 128, 32, "float32", True, False),
    (64, 64, 32, "float32", False, True),
]
QUICK_FLASH_BWD_SHAPES = [
    (128, 128, 32, "float32", True, False),
    (64, 64, 32, "float32", False, True),
]
QUICK_PAGED_SHAPES = [
    (4, 4, 8, 8, "float32"),        # tiny CI model geometry
]
QUICK_NMS_KS = [64]
QUICK_COMPRESS_SIZES = [(1 << 16, "int8")]


def tune_flash_lane(shapes, trials, batch_heads, bwd=False):
    from paddle_tpu import tuner

    results = {}
    for q, kv, d, dtype, causal, ring in shapes:
        key = tuner.flash_key(q, kv, d, dtype, causal, ring=ring, bwd=bwd)
        t0 = time.time()
        win = tuner.autotune_flash(batch_heads, q, kv, d, dtype=dtype,
                                   causal=causal, ring=ring, bwd=bwd,
                                   trials=trials)
        print(f"flash{'-bwd' if bwd else ''} {key}: "
              f"block_q={win['block_q']} "
              f"block_k={win['block_k']} ({win['us']:.0f}us, "
              f"{len(win['results'])} candidates, "
              f"{time.time() - t0:.1f}s search)")
        results[key] = {"block_q": win["block_q"],
                        "block_k": win["block_k"]}
    return results


def tune_nms_lane(ks, trials, interpret):
    import jax
    import jax.numpy as jnp
    from paddle_tpu import tuner
    from paddle_tpu.ops import custom as _custom
    from paddle_tpu.tuner import runner as _runner

    results = {}
    for k in ks:
        key = tuner.nms_key(k)
        rng = jax.random.PRNGKey(0)
        iou = jax.random.uniform(rng, (k, k), jnp.float32)
        iou = (iou + iou.T) / 2.0
        valid = jnp.ones((k,), jnp.int32)
        thr = jnp.asarray([0.5], jnp.float32)

        def make_runner(cand):
            # noqa-rationale: every candidate IS a distinct function
            # (unroll is baked into the kernel); the tuner times fresh
            # compiles on purpose and never reuses these traces.
            fn = jax.jit(lambda a, b, c, u=int(cand["unroll"]):  # noqa: PTA008 -- per-candidate kernels differ; tuner intentionally compiles each once
                         _custom.pallas_greedy_nms(a, b, c,
                                                   interpret=interpret,
                                                   unroll=u))
            return lambda: fn(iou, valid, thr)

        best, best_t, _ = _runner.search(tuner.nms_candidates(k),
                                         make_runner, trials=trials)
        if best is None:
            print(f"nms {key}: no candidate built, skipped")
            continue
        cfg = {"unroll": int(best["unroll"])}
        tuner.record_winner(key, cfg, us=best_t * 1e6)
        print(f"nms {key}: unroll={cfg['unroll']} ({best_t * 1e6:.0f}us)")
        results[key] = cfg
    return results


def tune_paged_lane(shapes, trials):
    from paddle_tpu import tuner

    results = {}
    for num_seqs, heads, d, page, dtype in shapes:
        key = tuner.paged_key(heads, d, page, dtype)
        win = tuner.autotune_paged_attn(num_seqs, heads, d, page,
                                        dtype=dtype, trials=trials)
        print(f"paged {key}: block_h={win['block_h']} "
              f"({win['us']:.0f}us, {len(win['results'])} candidates)")
        results[key] = {"block_h": win["block_h"]}
    return results


def tune_compress_lane(sizes, trials):
    from paddle_tpu import tuner

    results = {}
    for nelems, wire_dtype in sizes:
        key = tuner.compress_key(nelems, wire_dtype)
        win = tuner.autotune_compress(nelems, wire_dtype, trials=trials)
        print(f"compress {key}: block={win['block']} "
              f"({win['us']:.0f}us, {len(win['results'])} candidates)")
        results[key] = {"block": win["block"]}
    return results


def emit_defaults(tuned, path):
    """Merge this run's winners into the committed defaults table,
    preserving curated entries and notes for keys not retuned."""
    try:
        with open(path) as f:
            table = json.load(f)
        entries = table.get("entries", {})
    except (OSError, ValueError):
        entries = {}
    for key, cfg in sorted(tuned.items()):
        prev = entries.get(key, {})
        entry = {"config": cfg}
        if "note" in prev:
            entry["note"] = prev["note"]
        entries[key] = entry
    payload = {"version": 1, "platform": "defaults",
               "entries": dict(sorted(entries.items()))}
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"defaults table updated: {path} ({len(entries)} entries)")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="pre-populate the Pallas autotuner winner cache")
    ap.add_argument("--quick", action="store_true",
                    help="small interpret-friendly shapes (CPU/CI lane); "
                         "automatic off-TPU")
    ap.add_argument("--full", action="store_true",
                    help="force the bench lane even off-TPU (interpret "
                         "mode: very slow)")
    ap.add_argument("--trials", type=int, default=5,
                    help="timed trials per candidate, median scored "
                         "(default %(default)s)")
    ap.add_argument("--batch-heads", type=int, default=8,
                    help="leading batch*heads dim for flash search "
                         "arrays (default %(default)s)")
    ap.add_argument("--only",
                    choices=["flash", "flash-bwd", "paged", "nms",
                             "compress"],
                    help="restrict to one kernel family")
    ap.add_argument("--emit-defaults", nargs="?", metavar="PATH",
                    const=os.path.join(REPO, "paddle_tpu", "tuner",
                                       "default_winners.json"),
                    help="merge this run's winners into the committed "
                         "default-winners table (default: the package "
                         "file)")
    args = ap.parse_args(argv)

    import jax
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    quick = args.quick or (not on_tpu and not args.full)
    interpret = not on_tpu
    flash_shapes = QUICK_FLASH_SHAPES if quick else BENCH_FLASH_SHAPES
    flash_bwd_shapes = (QUICK_FLASH_BWD_SHAPES if quick
                        else BENCH_FLASH_BWD_SHAPES)
    paged_shapes = QUICK_PAGED_SHAPES if quick else BENCH_PAGED_SHAPES
    nms_ks = QUICK_NMS_KS if quick else BENCH_NMS_KS
    compress_sizes = (QUICK_COMPRESS_SIZES if quick
                      else BENCH_COMPRESS_SIZES)

    from paddle_tpu.tuner import cache_dir
    print(f"autotune: platform={platform} "
          f"lane={'quick' if quick else 'bench'} "
          f"trials={args.trials} cache={cache_dir()}")

    tuned = {}
    if args.only in (None, "flash"):
        tuned.update(tune_flash_lane(flash_shapes, args.trials,
                                     args.batch_heads))
    if args.only in (None, "flash-bwd"):
        tuned.update(tune_flash_lane(flash_bwd_shapes, args.trials,
                                     args.batch_heads, bwd=True))
    if args.only in (None, "paged"):
        tuned.update(tune_paged_lane(paged_shapes, args.trials))
    if args.only in (None, "nms"):
        tuned.update(tune_nms_lane(nms_ks, args.trials, interpret))
    if args.only in (None, "compress"):
        tuned.update(tune_compress_lane(compress_sizes, args.trials))

    if args.emit_defaults:
        emit_defaults(tuned, args.emit_defaults)
    print(f"done: {len(tuned)} winner(s) recorded")
    return 0


if __name__ == "__main__":
    sys.exit(main())
