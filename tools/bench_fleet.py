#!/usr/bin/env python
"""Traffic-replay chaos harness for the serving fleet — the self-driving
proof, printed as one JSON document.

    python -m tools.bench_fleet                   # run the chaos storm
    python -m tools.bench_fleet --check           # CI gate (run_tests.py
                                                  #   --bench-fleet)
    python -m tools.bench_fleet --migrate --check # zero-loss migration
                                                  #   storm (see below)
    python -m tools.bench_fleet --write-baseline  # refresh the committed
                                                  #   bench_fleet_baseline.json
    python -m tools.bench_fleet --trace my.jsonl  # replay a recorded trace

One storm, three injected disasters, one verdict. A seeded Poisson trace
(or ``--trace``, recorded from a live router by
:class:`~paddle_tpu.serving.fleet.TraceRecorder`) is replayed with
arrival-time fidelity against a 3-shell LLM router parked down to one
serving replica, while:

1. the SLO-aware autoscaler runs its controller loop — the cold-start
   latency spike breaches the SLO and the fleet scales up through the
   budgeted unpark path, with ``replica_boot:4:disk_full`` armed so the
   FIRST scale-up boot dies on ``ENOSPC`` (the health sweep finishes
   that boot on the backoff schedule: a failed scale-up is just a
   counted resurrection);
2. a live weight hot-swap rolls a committed checkpoint across the
   serving replicas mid-storm, with ``weight_swap:2:slow_io`` stretching
   one swap window — the cache-miss delta across the roll must be ZERO
   (executables are keyed by spec/dtype, so new weights reuse them);
3. a replica is hard-killed mid-storm (the in-process SIGKILL analog:
   queued + in-flight requests die with ``EngineKilled`` and the clients
   retry, exactly like production 503 handling).

The verdict: every offered request completes (**drops == 0** — retries
are allowed, losses are not), the fleet scales up at least once, the
roll finishes un-aborted with zero recompiles, and the controller
converges back inside the SLO within the committed tick budget after
the storm ends. Absolute latencies are machine-dependent and not gated;
the *structural* counters (drops, scale-ups, rollbacks, recompiles) and
the *relative* recovery budget are the invariants
(``bench_fleet_baseline.json``).

``--migrate`` runs the **zero-loss serving** storm instead
(docs/fault_tolerance.md): a paged-KV fleet serving long greedy token
streams takes a ``weight_swap:1:slow_io``-widened weight roll (every
in-flight sequence migrates — KV pages and all — to a sibling instead
of draining) and then a hard kill of the busiest replica with streams
in flight (journal replay resumes them on survivors). The roll targets
a checkpoint with IDENTICAL weights, so every client's assembled
stream must be **bitwise equal** to a reference computed on an
undisturbed standalone engine — zero drops, zero duplicated or missing
tokens, zero divergence, zero recompiles across the roll.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "bench_fleet_baseline.json")

#: the storm's armed disasters (see docs/fault_tolerance.md): the 4th
#: replica_boot is the first scale-up boot (3 shells boot at router
#: construction), and the 2nd weight_swap is mid-roll.
FAULT_SPEC = "replica_boot:4:disk_full,weight_swap:2:slow_io"

#: the migration storm's armed disaster: the FIRST replica swap of the
#: roll gets its window stretched by slow_io — the exact window the old
#: quiesce-drain path would have parked live streams in.
FAULT_SPEC_MIGRATE = "weight_swap:1:slow_io"


def _tiny_model():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    m = GPTForCausalLM(GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        max_position_embeddings=128, hidden_dropout_prob=0.0,
        attention_dropout_prob=0.0))
    m.eval()
    return m


def _total_misses(router):
    return sum(r.engine.cache.stats()["misses"]
               for r in router.replicas if r.engine is not None)


def run_chaos(args) -> dict:
    # Arm the injector BEFORE any engine exists; the singleton parses the
    # environment once per process.
    from paddle_tpu.utils import resilience
    if not args.no_faults:
        os.environ["PADDLE_TPU_FAULT_SPEC"] = FAULT_SPEC
        os.environ.setdefault("PADDLE_TPU_FAULT_SLOW_IO_S", "0.3")
        resilience._reset_fault_injector_for_tests()

    from paddle_tpu.core.monitor import StatRegistry
    from paddle_tpu.incubate.checkpoint import commit_checkpoint
    from paddle_tpu.serving.llm import LLMEngineConfig
    from paddle_tpu.serving.router import (Router, RouterConfig,
                                           llm_replica_factory)
    from paddle_tpu.serving.fleet import (SLO, Autoscaler, AutoscalerConfig,
                                          TraceReplayer, WeightSwapper,
                                          load_trace, synthesize_trace)

    cfg = LLMEngineConfig(
        num_slots=args.slots, max_seq=64, max_queue=256, warmup=False,
        default_max_new_tokens=args.max_new_tokens)
    reg = StatRegistry()
    router = Router(
        llm_replica_factory(lambda r: _tiny_model(), cfg),
        RouterConfig(num_replicas=args.replicas, kind="llm",
                     health_interval=0.1, max_restarts=8,
                     restart_backoff=0.2, restart_backoff_cap=1.0),
        registry=reg)

    slo = SLO(p95_ms=args.slo_p95_ms, max_queue=args.slo_max_queue,
              min_replicas=1, max_replicas=args.replicas)
    scaler = Autoscaler(
        router, slo,
        AutoscalerConfig(interval_s=args.tick_s, breach_ticks=2,
                         calm_ticks=3, cooldown_s=3 * args.tick_s,
                         start_at_min=False),
        registry=reg)
    # Park down to min by hand (start_at_min does the same; doing it here
    # keeps the controller loop below fully owned by the bench so every
    # decision is timestamped and countable).
    scaler._park_to_min()

    decisions = []
    stop = threading.Event()

    def controller():
        while not stop.is_set():
            try:
                d = scaler.tick()
            except Exception as e:  # a mid-death snapshot race must not
                d = {"action": "error", "breach": True, "error": repr(e)}
            d["t"] = time.monotonic()
            decisions.append(d)
            stop.wait(args.tick_s)

    if args.trace:
        trace = load_trace(args.trace)
    else:
        trace = synthesize_trace(args.requests, args.rate,
                                 seed=args.seed,
                                 prompt_len_range=(4, 16),
                                 max_new_tokens=args.max_new_tokens)
    storm_len = trace[-1]["t"] if trace else 0.0

    # the mid-storm roll target: a fresh set of weights, committed +
    # health-stamped the same way the async checkpointer publishes them
    import tempfile
    tmp = tempfile.mkdtemp(prefix="bench_fleet_")
    ckpt = os.path.join(tmp, "ckpt-step1")
    commit_checkpoint({"model": _tiny_model().state_dict()}, ckpt,
                      healthy=True, step=1)
    swapper = WeightSwapper(router, reg, quiesce_timeout=60.0,
                            probe_timeout=60.0)

    roll_report = {}
    roll_recompiles = [0]
    roll_done = threading.Event()
    kill_done = []

    def _serving_healthy():
        parked = set(router.parked_ids())
        return [r for r in router.replicas
                if r.state == "HEALTHY" and r.replica_id not in parked]

    def roller():
        # disaster 2: roll new weights while the storm is still falling.
        # Wait for the autoscaler to have scaled up (>= 2 serving
        # replicas) so the roll exercises the multi-replica sequence and
        # the armed weight_swap:2:slow_io actually fires mid-roll.
        t_deadline = time.monotonic() + max(2.0, storm_len * 0.7)
        time.sleep(max(1.0, storm_len * args.roll_at))
        while len(_serving_healthy()) < min(2, args.replicas) \
                and time.monotonic() < t_deadline:
            time.sleep(0.1)
        before = _total_misses(router)
        try:
            roll_report.update(swapper.roll(ckpt))
        except Exception as e:
            roll_report.update({"error": repr(e), "aborted": True})
        roll_recompiles[0] = _total_misses(router) - before
        roll_done.set()

    def saboteur():
        # disaster 3: hard-kill the busiest replica mid-storm — AFTER the
        # roll finishes, so the kill proves EngineKilled retry recovery
        # rather than corrupting a swap probe in flight (a kill during a
        # swap is a legitimate production hazard, but it makes the gate's
        # rollback-free invariant nondeterministic)
        time.sleep(max(0.5, storm_len * args.kill_at))
        roll_done.wait(timeout=max(5.0, storm_len))
        victims = [r for r in _serving_healthy() if not r.paused]
        if victims:
            v = max(victims, key=lambda r: r.outstanding)
            v.kill("bench-fleet chaos storm")
            kill_done.append(v.replica_id)

    ctrl = threading.Thread(target=controller, daemon=True,
                            name="bench-fleet-controller")
    sab = threading.Thread(target=saboteur, daemon=True)
    rol = threading.Thread(target=roller, daemon=True)

    replayer = TraceReplayer(router, trace, vocab=64,
                             max_retries=args.max_retries,
                             retry_delay=0.05,
                             request_timeout=args.request_timeout,
                             workers=args.workers)
    t0 = time.monotonic()
    ctrl.start()
    sab.start()
    rol.start()
    replay = replayer.run()
    storm_end = time.monotonic()
    sab.join(timeout=30)
    rol.join(timeout=120)

    # convergence: keep ticking until the controller reports calm_ticks
    # consecutive in-SLO decisions (or the patience budget runs out)
    deadline = storm_end + args.converge_timeout
    while time.monotonic() < deadline:
        tail = [d for d in decisions if d["t"] > storm_end]
        calm = 0
        for d in tail:
            calm = calm + 1 if not d.get("breach") else 0
        if calm >= scaler.config.calm_ticks:
            break
        time.sleep(args.tick_s)
    stop.set()
    ctrl.join(timeout=10)

    post = [d for d in decisions if d["t"] > storm_end]
    recovery_ticks = 0
    for d in post:  # ticks until the FIRST in-SLO decision after the storm
        if not d.get("breach"):
            break
        recovery_ticks += 1
    converged = any(not d.get("breach") for d in post)

    healthz = router.healthz()
    snap = router.fleet_snapshot()
    doc = {
        "bench": "fleet",
        "replicas": args.replicas,
        "fault_spec": "" if args.no_faults else FAULT_SPEC,
        "storm": {
            "requests": len(trace),
            "rate_rps": args.rate if not args.trace else None,
            "storm_len_s": round(storm_len, 2),
            "wall_s": round(storm_end - t0, 2),
        },
        "replay": replay,
        "autoscaler": {
            "ticks": len(decisions),
            "scale_ups": int(reg.stats().get(
                "fleet.autoscale.scale_ups", 0)),
            "scale_downs": int(reg.stats().get(
                "fleet.autoscale.scale_downs", 0)),
            "recovery_ticks": recovery_ticks,
            "converged": converged,
        },
        "kill": {"count": len(kill_done), "replicas": kill_done},
        "swap": {
            "swapped": roll_report.get("swapped", []),
            "skipped": roll_report.get("skipped", []),
            "rolled_back": roll_report.get("rolled_back"),
            "aborted": roll_report.get("aborted", True),
            "error": roll_report.get("error"),
            "downtime_p95_ms": round(
                reg.quantile("fleet.swap.downtime_ms", 0.95), 3),
            "recompiles": roll_recompiles[0],
        },
        "end_state": {
            "healthz": healthz["status"],
            "active_replicas": snap["active_replicas"],
            "degraded": snap["degraded"],
            "budget_remaining": snap["budget_remaining"],
        },
    }
    router.drain(timeout=60)
    return doc


def check(doc, baseline=None):
    """The acceptance bars. Structural invariants are absolute; the
    recovery budget is relative to the committed baseline with generous
    slack (CI boxes are slower than the baseline machine, and the tick
    count depends on compile times)."""
    problems = []
    rep, auto, swap = doc["replay"], doc["autoscaler"], doc["swap"]
    if rep["dropped"] != 0:
        problems.append(f"dropped {rep['dropped']} accepted requests "
                        f"(the fleet promises zero drops; retries are "
                        f"allowed, losses are not)")
    if rep["completed"] != rep["offered"]:
        problems.append(f"completed {rep['completed']} != offered "
                        f"{rep['offered']}")
    if auto["scale_ups"] < 1:
        problems.append("the storm never scaled the fleet up "
                        "(scale_ups == 0)")
    if not auto["converged"]:
        problems.append("the controller never converged back inside the "
                        "SLO after the storm")
    if doc["kill"]["count"] < 1 and doc["fault_spec"]:
        problems.append("the chaos kill never fired")
    if swap["aborted"]:
        problems.append(f"the weight roll aborted: {swap['error']}")
    if swap["rolled_back"] is not None:
        problems.append(f"replica {swap['rolled_back']} rolled back "
                        f"during the storm roll (probe failed)")
    if not swap["swapped"]:
        problems.append("the weight roll swapped zero replicas")
    if swap["recompiles"] != 0:
        problems.append(f"{swap['recompiles']} recompile(s) across the "
                        f"weight roll — swaps must reuse the spec-keyed "
                        f"executables")
    if doc["end_state"]["healthz"] not in ("ok", "degraded"):
        problems.append(f"end-state healthz is "
                        f"{doc['end_state']['healthz']!r}")
    if baseline:
        b = baseline.get("autoscaler", {})
        budget = max(2 * b.get("recovery_ticks", 0) + 4,
                     b.get("recovery_ticks", 0) + 10)
        if auto["recovery_ticks"] > budget:
            problems.append(
                f"recovery took {auto['recovery_ticks']} ticks "
                f"(baseline {b.get('recovery_ticks')}, budget {budget})")
        bswap = baseline.get("swap", {})
        base_dt = bswap.get("downtime_p95_ms", 0.0)
        if base_dt and swap["downtime_p95_ms"] > 10 * base_dt:
            problems.append(
                f"swap downtime p95 {swap['downtime_p95_ms']:.1f}ms "
                f"> 10x baseline {base_dt:.1f}ms")
    return problems


def run_migrate(args) -> dict:
    """The zero-loss serving storm (``--migrate``): live streams ride
    through a slow_io-widened weight roll (sequence migration) and a
    hard replica kill (journal replay), and every assembled stream must
    match an undisturbed reference engine bit for bit."""
    from paddle_tpu.utils import resilience
    if not args.no_faults:
        os.environ["PADDLE_TPU_FAULT_SPEC"] = FAULT_SPEC_MIGRATE
        os.environ.setdefault("PADDLE_TPU_FAULT_SLOW_IO_S", "0.3")
        resilience._reset_fault_injector_for_tests()

    import random
    import tempfile
    from paddle_tpu.core.monitor import StatRegistry
    from paddle_tpu.incubate.checkpoint import commit_checkpoint
    from paddle_tpu.serving.llm import LLMEngine, LLMEngineConfig
    from paddle_tpu.serving.router import (Router, RouterConfig,
                                           llm_replica_factory)
    from paddle_tpu.serving.fleet import WeightSwapper

    # ONE set of weights everywhere — fleet, roll target, and reference
    # engine — so the bitwise gate is version-independent: a stream that
    # migrates across the roll must still equal the reference.
    state = _tiny_model().state_dict()

    def make_model(_replica=None):
        m = _tiny_model()
        m.set_state_dict(state)
        return m

    # streams must be LONG relative to a decode tick, or they finish
    # before the roll/kill can catch them mid-flight (a CPU tick on the
    # tiny model is ~ms; 32 tokens keeps a stream alive for a window
    # the chaos can actually hit)
    n_new = args.stream_tokens

    def _paged_cfg():
        return LLMEngineConfig(
            num_slots=args.slots, max_seq=64, max_queue=256,
            kv_layout="paged", page_size=8, warmup=True,
            default_max_new_tokens=n_new)

    rng = random.Random(args.seed)
    n_streams = args.streams
    prompts = [[rng.randrange(1, 64) for _ in range(rng.randrange(4, 13))]
               for _ in range(n_streams)]

    # the ground truth: greedy streams from an engine nothing happens to
    ref_eng = LLMEngine(make_model(), _paged_cfg(),
                        registry=StatRegistry())
    refs = [ref_eng.submit(p, max_new_tokens=n_new)
            .result(timeout=args.request_timeout)["tokens"]
            for p in prompts]
    ref_eng.drain(timeout=60)

    reg = StatRegistry()
    router = Router(
        llm_replica_factory(make_model, _paged_cfg()),
        RouterConfig(num_replicas=args.replicas, kind="llm",
                     health_interval=0.1, max_restarts=8,
                     restart_backoff=0.2, restart_backoff_cap=1.0),
        registry=reg)

    tmp = tempfile.mkdtemp(prefix="bench_fleet_migrate_")
    ckpt = os.path.join(tmp, "ckpt-step1")
    commit_checkpoint({"model": make_model().state_dict()}, ckpt,
                      healthy=True, step=1)
    swapper = WeightSwapper(router, reg, quiesce_timeout=60.0,
                            probe_timeout=60.0)

    counts = {"completed": 0, "dropped": 0, "mismatched": 0, "retries": 0}
    counts_lock = threading.Lock()

    def one_stream(p_i) -> None:
        # production 503 handling: anything retryable (EngineKilled on a
        # queued request, a draining/paused window, a divergence-failed
        # sampled resume) restarts the request from scratch; migrated and
        # replayed streams keep flowing through the SAME iterator.
        for attempt in range(args.max_retries):
            try:
                req = router.submit(prompts[p_i],
                                    max_new_tokens=n_new,
                                    stream=True)
                toks = list(req.iter_tokens(timeout=args.request_timeout))
                with counts_lock:
                    counts["completed"] += 1
                    if toks != refs[p_i]:
                        counts["mismatched"] += 1
                return
            except Exception:  # noqa: BLE001 -- the client's whole job is retrying retryable failures
                with counts_lock:
                    counts["retries"] += 1
                time.sleep(0.05 * min(attempt + 1, 10))
        with counts_lock:
            counts["dropped"] += 1

    def client(idx, stop):
        # sustained load: tiny-model decode ticks are ~ms on CPU, so a
        # one-shot stream is gone before any chaos can catch it — each
        # client keeps streaming (cycling the prompt pool) until its
        # wave's chaos event has fully played out
        step = 0
        while not stop.is_set():
            one_stream((idx + step * n_streams) % len(prompts))
            step += 1

    def _serving(unpaused=True):
        return [r for r in router.replicas
                if r.state == "HEALTHY" and (not unpaused or not r.paused)]

    def _wait_inflight(want, deadline):
        """Block until some serving replica has >= want in-flight
        sequences (returns it), or the deadline passes (returns the
        busiest anyway — the storm must not hang on a quiet fleet)."""
        while time.monotonic() < deadline:
            live = _serving()
            if live:
                busiest = max(live, key=lambda r: r.outstanding)
                if busiest.outstanding >= want:
                    return busiest
            time.sleep(0.02)
        live = _serving()
        return max(live, key=lambda r: r.outstanding) if live else None

    roll_report: dict = {}
    roll_recompiles = [0]
    kill_info: dict = {"replica": None, "inflight_at_kill": 0}

    def roller():
        # the roll starts only once streams are genuinely in flight, so
        # migrate-out has sequences to move through the slow_io window
        _wait_inflight(2, time.monotonic() + 30.0)
        before = _total_misses(router)
        try:
            roll_report.update(swapper.roll(ckpt))
        except Exception as e:
            roll_report.update({"error": repr(e), "aborted": True})
        roll_recompiles[0] = _total_misses(router) - before

    def saboteur():
        # kill only once the victim carries >= kill_min_inflight live
        # streams — the crash-recovery path must have real work to do
        victim = _wait_inflight(args.kill_min_inflight,
                                time.monotonic() + 30.0)
        if victim is not None:
            kill_info["inflight_at_kill"] = victim.outstanding
            kill_info["min_inflight"] = args.kill_min_inflight
            kill_info["replica"] = victim.replica_id
            victim.kill("bench-fleet migration storm")

    # two sustained waves, run back to back: wave 1 holds streams in
    # flight for the whole weight roll (migrate-out through the slow_io
    # window), wave 2 does the same for the kill so the victim is
    # guaranteed to be carrying live sequences when it dies
    t0 = time.monotonic()
    stop_roll = threading.Event()
    wave1 = [threading.Thread(target=client, args=(i, stop_roll),
                              daemon=True, name=f"bench-migrate-w1-{i}")
             for i in range(n_streams)]
    for t in wave1:
        t.start()
        time.sleep(1.0 / args.rate)   # staggered arrivals
    rol = threading.Thread(target=roller, daemon=True)
    rol.start()
    rol.join(timeout=240.0)
    stop_roll.set()
    for t in wave1:
        t.join(timeout=args.request_timeout + 60.0)

    stop_kill = threading.Event()
    wave2 = [threading.Thread(target=client, args=(i, stop_kill),
                              daemon=True, name=f"bench-migrate-w2-{i}")
             for i in range(n_streams)]
    for t in wave2:
        t.start()                     # burst: pile up in-flight streams
    sab = threading.Thread(target=saboteur, daemon=True)
    sab.start()
    sab.join(timeout=60.0)
    time.sleep(2.0)  # let journal replay land the recovered streams
    stop_kill.set()
    for t in wave2:
        t.join(timeout=args.request_timeout + 60.0)
    wall = time.monotonic() - t0

    stats = reg.stats()

    def _sum_suffix(suffix):
        return int(sum(v for k, v in stats.items()
                       if k.endswith(suffix) and isinstance(v, (int, float))))

    doc = {
        "bench": "fleet-migrate",
        "replicas": args.replicas,
        "fault_spec": "" if args.no_faults else FAULT_SPEC_MIGRATE,
        "streams": {
            # sustained waves complete as many streams as the chaos
            # windows allow; min_expected is the floor the check enforces
            "min_expected": n_streams,
            "completed": counts["completed"],
            "dropped": counts["dropped"],
            "mismatched": counts["mismatched"],
            "retries": counts["retries"],
            "wall_s": round(wall, 2),
        },
        "migrate": {
            "exported": int(stats.get("fleet.migrate.sequences_exported", 0)),
            "imported": int(stats.get("fleet.migrate.sequences_imported", 0)),
            "recovered": int(stats.get("fleet.migrate.sequences_recovered", 0)),
            "failed": int(stats.get("fleet.migrate.sequences_failed", 0)),
            "export_failures": int(stats.get(
                "fleet.migrate.export_failures", 0)),
            "import_failures": int(stats.get(
                "fleet.migrate.import_failures", 0)),
            "replayed_on_engines": _sum_suffix(".recovered"),
            "divergence": _sum_suffix(".stream_divergence"),
            "latency_p95_ms": round(
                reg.quantile("fleet.migrate.latency_ms", 0.95), 3),
        },
        "swap": {
            "swapped": roll_report.get("swapped", []),
            "migrated": roll_report.get("migrated", {}),
            "rolled_back": roll_report.get("rolled_back"),
            "aborted": roll_report.get("aborted", True),
            "error": roll_report.get("error"),
            "downtime_p95_ms": round(
                reg.quantile("fleet.swap.downtime_ms", 0.95), 3),
            "recompiles": roll_recompiles[0],
        },
        "kill": kill_info,
        "end_state": {
            "healthz": router.healthz()["status"],
            "active_replicas": router.fleet_snapshot()["active_replicas"],
        },
    }
    router.drain(timeout=60)
    return doc


def check_migrate(doc, baseline=None):
    """Acceptance bars for the zero-loss storm: structural invariants
    are absolute (bitwise streams, zero drops, recompile-free roll);
    swap downtime is relative to the committed baseline — migration
    must not be SLOWER than the quiesce-drain roll it replaces."""
    problems = []
    st, mig, swap = doc["streams"], doc["migrate"], doc["swap"]
    if st["dropped"] != 0:
        problems.append(f"dropped {st['dropped']} streams (zero-loss "
                        f"serving promises zero drops)")
    if st["completed"] < st["min_expected"]:
        problems.append(f"completed only {st['completed']} streams "
                        f"(< {st['min_expected']}) — the storm never "
                        f"sustained real traffic")
    if st["mismatched"] != 0:
        problems.append(
            f"{st['mismatched']} stream(s) differ from the reference — "
            f"a duplicated, missing, or divergent token reached a client")
    if mig["exported"] < 1:
        problems.append("no sequence was ever exported — the roll never "
                        "exercised migrate-out")
    if mig["imported"] + mig["replayed_on_engines"] < 1:
        problems.append("no sequence was adopted by a sibling (imported "
                        "+ replayed == 0)")
    if swap["aborted"]:
        problems.append(f"the weight roll aborted: {swap['error']}")
    if swap["rolled_back"] is not None:
        problems.append(f"replica {swap['rolled_back']} rolled back "
                        f"during the roll (probe failed)")
    if swap["recompiles"] != 0:
        problems.append(f"{swap['recompiles']} recompile(s) across the "
                        f"migrating roll — sequence import must reuse "
                        f"the spec-keyed executables")
    if doc["fault_spec"]:
        if doc["kill"]["replica"] is None:
            problems.append("the chaos kill never fired")
        else:
            want = doc["kill"].get("min_inflight", 1)
            if doc["kill"]["inflight_at_kill"] < want:
                problems.append(
                    f"the kill caught only "
                    f"{doc['kill']['inflight_at_kill']} in-flight "
                    f"streams (needed >= {want} for a real recovery "
                    f"test)")
            if mig["recovered"] < 1:
                problems.append(
                    "the kill fired but no sequence was journal-"
                    "replayed onto a survivor (recovered == 0)")
    if doc["end_state"]["healthz"] not in ("ok", "degraded"):
        problems.append(f"end-state healthz is "
                        f"{doc['end_state']['healthz']!r}")
    if baseline:
        bswap = baseline.get("swap", {})
        base_dt = bswap.get("downtime_p95_ms", 0.0)
        if base_dt and swap["downtime_p95_ms"] > 10 * base_dt:
            problems.append(
                f"swap downtime p95 {swap['downtime_p95_ms']:.1f}ms "
                f"> 10x baseline {base_dt:.1f}ms — migration made the "
                f"roll slower than the drain it replaced")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--requests", type=int, default=120)
    ap.add_argument("--rate", type=float, default=12.0,
                    help="synthetic storm offered load, req/s")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trace", default=None,
                    help="replay this recorded JSONL trace instead of "
                         "synthesizing a storm")
    ap.add_argument("--max-new-tokens", type=int, default=4)
    ap.add_argument("--slo-p95-ms", type=float, default=750.0)
    ap.add_argument("--slo-max-queue", type=int, default=24)
    ap.add_argument("--tick-s", type=float, default=0.25,
                    help="autoscaler controller tick period")
    ap.add_argument("--kill-at", type=float, default=0.45,
                    help="kill a replica at this fraction of the storm")
    ap.add_argument("--roll-at", type=float, default=0.25,
                    help="start the weight roll at this storm fraction")
    ap.add_argument("--max-retries", type=int, default=40)
    ap.add_argument("--request-timeout", type=float, default=120.0)
    ap.add_argument("--workers", type=int, default=48)
    ap.add_argument("--converge-timeout", type=float, default=60.0)
    ap.add_argument("--migrate", action="store_true",
                    help="run the zero-loss serving storm instead: live "
                         "stream migration through a weight roll + "
                         "journal replay through a replica kill, gated "
                         "bitwise against an undisturbed reference")
    ap.add_argument("--streams", type=int, default=24,
                    help="concurrent greedy token streams (--migrate)")
    ap.add_argument("--stream-tokens", type=int, default=32,
                    help="tokens per stream (--migrate); long enough "
                         "that the roll and the kill catch streams "
                         "mid-flight")
    ap.add_argument("--kill-min-inflight", type=int, default=4,
                    help="kill waits until the victim carries at least "
                         "this many live streams (--migrate)")
    ap.add_argument("--no-faults", action="store_true",
                    help="storm without the injected disasters (latency "
                         "baseline of the harness itself)")
    ap.add_argument("--check", action="store_true",
                    help="gate the acceptance bars + baseline budgets")
    ap.add_argument("--write-baseline", action="store_true",
                    help="refresh the committed baseline")
    ap.add_argument("--baseline", default=BASELINE)
    args = ap.parse_args(argv)

    doc = run_migrate(args) if args.migrate else run_chaos(args)
    json.dump(doc, sys.stdout, indent=2)
    print()

    if args.write_baseline:
        base = {
            "version": 1,
            "autoscaler": {
                "recovery_ticks": doc["autoscaler"]["recovery_ticks"]},
            "swap": {
                "downtime_p95_ms": doc["swap"]["downtime_p95_ms"]},
            "replay": {"dropped": 0},
        }
        with open(args.baseline, "w") as f:
            json.dump(base, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"bench fleet: baseline written to {args.baseline}",
              file=sys.stderr)

    if args.check:
        baseline = None
        try:
            with open(args.baseline) as f:
                baseline = json.load(f)
        except (OSError, ValueError):
            print(f"bench fleet: no baseline at {args.baseline} "
                  f"(absolute budgets skipped)", file=sys.stderr)
        problems = (check_migrate(doc, baseline) if args.migrate
                    else check(doc, baseline))
        if problems:
            for p in problems:
                print(f"FAIL: {p}", file=sys.stderr)
            return 1
        print("OK: " + ("zero-loss: streams bitwise, zero drops, "
                        "migrating roll clean" if args.migrate else
                        "zero drops, fleet scaled, roll clean, "
                        "SLO recovered"),
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
