#!/usr/bin/env python
"""Traffic-replay chaos harness for the serving fleet — the self-driving
proof, printed as one JSON document.

    python -m tools.bench_fleet                   # run the chaos storm
    python -m tools.bench_fleet --check           # CI gate (run_tests.py
                                                  #   --bench-fleet)
    python -m tools.bench_fleet --write-baseline  # refresh the committed
                                                  #   bench_fleet_baseline.json
    python -m tools.bench_fleet --trace my.jsonl  # replay a recorded trace

One storm, three injected disasters, one verdict. A seeded Poisson trace
(or ``--trace``, recorded from a live router by
:class:`~paddle_tpu.serving.fleet.TraceRecorder`) is replayed with
arrival-time fidelity against a 3-shell LLM router parked down to one
serving replica, while:

1. the SLO-aware autoscaler runs its controller loop — the cold-start
   latency spike breaches the SLO and the fleet scales up through the
   budgeted unpark path, with ``replica_boot:4:disk_full`` armed so the
   FIRST scale-up boot dies on ``ENOSPC`` (the health sweep finishes
   that boot on the backoff schedule: a failed scale-up is just a
   counted resurrection);
2. a live weight hot-swap rolls a committed checkpoint across the
   serving replicas mid-storm, with ``weight_swap:2:slow_io`` stretching
   one swap window — the cache-miss delta across the roll must be ZERO
   (executables are keyed by spec/dtype, so new weights reuse them);
3. a replica is hard-killed mid-storm (the in-process SIGKILL analog:
   queued + in-flight requests die with ``EngineKilled`` and the clients
   retry, exactly like production 503 handling).

The verdict: every offered request completes (**drops == 0** — retries
are allowed, losses are not), the fleet scales up at least once, the
roll finishes un-aborted with zero recompiles, and the controller
converges back inside the SLO within the committed tick budget after
the storm ends. Absolute latencies are machine-dependent and not gated;
the *structural* counters (drops, scale-ups, rollbacks, recompiles) and
the *relative* recovery budget are the invariants
(``bench_fleet_baseline.json``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "bench_fleet_baseline.json")

#: the storm's armed disasters (see docs/fault_tolerance.md): the 4th
#: replica_boot is the first scale-up boot (3 shells boot at router
#: construction), and the 2nd weight_swap is mid-roll.
FAULT_SPEC = "replica_boot:4:disk_full,weight_swap:2:slow_io"


def _tiny_model():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    m = GPTForCausalLM(GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        max_position_embeddings=128, hidden_dropout_prob=0.0,
        attention_dropout_prob=0.0))
    m.eval()
    return m


def _total_misses(router):
    return sum(r.engine.cache.stats()["misses"]
               for r in router.replicas if r.engine is not None)


def run_chaos(args) -> dict:
    # Arm the injector BEFORE any engine exists; the singleton parses the
    # environment once per process.
    from paddle_tpu.utils import resilience
    if not args.no_faults:
        os.environ["PADDLE_TPU_FAULT_SPEC"] = FAULT_SPEC
        os.environ.setdefault("PADDLE_TPU_FAULT_SLOW_IO_S", "0.3")
        resilience._reset_fault_injector_for_tests()

    from paddle_tpu.core.monitor import StatRegistry
    from paddle_tpu.incubate.checkpoint import commit_checkpoint
    from paddle_tpu.serving.llm import LLMEngineConfig
    from paddle_tpu.serving.router import (Router, RouterConfig,
                                           llm_replica_factory)
    from paddle_tpu.serving.fleet import (SLO, Autoscaler, AutoscalerConfig,
                                          TraceReplayer, WeightSwapper,
                                          load_trace, synthesize_trace)

    cfg = LLMEngineConfig(
        num_slots=args.slots, max_seq=64, max_queue=256, warmup=False,
        default_max_new_tokens=args.max_new_tokens)
    reg = StatRegistry()
    router = Router(
        llm_replica_factory(lambda r: _tiny_model(), cfg),
        RouterConfig(num_replicas=args.replicas, kind="llm",
                     health_interval=0.1, max_restarts=8,
                     restart_backoff=0.2, restart_backoff_cap=1.0),
        registry=reg)

    slo = SLO(p95_ms=args.slo_p95_ms, max_queue=args.slo_max_queue,
              min_replicas=1, max_replicas=args.replicas)
    scaler = Autoscaler(
        router, slo,
        AutoscalerConfig(interval_s=args.tick_s, breach_ticks=2,
                         calm_ticks=3, cooldown_s=3 * args.tick_s,
                         start_at_min=False),
        registry=reg)
    # Park down to min by hand (start_at_min does the same; doing it here
    # keeps the controller loop below fully owned by the bench so every
    # decision is timestamped and countable).
    scaler._park_to_min()

    decisions = []
    stop = threading.Event()

    def controller():
        while not stop.is_set():
            try:
                d = scaler.tick()
            except Exception as e:  # a mid-death snapshot race must not
                d = {"action": "error", "breach": True, "error": repr(e)}
            d["t"] = time.monotonic()
            decisions.append(d)
            stop.wait(args.tick_s)

    if args.trace:
        trace = load_trace(args.trace)
    else:
        trace = synthesize_trace(args.requests, args.rate,
                                 seed=args.seed,
                                 prompt_len_range=(4, 16),
                                 max_new_tokens=args.max_new_tokens)
    storm_len = trace[-1]["t"] if trace else 0.0

    # the mid-storm roll target: a fresh set of weights, committed +
    # health-stamped the same way the async checkpointer publishes them
    import tempfile
    tmp = tempfile.mkdtemp(prefix="bench_fleet_")
    ckpt = os.path.join(tmp, "ckpt-step1")
    commit_checkpoint({"model": _tiny_model().state_dict()}, ckpt,
                      healthy=True, step=1)
    swapper = WeightSwapper(router, reg, quiesce_timeout=60.0,
                            probe_timeout=60.0)

    roll_report = {}
    roll_recompiles = [0]
    roll_done = threading.Event()
    kill_done = []

    def _serving_healthy():
        parked = set(router.parked_ids())
        return [r for r in router.replicas
                if r.state == "HEALTHY" and r.replica_id not in parked]

    def roller():
        # disaster 2: roll new weights while the storm is still falling.
        # Wait for the autoscaler to have scaled up (>= 2 serving
        # replicas) so the roll exercises the multi-replica sequence and
        # the armed weight_swap:2:slow_io actually fires mid-roll.
        t_deadline = time.monotonic() + max(2.0, storm_len * 0.7)
        time.sleep(max(1.0, storm_len * args.roll_at))
        while len(_serving_healthy()) < min(2, args.replicas) \
                and time.monotonic() < t_deadline:
            time.sleep(0.1)
        before = _total_misses(router)
        try:
            roll_report.update(swapper.roll(ckpt))
        except Exception as e:
            roll_report.update({"error": repr(e), "aborted": True})
        roll_recompiles[0] = _total_misses(router) - before
        roll_done.set()

    def saboteur():
        # disaster 3: hard-kill the busiest replica mid-storm — AFTER the
        # roll finishes, so the kill proves EngineKilled retry recovery
        # rather than corrupting a swap probe in flight (a kill during a
        # swap is a legitimate production hazard, but it makes the gate's
        # rollback-free invariant nondeterministic)
        time.sleep(max(0.5, storm_len * args.kill_at))
        roll_done.wait(timeout=max(5.0, storm_len))
        victims = [r for r in _serving_healthy() if not r.paused]
        if victims:
            v = max(victims, key=lambda r: r.outstanding)
            v.kill("bench-fleet chaos storm")
            kill_done.append(v.replica_id)

    ctrl = threading.Thread(target=controller, daemon=True,
                            name="bench-fleet-controller")
    sab = threading.Thread(target=saboteur, daemon=True)
    rol = threading.Thread(target=roller, daemon=True)

    replayer = TraceReplayer(router, trace, vocab=64,
                             max_retries=args.max_retries,
                             retry_delay=0.05,
                             request_timeout=args.request_timeout,
                             workers=args.workers)
    t0 = time.monotonic()
    ctrl.start()
    sab.start()
    rol.start()
    replay = replayer.run()
    storm_end = time.monotonic()
    sab.join(timeout=30)
    rol.join(timeout=120)

    # convergence: keep ticking until the controller reports calm_ticks
    # consecutive in-SLO decisions (or the patience budget runs out)
    deadline = storm_end + args.converge_timeout
    while time.monotonic() < deadline:
        tail = [d for d in decisions if d["t"] > storm_end]
        calm = 0
        for d in tail:
            calm = calm + 1 if not d.get("breach") else 0
        if calm >= scaler.config.calm_ticks:
            break
        time.sleep(args.tick_s)
    stop.set()
    ctrl.join(timeout=10)

    post = [d for d in decisions if d["t"] > storm_end]
    recovery_ticks = 0
    for d in post:  # ticks until the FIRST in-SLO decision after the storm
        if not d.get("breach"):
            break
        recovery_ticks += 1
    converged = any(not d.get("breach") for d in post)

    healthz = router.healthz()
    snap = router.fleet_snapshot()
    doc = {
        "bench": "fleet",
        "replicas": args.replicas,
        "fault_spec": "" if args.no_faults else FAULT_SPEC,
        "storm": {
            "requests": len(trace),
            "rate_rps": args.rate if not args.trace else None,
            "storm_len_s": round(storm_len, 2),
            "wall_s": round(storm_end - t0, 2),
        },
        "replay": replay,
        "autoscaler": {
            "ticks": len(decisions),
            "scale_ups": int(reg.stats().get(
                "fleet.autoscale.scale_ups", 0)),
            "scale_downs": int(reg.stats().get(
                "fleet.autoscale.scale_downs", 0)),
            "recovery_ticks": recovery_ticks,
            "converged": converged,
        },
        "kill": {"count": len(kill_done), "replicas": kill_done},
        "swap": {
            "swapped": roll_report.get("swapped", []),
            "skipped": roll_report.get("skipped", []),
            "rolled_back": roll_report.get("rolled_back"),
            "aborted": roll_report.get("aborted", True),
            "error": roll_report.get("error"),
            "downtime_p95_ms": round(
                reg.quantile("fleet.swap.downtime_ms", 0.95), 3),
            "recompiles": roll_recompiles[0],
        },
        "end_state": {
            "healthz": healthz["status"],
            "active_replicas": snap["active_replicas"],
            "degraded": snap["degraded"],
            "budget_remaining": snap["budget_remaining"],
        },
    }
    router.drain(timeout=60)
    return doc


def check(doc, baseline=None):
    """The acceptance bars. Structural invariants are absolute; the
    recovery budget is relative to the committed baseline with generous
    slack (CI boxes are slower than the baseline machine, and the tick
    count depends on compile times)."""
    problems = []
    rep, auto, swap = doc["replay"], doc["autoscaler"], doc["swap"]
    if rep["dropped"] != 0:
        problems.append(f"dropped {rep['dropped']} accepted requests "
                        f"(the fleet promises zero drops; retries are "
                        f"allowed, losses are not)")
    if rep["completed"] != rep["offered"]:
        problems.append(f"completed {rep['completed']} != offered "
                        f"{rep['offered']}")
    if auto["scale_ups"] < 1:
        problems.append("the storm never scaled the fleet up "
                        "(scale_ups == 0)")
    if not auto["converged"]:
        problems.append("the controller never converged back inside the "
                        "SLO after the storm")
    if doc["kill"]["count"] < 1 and doc["fault_spec"]:
        problems.append("the chaos kill never fired")
    if swap["aborted"]:
        problems.append(f"the weight roll aborted: {swap['error']}")
    if swap["rolled_back"] is not None:
        problems.append(f"replica {swap['rolled_back']} rolled back "
                        f"during the storm roll (probe failed)")
    if not swap["swapped"]:
        problems.append("the weight roll swapped zero replicas")
    if swap["recompiles"] != 0:
        problems.append(f"{swap['recompiles']} recompile(s) across the "
                        f"weight roll — swaps must reuse the spec-keyed "
                        f"executables")
    if doc["end_state"]["healthz"] not in ("ok", "degraded"):
        problems.append(f"end-state healthz is "
                        f"{doc['end_state']['healthz']!r}")
    if baseline:
        b = baseline.get("autoscaler", {})
        budget = max(2 * b.get("recovery_ticks", 0) + 4,
                     b.get("recovery_ticks", 0) + 10)
        if auto["recovery_ticks"] > budget:
            problems.append(
                f"recovery took {auto['recovery_ticks']} ticks "
                f"(baseline {b.get('recovery_ticks')}, budget {budget})")
        bswap = baseline.get("swap", {})
        base_dt = bswap.get("downtime_p95_ms", 0.0)
        if base_dt and swap["downtime_p95_ms"] > 10 * base_dt:
            problems.append(
                f"swap downtime p95 {swap['downtime_p95_ms']:.1f}ms "
                f"> 10x baseline {base_dt:.1f}ms")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--requests", type=int, default=120)
    ap.add_argument("--rate", type=float, default=12.0,
                    help="synthetic storm offered load, req/s")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trace", default=None,
                    help="replay this recorded JSONL trace instead of "
                         "synthesizing a storm")
    ap.add_argument("--max-new-tokens", type=int, default=4)
    ap.add_argument("--slo-p95-ms", type=float, default=750.0)
    ap.add_argument("--slo-max-queue", type=int, default=24)
    ap.add_argument("--tick-s", type=float, default=0.25,
                    help="autoscaler controller tick period")
    ap.add_argument("--kill-at", type=float, default=0.45,
                    help="kill a replica at this fraction of the storm")
    ap.add_argument("--roll-at", type=float, default=0.25,
                    help="start the weight roll at this storm fraction")
    ap.add_argument("--max-retries", type=int, default=40)
    ap.add_argument("--request-timeout", type=float, default=120.0)
    ap.add_argument("--workers", type=int, default=48)
    ap.add_argument("--converge-timeout", type=float, default=60.0)
    ap.add_argument("--no-faults", action="store_true",
                    help="storm without the injected disasters (latency "
                         "baseline of the harness itself)")
    ap.add_argument("--check", action="store_true",
                    help="gate the acceptance bars + baseline budgets")
    ap.add_argument("--write-baseline", action="store_true",
                    help="refresh the committed baseline")
    ap.add_argument("--baseline", default=BASELINE)
    args = ap.parse_args(argv)

    doc = run_chaos(args)
    json.dump(doc, sys.stdout, indent=2)
    print()

    if args.write_baseline:
        base = {
            "version": 1,
            "autoscaler": {
                "recovery_ticks": doc["autoscaler"]["recovery_ticks"]},
            "swap": {
                "downtime_p95_ms": doc["swap"]["downtime_p95_ms"]},
            "replay": {"dropped": 0},
        }
        with open(args.baseline, "w") as f:
            json.dump(base, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"bench fleet: baseline written to {args.baseline}",
              file=sys.stderr)

    if args.check:
        baseline = None
        try:
            with open(args.baseline) as f:
                baseline = json.load(f)
        except (OSError, ValueError):
            print(f"bench fleet: no baseline at {args.baseline} "
                  f"(absolute budgets skipped)", file=sys.stderr)
        problems = check(doc, baseline)
        if problems:
            for p in problems:
                print(f"FAIL: {p}", file=sys.stderr)
            return 1
        print("OK: zero drops, fleet scaled, roll clean, SLO recovered",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
