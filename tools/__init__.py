# tools/ is importable so `python -m tools.analyze` works; the scripts in
# this directory remain directly runnable (`python tools/<script>.py`).
