#!/usr/bin/env python
"""Offered-load sweep for the serving engine — throughput, tail latency,
and recompile count per load level, printed as one JSON document.

    python -m tools.bench_serving                      # synthetic MLP
    python -m tools.bench_serving --model /path/prefix # jit.save artifact
    python -m tools.bench_serving --loads 100,500,0    # 0 = unthrottled

Each sweep drives ``--requests`` mixed-size requests at the offered rate
(requests/s; 0 means as fast as submission allows) through a fresh
:class:`~paddle_tpu.serving.Engine` with its own StatRegistry, so the
latency histograms and cache counters are per-sweep. The headline numbers:
``throughput_rps``, ``p50_ms``/``p99_ms`` (request latency), ``fill_p50``
(batch occupancy), and ``recompiles`` — which should equal the bucket
count on the first sweep and ZERO on later sweeps when ``--share-engine``
is set (the compile-once-reuse claim, measurable).
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from concurrent.futures import wait


def _synthetic_model(dim: int = 64):
    """A jitted 2-layer MLP: each new padded shape costs one real XLA
    compile, so cache misses == compiles."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    w1 = jnp.asarray(rng.randn(dim, 4 * dim).astype(np.float32))
    w2 = jnp.asarray(rng.randn(4 * dim, dim).astype(np.float32))

    @jax.jit
    def fn(x):
        return jnp.tanh(x @ w1) @ w2

    return fn, dim


def run_sweep(engine, requests, offered_qps, sizes, dim, seed=0):
    import numpy as np
    rng = np.random.RandomState(seed)
    payloads = [rng.randn(sizes[i % len(sizes)], dim).astype(np.float32)
                for i in range(requests)]
    gap = 0.0 if not offered_qps else 1.0 / offered_qps
    t0 = time.monotonic()
    futs = []
    for i, x in enumerate(payloads):
        futs.append(engine.submit([x]))
        if gap:
            # pace submissions to the offered rate (absolute schedule so
            # slow submits don't silently lower the offered load)
            sleep_until = t0 + (i + 1) * gap
            pause = sleep_until - time.monotonic()
            if pause > 0:
                time.sleep(pause)
    wait(futs, timeout=120)
    wall = time.monotonic() - t0
    reg = engine.registry
    errors = sum(1 for f in futs if f.exception() is not None)
    rows = sum(p.shape[0] for p in payloads)
    return {
        "offered_qps": offered_qps or None,
        "requests": requests,
        "errors": errors,
        "wall_s": round(wall, 4),
        "throughput_rps": round(requests / wall, 2),
        "throughput_rows_s": round(rows / wall, 2),
        "p50_ms": round(reg.quantile("serving.latency_ms", 0.50), 3),
        "p95_ms": round(reg.quantile("serving.latency_ms", 0.95), 3),
        "p99_ms": round(reg.quantile("serving.latency_ms", 0.99), 3),
        "fill_p50": round(reg.quantile("serving.batch_fill", 0.50), 3),
        "coalesced_batches": reg.get("serving.coalesced_batches"),
        "batches": reg.get("serving.batches"),
        "recompiles": engine.cache.stats()["misses"],
        "cache": engine.cache.stats(),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None,
                    help="jit.save artifact prefix (default: synthetic MLP)")
    ap.add_argument("--requests", type=int, default=256)
    ap.add_argument("--loads", default="100,400,0",
                    help="comma-separated offered loads in req/s; 0 = "
                         "unthrottled")
    ap.add_argument("--sizes", default="1,2,3,5,8",
                    help="request row counts, cycled")
    ap.add_argument("--max-batch", type=int, default=16)
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--dim", type=int, default=64,
                    help="synthetic model feature dim")
    ap.add_argument("--share-engine", action="store_true",
                    help="reuse one engine across sweeps (recompiles go to "
                         "zero after the first)")
    args = ap.parse_args(argv)

    from paddle_tpu.core.monitor import StatRegistry
    from paddle_tpu.serving import Engine, EngineConfig

    if args.model:
        from paddle_tpu.inference import Config, create_predictor
        pred = create_predictor(Config(args.model))
        dim = pred._exported.in_avals[-1].shape[-1]

        def make_model():
            return pred
    else:
        fn, dim = _synthetic_model(args.dim)

        def make_model():
            return fn

    sizes = [int(s) for s in args.sizes.split(",") if s.strip()]
    loads = [float(x) for x in args.loads.split(",") if x.strip()]

    def make_engine():
        return Engine(make_model(), EngineConfig(
            max_batch=args.max_batch,
            max_batch_delay=args.max_delay_ms / 1000.0,
            max_queue=max(1024, args.requests)),
            registry=StatRegistry())

    engine = make_engine() if args.share_engine else None
    sweeps = []
    for i, qps in enumerate(loads):
        eng = engine if engine is not None else make_engine()
        if engine is not None:
            eng.registry.reset()
        sweeps.append(run_sweep(eng, args.requests, qps, sizes, dim, seed=i))
        if engine is None:
            eng.drain()
    if engine is not None:
        engine.drain()

    doc = {"bench": "serving", "model": args.model or "synthetic-mlp",
           "dim": dim, "max_batch": args.max_batch,
           "max_delay_ms": args.max_delay_ms,
           "share_engine": bool(args.share_engine), "sweeps": sweeps}
    json.dump(doc, sys.stdout, indent=2)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
