"""Capture an xplane profile of the fused ResNet-50 train step and leave
the trace under /tmp/rsprof for xprof parsing (docs/perf_notes.md round-4
section). Run on the TPU host:

    PYTHONPATH=/root/repo:$PYTHONPATH python tools/profile_resnet_step.py
    JAX_PLATFORMS=cpu python - <<'PY'
    from xprof.convert import raw_to_tool_data as rtd
    import glob
    xp = sorted(glob.glob("/tmp/rsprof/**/*.xplane.pb", recursive=True))
    data, _ = rtd.xspace_to_tool_data(xp, "framework_op_stats", {})
    open("/tmp/framework_op_stats.out", "wb").write(data.encode())
    PY

(two processes: tensorflow's protobuf clashes with the axon plugin's.)
"""

import os
import numpy as np
import jax, jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.optimizer as optim
from paddle_tpu.vision import models
from paddle_tpu.core import generator as _gen
from paddle_tpu.core.tensor import stable_uid

B = 256
paddle.seed(0)
net = models.resnet50(num_classes=1000)
opt = optim.Momentum(learning_rate=0.1, momentum=0.9,
                     parameters=net.parameters(), weight_decay=1e-4)
model = paddle.Model(net)
model.prepare(opt, paddle.nn.CrossEntropyLoss())
rng = np.random.RandomState(0)
x = paddle.to_tensor(rng.rand(B, 3, 224, 224).astype(np.float32))
y = paddle.to_tensor(rng.randint(0, 1000, (B,)).astype(np.int64))
with paddle.amp.auto_cast(enable=True, dtype="bfloat16"):
    model.train_batch([x], [y])
ts = model._train_step_fn
opt_states = [opt._state[stable_uid(p)] for p in ts["trainable"]]
train_raws = [p._data for p in ts["trainable"]]
fixed_raws = [ts["state"][i]._data for i in ts["fixed_pos"]]
lr = jnp.asarray(opt.get_lr(), jnp.float32)

def run(n, s0):
    global train_raws, opt_states
    loss = None
    for i in range(n):
        loss, _, train_raws, opt_states, _ = ts["fn"](
            train_raws, fixed_raws, opt_states, [x._data], [y._data],
            _gen.next_key(), lr, jnp.asarray(float(s0 + i), jnp.float32))
    return float(np.asarray(loss))

run(5, 3)  # warm
logdir = "/tmp/rsprof"
os.system(f"rm -rf {logdir}")
with jax.profiler.trace(logdir):
    run(10, 10)
print("trace done")
