#!/usr/bin/env python
"""Observability overhead microbench — instrumented vs raw hot paths, one
JSON document.

    python -m tools.bench_observability
    python -m tools.bench_observability --steps 200 --json out.json

Measures the standing tax of the span instrumentation with tracing
*disabled* (the always-on configuration) on the two hottest instrumented
paths:

* hapi train step — ``Model.train_batch`` (public wrapper: meter check +
  ``span()`` gate) vs ``Model._train_batch_impl`` (the raw body);
* LLM decode tick — ``ContinuousBatcher.tick`` vs ``_tick_inner``.

The two variants are interleaved A/B per iteration so clock drift and
thermal state cancel; medians of each variant's samples are compared. The
acceptance budget is ≤2% (tests/test_observability.py carries the
``slow``-marked assertion). With tracing disabled the wrapper cost is one
list-index check plus one shared no-op context manager — sub-µs against
hot paths that are O(100µs)+ even on tiny shapes, so the measured delta
is dominated by run-to-run noise.
"""
from __future__ import annotations

import argparse
import json
import statistics
import sys
import time


def _ab_medians(fn_a, fn_b, steps: int, warmup: int):
    """Interleaved A/B timing: run (A, B) pairs, return (median_a,
    median_b) over the post-warmup samples."""
    ta, tb = [], []
    for i in range(warmup + steps):
        t0 = time.perf_counter()
        fn_a()
        t1 = time.perf_counter()
        fn_b()
        t2 = time.perf_counter()
        if i >= warmup:
            ta.append(t1 - t0)
            tb.append(t2 - t1)
    return statistics.median(ta), statistics.median(tb)


def bench_train_step(steps: int, warmup: int, hidden: int, batch: int):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer as optim
    from paddle_tpu.static import InputSpec

    paddle.seed(0)
    net = nn.Sequential(
        nn.Linear(hidden, hidden), nn.ReLU(), nn.Linear(hidden, 1))
    model = paddle.Model(
        net, inputs=[InputSpec([None, hidden], "float32")],
        labels=[InputSpec([None, 1], "float32")])
    model.prepare(optim.SGD(learning_rate=1e-3,
                            parameters=net.parameters()),
                  nn.loss.MSELoss())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(batch, hidden).astype("float32"))
    y = paddle.to_tensor(rng.randn(batch, 1).astype("float32"))
    model.train_batch(x, y)  # compile outside the timed region

    raw, wrapped = _ab_medians(lambda: model._train_batch_impl(x, y),
                               lambda: model.train_batch(x, y),
                               steps, warmup)
    return raw, wrapped


def bench_decode_tick(steps: int, warmup: int):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.core import monitor as _mon
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving.llm import LLMEngineConfig
    from paddle_tpu.serving.llm.decode import GPTStaticDecoder, SamplingParams
    from paddle_tpu.serving.llm.scheduler import (ContinuousBatcher,
                                                  GenerationRequest)

    # max_seq must out-last the bench: prompt + warmup/steps pairs + slack
    max_seq = 8 + 2 * (warmup + steps) + 8
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=max_seq,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    net = GPTForCausalLM(cfg)
    net.eval()
    econf = LLMEngineConfig(num_slots=2, max_seq=max_seq,
                            prefill_buckets=(8,), warmup=False)
    b = ContinuousBatcher(GPTStaticDecoder(net), econf, _mon.StatRegistry())
    b.warmup()
    # one sequence that never finishes inside the bench window (no eos in
    # greedy decode of a random net is not guaranteed, so sample-free
    # greedy + max_new_tokens > total ticks + no eos_token_id)
    req = GenerationRequest(
        np.arange(1, 6, dtype=np.int32),
        SamplingParams(max_new_tokens=10 * (warmup + steps)))
    b.admit(req)

    raw, wrapped = _ab_medians(b._tick_inner, b.tick, steps, warmup)
    assert b.active == 1, "benched sequence retired mid-run"
    b.abort_all(lambda r: RuntimeError("bench done"))
    return raw, wrapped


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=100,
                    help="measured A/B pairs per path (default 100)")
    ap.add_argument("--warmup", type=int, default=10,
                    help="untimed steady-state pairs (default 10)")
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--json", default=None,
                    help="also write the JSON document to this path")
    args = ap.parse_args(argv)

    from paddle_tpu.observability import tracer
    tracer.disable()  # the configuration under test

    train_raw, train_wrapped = bench_train_step(
        args.steps, args.warmup, args.hidden, args.batch)
    tick_raw, tick_wrapped = bench_decode_tick(args.steps, args.warmup)

    def pct(raw, wrapped):
        return 100.0 * (wrapped - raw) / raw

    doc = {
        "config": {"steps": args.steps, "warmup": args.warmup,
                   "hidden": args.hidden, "batch": args.batch},
        "train_step": {
            "raw_ms": train_raw * 1e3,
            "instrumented_ms": train_wrapped * 1e3,
            "overhead_pct": pct(train_raw, train_wrapped),
        },
        "decode_tick": {
            "raw_ms": tick_raw * 1e3,
            "instrumented_ms": tick_wrapped * 1e3,
            "overhead_pct": pct(tick_raw, tick_wrapped),
        },
        "budget_pct": 2.0,
        "within_budget": (pct(train_raw, train_wrapped) <= 2.0
                          and pct(tick_raw, tick_wrapped) <= 2.0),
    }
    out = json.dumps(doc, indent=2)
    print(out)
    if args.json:
        with open(args.json, "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
