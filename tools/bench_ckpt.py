#!/usr/bin/env python
"""Sync vs async checkpoint step-time overhead, printed as one JSON doc.

    python -m tools.bench_ckpt                 # 3 param scales
    python -m tools.bench_ckpt --check         # CI gate (>=80% hidden)

Each scale runs the same synthetic train loop three ways: ``none`` (no
checkpointing — the baseline), ``sync``
(:func:`~paddle_tpu.incubate.checkpoint.commit_checkpoint` every
``--save-every`` steps, blocking the loop) and ``async``
(:class:`~paddle_tpu.incubate.checkpoint.AsyncCheckpointer`, the writer
thread overlapping the loop). Every step simulates ``--step-ms`` of
accelerator time with a GIL-released sleep (same trick as
tools/bench_router.py) — that is the window a real TPU step gives the
host, and it is what the async writer hides its I/O under.

Per-save overhead is ``(loop_time(mode) - loop_time(none)) / n_saves``,
measured over the steps loop only; the async mode's end-of-job drain is
reported separately (``drain_ms``) because it happens once at exit, not
on the step path. The headline number,

    hidden_fraction = 1 - async_overhead / sync_overhead

aggregated over all scales weighted by sync overhead, is the tentpole
claim of docs/fault_tolerance.md "Async checkpointing": the async path
must hide >= 80% of the synchronous checkpoint wall time from the train
step. ``--check`` turns that into an exit code for
``tools/run_tests.py --bench-ckpt``; the slow-lane budget test
(tests/test_async_checkpoint.py) asserts the same bar in-process.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

DEFAULT_SCALES = (1 << 18, 1 << 20, 1 << 22)  # floats: 1 MiB, 4 MiB, 16 MiB


def _make_step(n_params: int, step_ms: float):
    """A jitted parameter update + ``step_ms`` of simulated device time."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def update(w):
        return w * 0.999 + 0.001

    w0 = jnp.ones((n_params,), jnp.float32)

    def step(w):
        w = update(w)
        w.block_until_ready()
        if step_ms:
            time.sleep(step_ms / 1000.0)  # GIL released: the writer overlaps
        return w

    return step, w0


def _run_mode(mode: str, n_params: int, steps: int, save_every: int,
              step_ms: float, root: str):
    """One timed loop; returns (loop_seconds, drain_seconds, n_saves,
    superseded)."""
    from paddle_tpu.core.monitor import StatRegistry
    from paddle_tpu.incubate.checkpoint import (AsyncCheckpointer,
                                                commit_checkpoint)
    step, w = _make_step(n_params, step_ms)
    step(w)  # compile outside the timed region
    reg = StatRegistry()
    ckpt = AsyncCheckpointer(registry=reg) if mode == "async" else None
    n_saves = 0
    t0 = time.perf_counter()
    for i in range(steps):
        w = step(w)
        if mode != "none" and (i + 1) % save_every == 0:
            path = os.path.join(root, f"{mode}_{n_params}_{i}")
            if ckpt is not None:
                ckpt.save({"w": w}, path, step=i)
            else:
                commit_checkpoint({"w": w}, path, step=i)
            n_saves += 1
    loop_s = time.perf_counter() - t0
    drain_s = 0.0
    superseded = 0
    if ckpt is not None:
        t1 = time.perf_counter()
        ckpt.wait()
        drain_s = time.perf_counter() - t1
        superseded = int(reg.get("ckpt.async.superseded", 0))
        ckpt.close()
    return loop_s, drain_s, n_saves, superseded


def run_bench(scales=DEFAULT_SCALES, steps: int = 12, save_every: int = 2,
              step_ms: float = 40.0, root=None) -> dict:
    """Run the full sweep; returns the JSON-ready result dict."""
    own_root = root is None
    root = root or tempfile.mkdtemp(prefix="bench_ckpt_")
    results = []
    try:
        for n in scales:
            per_mode = {}
            for mode in ("none", "sync", "async"):
                loop_s, drain_s, n_saves, superseded = _run_mode(
                    mode, n, steps, save_every, step_ms, root)
                per_mode[mode] = {"loop_s": loop_s, "drain_s": drain_s,
                                  "n_saves": n_saves,
                                  "superseded": superseded}
            n_saves = per_mode["sync"]["n_saves"]
            sync_ovh = max(
                0.0, per_mode["sync"]["loop_s"] - per_mode["none"]["loop_s"])
            async_ovh = max(
                0.0, per_mode["async"]["loop_s"] - per_mode["none"]["loop_s"])
            hidden = (1.0 - async_ovh / sync_ovh) if sync_ovh > 0 else 1.0
            results.append({
                "n_params": n,
                "mib": round(n * 4 / (1 << 20), 2),
                "baseline_loop_s": round(per_mode["none"]["loop_s"], 4),
                "sync_overhead_ms_per_save":
                    round(sync_ovh / n_saves * 1e3, 3),
                "async_overhead_ms_per_save":
                    round(async_ovh / n_saves * 1e3, 3),
                "async_drain_ms":
                    round(per_mode["async"]["drain_s"] * 1e3, 3),
                "superseded": per_mode["async"]["superseded"],
                "hidden_fraction": round(hidden, 4),
                "_sync_overhead_s": sync_ovh,
                "_async_overhead_s": async_ovh,
            })
    finally:
        if own_root:
            shutil.rmtree(root, ignore_errors=True)
    total_sync = sum(r["_sync_overhead_s"] for r in results)
    total_async = sum(r["_async_overhead_s"] for r in results)
    overall = (1.0 - total_async / total_sync) if total_sync > 0 else 1.0
    for r in results:
        r.pop("_sync_overhead_s")
        r.pop("_async_overhead_s")
    return {
        "bench": "ckpt",
        "steps": steps,
        "save_every": save_every,
        "step_ms": step_ms,
        "scales": results,
        "hidden_fraction_overall": round(overall, 4),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scales", default=",".join(
        str(s) for s in DEFAULT_SCALES),
        help="comma-separated param counts (default %(default)s)")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--save-every", type=int, default=2)
    ap.add_argument("--step-ms", type=float, default=40.0,
                    help="simulated device time per step (GIL-released)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless the async path hides >= --threshold "
                         "of the sync checkpoint overhead")
    ap.add_argument("--threshold", type=float, default=0.8)
    args = ap.parse_args(argv)
    scales = tuple(int(s) for s in args.scales.split(",") if s)

    out = run_bench(scales, args.steps, args.save_every, args.step_ms)
    print(json.dumps(out, indent=2))
    if args.check:
        got = out["hidden_fraction_overall"]
        if got < args.threshold:
            print(f"FAIL: async hides {got:.1%} of sync checkpoint "
                  f"overhead, need >= {args.threshold:.0%}",
                  file=sys.stderr)
            return 1
        print(f"OK: async hides {got:.1%} (>= {args.threshold:.0%})",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
