#!/usr/bin/env python
"""Offline reference-checkpoint converter (VERDICT r3 item 9).

Reads a reference-format ``.pdparams`` pickle (paddle.save's on-disk
layout: numpy state_dict + StructuredToParameterName@@ /
UnpackBigParamInfor@@ metadata), verifies it against a paddle_tpu model,
and writes it back in either format:

    # verify + load into a zoo model, re-save as paddle_tpu checkpoint
    python tools/convert_reference_checkpoint.py in.pdparams \
        --model resnet18 --out out.pdparams

    # no model check, just normalize the container format
    python tools/convert_reference_checkpoint.py in.pdparams --out out.pdparams
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("src", help="reference-format .pdparams")
    ap.add_argument("--model", default=None,
                    help="paddle_tpu.vision.models factory name to verify "
                         "against (e.g. resnet18)")
    ap.add_argument("--out", default=None,
                    help="write the converted checkpoint here "
                         "(paddle_tpu save format)")
    ap.add_argument("--num-classes", type=int, default=1000)
    args = ap.parse_args()

    import paddle_tpu as paddle
    from paddle_tpu import framework_io

    sd = framework_io.load_reference_state_dict(args.src)
    print(f"{args.src}: {len(sd)} arrays, "
          f"{sum(v.size for v in sd.values()) / 1e6:.1f}M elements")

    if args.model:
        from paddle_tpu.vision import models
        net = getattr(models, args.model)(num_classes=args.num_classes)
        missing, unexpected = framework_io.convert_reference_checkpoint(
            args.src, net)
        print(f"loaded into {args.model}: missing={missing} "
              f"unexpected={unexpected}")
        if args.out:
            framework_io.save(net.state_dict(), args.out)
            print(f"wrote {args.out}")
    elif args.out:
        framework_io.save(sd, args.out)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
