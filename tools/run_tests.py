#!/usr/bin/env python
"""Sharded test runner (reference: tools/parallel_UT_rule.py +
unittests/CMakeLists.txt RUN_TYPE scheduling).

Splits the test files across worker processes, each running its shard in a
separate pytest (XLA compile caches are per-process, so file-level sharding
is the efficient cut). Default runs the fast lane (`-m "not slow"`); pass
--slow for the slow lane only or --all for both.

    python tools/run_tests.py            # fast lane, N=cpu/4 shards
    python tools/run_tests.py --all -j4  # everything, 4 shards
"""
from __future__ import annotations

import argparse
import glob
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Heaviest files first so the long pole starts immediately (greedy LPT).
_WEIGHT_HINTS = {
    "test_vision.py": 250, "test_graft_entry.py": 70, "test_moe.py": 70,
    "test_sequence_parallel.py": 70, "test_pipeline.py": 90,
    "test_launch_spawn.py": 60, "test_nn_layers.py": 70,
    "test_detection_round3.py": 50, "test_sampled_segment_ops.py": 50,
    "test_serving.py": 40, "test_serving_http.py": 20,
    "test_router_sharded.py": 60,
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-j", "--jobs", type=int,
                    default=max(2, (os.cpu_count() or 8) // 4))
    ap.add_argument("--slow", action="store_true",
                    help="run only the slow lane")
    ap.add_argument("--all", action="store_true", help="run both lanes")
    ap.add_argument("--files", nargs="*", help="restrict to these files")
    ap.add_argument("--no-analyze", action="store_true",
                    help="skip the static-analysis gate")
    ap.add_argument("--trace-audit", action="store_true",
                    help="also run the trace tier (PTA009/PTA010/PTA012/"
                         "PTA014): compiles every registered entrypoint "
                         "under JAX_PLATFORMS=cpu and writes the trace "
                         "report (plus the PTA014 fusion_audit.json)")
    ap.add_argument("--trace-audit-output", default="trace_audit.json",
                    help="where --trace-audit writes its report (default "
                         "%(default)s, which .gitignore covers; keep "
                         "custom paths out of the tree too)")
    ap.add_argument("--bench-check", action="store_true",
                    help="opt-in gate: compare the two newest BENCH_*.json "
                         "via tools/check_bench_regression.py and fail on "
                         "a >5%% throughput drop, then run the PTA009 "
                         "bench-audit gate (tools/check_audit_regression"
                         ".py) against bench_audit_baseline.json — new "
                         "host transfers / fusion breaks on the bench "
                         "step paths fail without spending chip time")
    ap.add_argument("--bench-router", action="store_true",
                    help="opt-in gate: run tools/bench_router.py "
                         "--check-recompiles and fail if any replica "
                         "engine recompiled after warmup")
    ap.add_argument("--bench-ckpt", action="store_true",
                    help="opt-in gate: run tools/bench_ckpt.py --check and "
                         "fail unless the async checkpointer hides >=80%% "
                         "of the sync checkpoint step-time overhead")
    ap.add_argument("--bench-llm", action="store_true",
                    help="opt-in gate: run tools/bench_llm_serving.py "
                         "--prefix-trace --check (80%% shared-prefix "
                         "trace; prefix hit rate >=0.5, reuse-on TTFT "
                         "p50 beats reuse-off) then --paged-trace "
                         "--check (>=5x concurrency at byte-equal KV, "
                         "greedy bitwise parity, zero-copy prefix vs "
                         "bench_llm_paged.json)")
    ap.add_argument("--bench-fleet", action="store_true",
                    help="opt-in gate: run tools/bench_fleet.py --check "
                         "(traffic-replay chaos storm: kill + ENOSPC "
                         "scale-up + mid-storm weight roll) and fail "
                         "unless drops == 0, the fleet scaled up, the "
                         "roll was recompile-free, and SLO recovery "
                         "fits the bench_fleet_baseline.json budget; "
                         "then --migrate --check (zero-loss storm: live "
                         "streams migrate through a slow_io-widened "
                         "roll and replay through a replica kill, every "
                         "stream bitwise-equal to an undisturbed "
                         "reference, zero drops, recompile-free)")
    ap.add_argument("--bench-elastic", action="store_true",
                    help="opt-in gate: run tools/bench_elastic.py --check "
                         "(host-loss kill matrix: watchdog hang, "
                         "heartbeat silence/partition, slow link) and "
                         "fail unless every loss is detected inside its "
                         "latency budget, transient blips stay "
                         "undeclared, and watchdog overhead is <=2% "
                         "(bench_elastic_baseline.json)")
    ap.add_argument("--bench-quant", action="store_true",
                    help="opt-in gate: run tools/bench_quant.py --check "
                         "and fail unless int8 allreduce wire bytes are "
                         ">=3x smaller than dense, int8 KV fits >=1.8x "
                         "the slots, decode accuracy holds, and warm "
                         "retraces == 0 (bench_quant_baseline.json)")
    args = ap.parse_args()

    if not args.no_analyze:
        # Static analysis gates the suite: 0 clean, 1 new findings,
        # 2 analyzer internal error (python -m tools.analyze semantics).
        # --strict gates on warnings too; the SARIF sidecar feeds code
        # scanning UIs without a second analyzer run.
        t0 = time.time()
        code = subprocess.call(
            [sys.executable, "-m", "tools.analyze", "--strict",
             "--format", "sarif", "--output", "analysis.sarif",
             "paddle_tpu"], cwd=REPO)
        print(f"static analysis: exit {code} ({time.time() - t0:.0f}s)")
        if code:
            sys.exit(code)

    if args.trace_audit:
        # Opt-in: compiles real programs, so it is not part of the default
        # gate. Forces CPU so the audit never grabs an accelerator that a
        # concurrent training job owns.
        t0 = time.time()
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        code = subprocess.call(
            [sys.executable, "-m", "tools.analyze", "--strict",
             "--only", "PTA009,PTA010,PTA012,PTA014",
             "--trace-report", args.trace_audit_output, "paddle_tpu"],
            cwd=REPO, env=env)
        print(f"trace audit: exit {code} ({time.time() - t0:.0f}s)")
        if code:
            sys.exit(code)

    if args.bench_check:
        t0 = time.time()
        code = subprocess.call(
            [sys.executable, os.path.join("tools",
                                          "check_bench_regression.py")],
            cwd=REPO)
        print(f"bench check: exit {code} ({time.time() - t0:.0f}s)")
        if code:
            sys.exit(code)
        # PTA009 audit gate: traces the bench step paths on CPU and fails
        # on new host transfers / retraces / copy-fraction growth vs the
        # committed baseline — catches the CAUSE of a throughput drop
        # before a TPU round measures the effect.
        t0 = time.time()
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        code = subprocess.call(
            [sys.executable, os.path.join("tools",
                                          "check_audit_regression.py")],
            cwd=REPO, env=env)
        print(f"bench audit gate: exit {code} ({time.time() - t0:.0f}s)")
        if code:
            sys.exit(code)

    if args.bench_router:
        # Opt-in: drives real traffic through a replica router on the CPU
        # backend and gates on the zero-post-warmup-recompiles invariant
        # (throughput numbers print but are machine-dependent, not gated).
        t0 = time.time()
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        code = subprocess.call(
            [sys.executable, "-m", "tools.bench_router",
             "--requests", "192", "--check-recompiles"],
            cwd=REPO, env=env)
        print(f"bench router: exit {code} ({time.time() - t0:.0f}s)")
        if code:
            sys.exit(code)

    if args.bench_ckpt:
        # Opt-in: sync-vs-async checkpoint overhead sweep on the CPU
        # backend, gated on the >=80%-hidden acceptance bar (absolute I/O
        # times are machine-dependent; the *ratio* is the invariant).
        t0 = time.time()
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        code = subprocess.call(
            [sys.executable, "-m", "tools.bench_ckpt", "--check"],
            cwd=REPO, env=env)
        print(f"bench ckpt: exit {code} ({time.time() - t0:.0f}s)")
        if code:
            sys.exit(code)

    if args.bench_llm:
        # Opt-in: the shared-prefix A/B on the CPU backend, gated on the
        # hit-rate and TTFT invariants (absolute times are machine-
        # dependent; the reuse-on-vs-off *ordering* is the invariant).
        t0 = time.time()
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        code = subprocess.call(
            [sys.executable, "-m", "tools.bench_llm_serving",
             "--prefix-trace", "--check"],
            cwd=REPO, env=env)
        print(f"bench llm: exit {code} ({time.time() - t0:.0f}s)")
        if code:
            sys.exit(code)
        # the paged-KV burst A/B: >=5x concurrent sequences at a
        # byte-equal KV budget, greedy bitwise parity with the slot
        # path, and zero-copy prefix sharing, gated against the
        # committed bench_llm_paged.json
        t0 = time.time()
        code = subprocess.call(
            [sys.executable, "-m", "tools.bench_llm_serving",
             "--paged-trace", "--check"],
            cwd=REPO, env=env)
        print(f"bench llm paged: exit {code} ({time.time() - t0:.0f}s)")
        if code:
            sys.exit(code)

    if args.bench_fleet:
        # Opt-in: the self-driving-fleet chaos storm on the CPU backend,
        # gated on the structural invariants (zero drops, scale-up
        # happened, roll clean) and the relative recovery-tick budget
        # (absolute latencies are machine-dependent).
        t0 = time.time()
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        code = subprocess.call(
            [sys.executable, "-m", "tools.bench_fleet", "--check"],
            cwd=REPO, env=env)
        print(f"bench fleet: exit {code} ({time.time() - t0:.0f}s)")
        if code:
            sys.exit(code)
        # Second storm: zero-loss serving (separate subprocess — each
        # storm arms its own PADDLE_TPU_FAULT_SPEC singleton). Gated on
        # bitwise stream equality, zero drops, and a recompile-free
        # migrating roll.
        t0 = time.time()
        code = subprocess.call(
            [sys.executable, "-m", "tools.bench_fleet",
             "--migrate", "--check"],
            cwd=REPO, env=env)
        print(f"bench fleet migrate: exit {code} ({time.time() - t0:.0f}s)")
        if code:
            sys.exit(code)

    if args.bench_elastic:
        # Opt-in: the host-loss kill matrix on the CPU backend, gated on
        # the detection-latency budgets (derived from the configured
        # deadlines, not the machine), the no-false-positive bar, and the
        # <=2% watchdog step overhead contract.
        t0 = time.time()
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        code = subprocess.call(
            [sys.executable, "-m", "tools.bench_elastic", "--check"],
            cwd=REPO, env=env)
        print(f"bench elastic: exit {code} ({time.time() - t0:.0f}s)")
        if code:
            sys.exit(code)

    if args.bench_quant:
        # Opt-in: the quantized hot-path sweep on the CPU backend, gated
        # on the wire-bytes / slots-per-chip / accuracy / retrace bars
        # (absolute times are machine-dependent; the byte ratios and the
        # retrace count are the invariants).
        t0 = time.time()
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        code = subprocess.call(
            [sys.executable, "-m", "tools.bench_quant", "--check"],
            cwd=REPO, env=env)
        print(f"bench quant: exit {code} ({time.time() - t0:.0f}s)")
        if code:
            sys.exit(code)

    files = args.files or sorted(
        glob.glob(os.path.join(REPO, "tests", "test_*.py")))
    files.sort(key=lambda f: -_WEIGHT_HINTS.get(os.path.basename(f), 10))

    # greedy longest-processing-time assignment
    shards = [[] for _ in range(min(args.jobs, len(files)))]
    loads = [0] * len(shards)
    for f in files:
        i = loads.index(min(loads))
        shards[i].append(f)
        loads[i] += _WEIGHT_HINTS.get(os.path.basename(f), 10)

    if args.all:
        mark = "slow or not slow"
    elif args.slow:
        mark = "slow"
    else:
        mark = "not slow"

    t0 = time.time()
    procs = []
    for i, shard in enumerate(shards):
        if not shard:
            continue
        cmd = [sys.executable, "-m", "pytest", "-q", "-m", mark,
               "-p", "no:cacheprovider", *shard]
        log = open(os.path.join(REPO, f".pytest_shard_{i}.log"), "w")
        procs.append((i, shard, log,
                      subprocess.Popen(cmd, cwd=REPO, stdout=log,
                                       stderr=subprocess.STDOUT)))
    rc = 0
    for i, shard, log, p in procs:
        code = p.wait()
        log.close()
        tail = open(log.name).read().strip().splitlines()
        status = tail[-1] if tail else "(no output)"
        print(f"shard {i} [{len(shard)} files] exit={code}: {status}")
        # pytest exit 5 = no tests collected in this shard's lane — fine
        if code not in (0, 5):
            rc = 1
            print("\n".join(tail[-30:]))
    print(f"total: {time.time() - t0:.0f}s, exit {rc}")
    sys.exit(rc)


if __name__ == "__main__":
    main()
