#!/usr/bin/env python
"""Offered-load sweep for the LLM serving engine — decode throughput,
TTFT/TPOT tails, and recompile count per load level, printed as one JSON
document (same shape as tools/bench_serving.py).

    python -m tools.bench_llm_serving                    # synthetic GPT
    python -m tools.bench_llm_serving --loads 2,8,0      # 0 = unthrottled
    python -m tools.bench_llm_serving --no-baseline      # skip the
                                                         # static-vs-concat
                                                         # comparison
    python -m tools.bench_llm_serving --prefix-trace     # shared-prefix
                                                         # reuse-on-vs-off
                                                         # A/B (--check
                                                         # gates it)

The ``--prefix-trace`` mode replays ONE trace of prompts where
``--shared-frac`` of the requests open with the same ``--shared-len``-token
prefix (the few-shot/system-prompt pattern) through two fresh engines —
prefix KV reuse on and off — and reports the store hit rate plus TTFT
percentiles for both. Requests run one at a time so TTFT measures prefill
cost, not queue depth. ``--check`` gates ``hit_rate >= 0.5`` and
reuse-on TTFT p50 strictly below reuse-off (the tools/run_tests.py
``--bench-llm`` stage).

Each sweep drives ``--requests`` mixed-length prompts at the offered rate
(requests/s; 0 = as fast as submission allows) through a fresh
:class:`~paddle_tpu.serving.llm.LLMEngine` with its own StatRegistry, so
the latency histograms and cache counters are per-sweep. Headline
numbers: ``throughput_tok_s`` (generated tokens/s), ``ttft_p50_ms`` /
``ttft_p95_ms`` (time to first token), ``tpot_p50_ms`` / ``tpot_p95_ms``
(per-output-token tick latency), and ``recompiles`` — the NEW executable
compiles during the sweep, which should be zero after warmup (the
one-compiled-decode-step claim, measurable).

The ``baseline`` section times ``model.generate`` at batch
``--baseline-batch`` through the static-slot KV cache (``use_cache=True``)
and the legacy concat-grown cache (``use_cache="concat"``), cold (first
call, includes tracing) and warm (steady state). The acceptance bar is
``warm_speedup >= 3`` on CPU.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from concurrent.futures import wait

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _synthetic_gpt(vocab, hidden, layers, heads, max_pos, seed=0):
    """A small random-weight GPT: real attention shapes, real KV traffic,
    fast enough that the sweep measures scheduling, not matmuls."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden, num_layers=layers,
                    num_heads=heads, max_position_embeddings=max_pos,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    net = GPTForCausalLM(cfg)
    net.eval()
    return net


def run_sweep(engine, requests, offered_qps, prompt_lens, max_new, vocab,
              seed=0):
    import numpy as np
    rng = np.random.RandomState(seed)
    prompts = [rng.randint(0, vocab,
                           size=prompt_lens[i % len(prompt_lens)])
               .astype(np.int32) for i in range(requests)]
    gap = 0.0 if not offered_qps else 1.0 / offered_qps
    reg = engine.registry
    misses0 = engine.cache.stats()["misses"]
    t0 = time.monotonic()
    reqs = []
    for i, p in enumerate(prompts):
        reqs.append(engine.submit(p, max_new_tokens=max_new))
        if gap:
            # pace submissions to the offered rate (absolute schedule so
            # slow submits don't silently lower the offered load)
            sleep_until = t0 + (i + 1) * gap
            pause = sleep_until - time.monotonic()
            if pause > 0:
                time.sleep(pause)
    wait([r.future for r in reqs], timeout=600)
    wall = time.monotonic() - t0
    errors = sum(1 for r in reqs
                 if r.future.done() and r.future.exception() is not None)
    pre = engine.config.stat_prefix
    tokens = reg.get(f"{pre}.tokens_generated")
    return {
        "offered_qps": offered_qps or None,
        "requests": requests,
        "errors": errors,
        "wall_s": round(wall, 4),
        "throughput_rps": round(requests / wall, 2),
        "throughput_tok_s": round(tokens / wall, 2),
        "tokens_generated": tokens,
        "ttft_p50_ms": round(reg.quantile(f"{pre}.ttft_ms", 0.50), 3),
        "ttft_p95_ms": round(reg.quantile(f"{pre}.ttft_ms", 0.95), 3),
        "tpot_p50_ms": round(reg.quantile(f"{pre}.tpot_ms", 0.50), 3),
        "tpot_p95_ms": round(reg.quantile(f"{pre}.tpot_ms", 0.95), 3),
        "p50_ms": round(reg.quantile(f"{pre}.request_latency_ms", 0.50), 3),
        "p95_ms": round(reg.quantile(f"{pre}.request_latency_ms", 0.95), 3),
        "p99_ms": round(reg.quantile(f"{pre}.request_latency_ms", 0.99), 3),
        "prefills": reg.get(f"{pre}.prefills"),
        "completed": reg.get(f"{pre}.completed"),
        "evicted_midstream": reg.get(f"{pre}.evicted_midstream"),
        "recompiles": engine.cache.stats()["misses"] - misses0,
        "cache": engine.cache.stats(),
    }


def _prefix_trace_prompts(requests, shared_frac, shared_len, tail_len,
                          vocab, seed=0):
    """One fixed trace: ``shared_frac`` of prompts = common prefix +
    unique tail, the rest fully unique (same total length)."""
    import numpy as np
    rng = np.random.RandomState(seed)
    shared = rng.randint(0, vocab, size=shared_len).astype(np.int32)
    prompts = []
    for _ in range(requests):
        if rng.rand() < shared_frac:
            tail = rng.randint(0, vocab, size=tail_len).astype(np.int32)
            prompts.append(np.concatenate([shared, tail]))
        else:
            prompts.append(rng.randint(
                0, vocab, size=shared_len + tail_len).astype(np.int32))
    return prompts


def run_prefix_trace(model, prompts, max_new, num_slots, max_seq,
                     reuse: bool):
    """Replay ``prompts`` sequentially (submit → wait → next, so TTFT is
    pure prefill + first-tick cost) through a fresh engine with prefix KV
    reuse on or off; returns TTFT/wall numbers plus the store counters."""
    from paddle_tpu.core.monitor import StatRegistry
    from paddle_tpu.serving.llm import LLMEngine, LLMEngineConfig

    reg = StatRegistry()
    engine = LLMEngine(model, LLMEngineConfig(
        num_slots=num_slots, max_seq=max_seq,
        max_queue=max(1024, len(prompts)),
        default_max_new_tokens=max_new,
        prefix_cache=reuse), registry=reg)
    t0 = time.monotonic()
    for p in prompts:
        engine.generate(p, max_new_tokens=max_new)
    wall = time.monotonic() - t0
    pre = engine.config.stat_prefix
    hits = reg.get(f"{pre}.prefix.hits")
    misses = reg.get(f"{pre}.prefix.misses")
    out = {
        "reuse": reuse,
        "requests": len(prompts),
        "wall_s": round(wall, 4),
        "ttft_p50_ms": round(reg.quantile(f"{pre}.ttft_ms", 0.50), 3),
        "ttft_p95_ms": round(reg.quantile(f"{pre}.ttft_ms", 0.95), 3),
        "tokens_generated": reg.get(f"{pre}.tokens_generated"),
        "prefix_hits": hits,
        "prefix_misses": misses,
        "hit_rate": round(hits / max(1, hits + misses), 4),
        "reused_tokens": reg.get(f"{pre}.prefix.reused_tokens"),
    }
    engine.drain()
    return out


def run_prefix_ab(model, args):
    """The reuse-on vs reuse-off A/B over one shared-prefix trace."""
    prompts = _prefix_trace_prompts(
        args.requests, args.shared_frac, args.shared_len, args.tail_len,
        args.vocab)
    on = run_prefix_trace(model, prompts, args.max_new, args.num_slots,
                          args.max_seq, reuse=True)
    off = run_prefix_trace(model, prompts, args.max_new, args.num_slots,
                           args.max_seq, reuse=False)
    doc = {
        "bench": "llm-prefix-trace",
        "shared_frac": args.shared_frac,
        "shared_len": args.shared_len,
        "tail_len": args.tail_len,
        "vocab": args.vocab, "hidden": args.hidden,
        "layers": args.layers, "heads": args.heads,
        "num_slots": args.num_slots, "max_seq": args.max_seq,
        "max_new": args.max_new,
        "reuse_on": on,
        "reuse_off": off,
        "ttft_p50_speedup": round(
            off["ttft_p50_ms"] / max(1e-9, on["ttft_p50_ms"]), 3),
        "check": {
            "hit_rate_ge_0.5": on["hit_rate"] >= 0.5,
            "ttft_p50_improved":
                on["ttft_p50_ms"] < off["ttft_p50_ms"],
        },
    }
    return doc


def _paged_trace_prompts(requests, vocab, max_seq, max_new, seed=0):
    """Mixed realistic lengths: a clipped lognormal (chat traffic is a
    short head with a long tail), far below ``max_seq`` on average —
    the regime where worst-case slot reservation wastes almost the whole
    KV arena and page-granular admission does not."""
    import numpy as np
    rng = np.random.RandomState(seed)
    lens = np.clip(rng.lognormal(3.2, 0.7, size=requests).astype(int),
                   4, max_seq - max_new - 1)
    return [rng.randint(0, vocab, size=int(n)).astype(np.int32)
            for n in lens]


def run_paged_burst(model, prompts, max_new, num_slots, max_seq,
                    kv_layout, page_size=16, num_pages=None):
    """Submit the whole trace at once and poll the scheduler's resident
    set while the burst drains: ``peak_concurrent`` is how many sequences
    the KV memory actually held simultaneously. Returns the generated
    token lists too, so the caller can prove slot-vs-paged greedy decode
    is bitwise identical on the same trace."""
    import threading
    from paddle_tpu.core.monitor import StatRegistry
    from paddle_tpu.serving.llm import LLMEngine, LLMEngineConfig

    kw = {}
    if kv_layout == "paged":
        kw = {"kv_layout": "paged", "page_size": page_size,
              "num_pages": num_pages}
    engine = LLMEngine(model, LLMEngineConfig(
        num_slots=num_slots, max_seq=max_seq,
        max_queue=max(1024, len(prompts)),
        default_max_new_tokens=max_new, **kw),
        registry=StatRegistry())
    peak = [0]
    stop = threading.Event()

    def _poll():
        while not stop.is_set():
            peak[0] = max(peak[0], len(engine._batcher._reqs))
            stop.wait(0.002)

    poller = threading.Thread(target=_poll, daemon=True)
    t0 = time.monotonic()
    reqs = [engine.submit(p, max_new_tokens=max_new) for p in prompts]
    poller.start()
    wait([r.future for r in reqs], timeout=600)
    stop.set()
    poller.join(timeout=5)
    wall = time.monotonic() - t0
    tokens = [r.future.result()["tokens"] for r in reqs]
    reg, pre = engine.registry, engine.config.stat_prefix
    out = {
        "kv_layout": kv_layout,
        "num_slots": num_slots,
        "requests": len(prompts),
        "peak_concurrent": peak[0],
        "wall_s": round(wall, 4),
        "throughput_tok_s": round(
            reg.get(f"{pre}.tokens_generated") / wall, 2),
        "evicted_midstream": reg.get(f"{pre}.evicted_midstream"),
    }
    if kv_layout == "paged":
        kv = engine._batcher.kv
        out.update(page_size=page_size, num_pages=kv.pool.num_pages,
                   kv_bytes=kv.kv_bytes(),
                   peak_pages_in_use=kv.pool.peak_in_use,
                   cow_splits=kv.cow_splits)
    engine.drain()
    return out, tokens


def run_paged_prefix_phase(model, page_size, num_pages, num_slots,
                           max_seq, max_new, vocab, requests=12, seed=1):
    """Shared page-aligned system prompt through a paged engine with the
    prefix store on: every hit must splice pages by refcount — zero
    copied bytes, ``bytes_shared`` exactly hits * shared pages."""
    import numpy as np
    from paddle_tpu.core.monitor import StatRegistry
    from paddle_tpu.serving.llm import LLMEngine, LLMEngineConfig

    rng = np.random.RandomState(seed)
    shared_pages = 8
    shared = rng.randint(0, vocab,
                         size=shared_pages * page_size).astype(np.int32)
    engine = LLMEngine(model, LLMEngineConfig(
        num_slots=num_slots, max_seq=max_seq,
        max_queue=max(1024, requests), default_max_new_tokens=max_new,
        kv_layout="paged", page_size=page_size, num_pages=num_pages,
        prefix_cache=True), registry=StatRegistry())
    for _ in range(requests):
        tail = rng.randint(0, vocab, size=7).astype(np.int32)
        engine.generate(np.concatenate([shared, tail]),
                        max_new_tokens=max_new)
    ps = engine.prefix_store.stats()
    page_nbytes = engine._batcher.kv.page_nbytes()
    expect_shared = ps["hits"] * shared_pages * page_nbytes
    out = {
        "requests": requests,
        "shared_tokens": int(shared.size),
        "shared_pages": shared_pages,
        "hits": ps["hits"],
        "misses": ps["misses"],
        "bytes_shared": ps["bytes_shared"],
        "bytes_copied": ps["bytes_copied"],
        "expected_bytes_shared": expect_shared,
        "zero_copy": (ps["bytes_copied"] == 0 and ps["hits"] > 0
                      and ps["bytes_shared"] == expect_shared),
    }
    engine.drain()
    return out


def run_paged_ab(model, args):
    """The slot-vs-paged burst A/B at a byte-equal KV budget (the paged
    arena carries one extra trash page), plus the zero-copy prefix
    phase."""
    prompts = _paged_trace_prompts(args.requests, args.vocab,
                                   args.max_seq, args.max_new)
    # byte parity: the paged arena holds exactly the slot path's rows
    num_pages = args.num_slots * args.max_seq // args.page_size
    slot, slot_toks = run_paged_burst(
        model, prompts, args.max_new, args.num_slots, args.max_seq,
        kv_layout="slot")
    paged, paged_toks = run_paged_burst(
        model, prompts, args.max_new, args.paged_slots, args.max_seq,
        kv_layout="paged", page_size=args.page_size, num_pages=num_pages)
    prefix = run_paged_prefix_phase(
        model, args.page_size, num_pages, args.paged_slots, args.max_seq,
        args.max_new, args.vocab)
    ratio = round(paged["peak_concurrent"]
                  / max(1, slot["peak_concurrent"]), 2)
    match = slot_toks == paged_toks
    doc = {
        "bench": "llm-paged-trace",
        "geometry": {
            "vocab": args.vocab, "hidden": args.hidden,
            "layers": args.layers, "heads": args.heads,
            "max_seq": args.max_seq, "max_new": args.max_new,
            "requests": args.requests, "page_size": args.page_size,
            "slot_slots": args.num_slots,
            "paged_slots": args.paged_slots,
            "num_pages": num_pages,
        },
        "slot": slot,
        "paged": paged,
        "prefix": prefix,
        "concurrency_ratio": ratio,
        "greedy_bitwise_match": match,
        "check": {
            "concurrency_ratio_ge_5": ratio >= 5.0,
            "greedy_bitwise_match": match,
            "prefix_zero_copy": prefix["zero_copy"],
        },
    }
    return doc


def check_paged_doc(doc, baseline_path):
    """Gate a --paged-trace doc against the committed baseline: same
    geometry (so the ratio can't be gamed by shrinking the slot lane),
    every in-doc invariant true, and the concurrency ratio no worse than
    80% of the committed run (and never below the 5x acceptance bar)."""
    problems = [f"{k} failed" for k, ok in doc["check"].items() if not ok]
    try:
        with open(baseline_path) as f:
            base = json.load(f)
    except (OSError, ValueError) as e:
        return problems + [f"baseline unreadable at {baseline_path}: {e}"]
    if base.get("geometry") != doc["geometry"]:
        problems.append(f"geometry drifted from baseline: "
                        f"{base.get('geometry')} != {doc['geometry']}")
    floor = max(5.0, 0.8 * float(base.get("concurrency_ratio", 5.0)))
    if doc["concurrency_ratio"] < floor:
        problems.append(f"concurrency_ratio {doc['concurrency_ratio']} "
                        f"< floor {floor:.2f}")
    return problems


def run_baseline(model, batch, prompt_len, new_tokens, vocab, seed=0):
    """Static-slot vs concat-grown decode through the SAME
    ``model.generate`` entry point: cold (includes tracing) and warm
    (steady-state) wall time, batch ``batch`` greedy decode."""
    import numpy as np
    import paddle_tpu as paddle
    rng = np.random.RandomState(seed)
    ids = paddle.to_tensor(
        rng.randint(0, vocab, size=(batch, prompt_len)).astype("int64"))
    ntok = batch * new_tokens
    out = {"batch": batch, "prompt_len": prompt_len,
           "new_tokens": new_tokens}
    for key, mode in (("static", True), ("concat", "concat")):
        t0 = time.monotonic()
        model.generate(ids, max_length=new_tokens, use_cache=mode)
        cold = time.monotonic() - t0
        t0 = time.monotonic()
        model.generate(ids, max_length=new_tokens, use_cache=mode)
        warm = time.monotonic() - t0
        out[key] = {
            "cold_s": round(cold, 4),
            "warm_s": round(warm, 4),
            "cold_tok_s": round(ntok / cold, 1),
            "warm_tok_s": round(ntok / warm, 1),
        }
    out["cold_speedup"] = round(
        out["concat"]["cold_s"] / out["static"]["cold_s"], 2)
    out["warm_speedup"] = round(
        out["concat"]["warm_s"] / out["static"]["warm_s"], 2)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=48)
    ap.add_argument("--loads", default="4,16,0",
                    help="comma-separated offered loads in req/s; 0 = "
                         "unthrottled")
    ap.add_argument("--prompt-lens", default="4,8,12,16",
                    help="prompt token counts, cycled")
    ap.add_argument("--max-new", type=int, default=32,
                    help="generated tokens per request")
    ap.add_argument("--num-slots", type=int, default=8)
    ap.add_argument("--max-seq", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--share-engine", action="store_true",
                    help="reuse one engine across sweeps (recompiles go to "
                         "zero after the first)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the static-vs-concat model.generate timing")
    ap.add_argument("--baseline-batch", type=int, default=8)
    ap.add_argument("--baseline-new", type=int, default=64)
    ap.add_argument("--prefix-trace", action="store_true",
                    help="run the shared-prefix reuse-on-vs-off A/B "
                         "instead of the load sweep")
    ap.add_argument("--shared-frac", type=float, default=0.8,
                    help="fraction of trace prompts opening with the "
                         "common prefix")
    ap.add_argument("--shared-len", type=int, default=248,
                    help="common-prefix length in tokens")
    ap.add_argument("--tail-len", type=int, default=8,
                    help="unique tail length behind the shared prefix")
    ap.add_argument("--paged-trace", action="store_true",
                    help="run the slot-vs-paged mixed-length burst A/B "
                         "(byte-equal KV budget) plus the zero-copy "
                         "prefix phase instead of the load sweep")
    ap.add_argument("--page-size", type=int, default=16,
                    help="paged-trace: tokens per KV page")
    ap.add_argument("--paged-slots", type=int, default=48,
                    help="paged-trace: sequence slots for the paged "
                         "engine (its concurrency is page-bound, not "
                         "slot-bound)")
    ap.add_argument("--paged-baseline",
                    default=os.path.join(REPO, "bench_llm_paged.json"),
                    help="paged-trace: committed baseline the --check "
                         "gate compares against")
    ap.add_argument("--write-baseline", action="store_true",
                    help="with --paged-trace: record this run as the "
                         "committed baseline")
    ap.add_argument("--check", action="store_true",
                    help="with --prefix-trace: exit 1 unless hit_rate >= "
                         "0.5 and reuse-on TTFT p50 beats reuse-off; "
                         "with --paged-trace: gate the >=5x concurrency "
                         "ratio, greedy bitwise parity and zero-copy "
                         "prefix invariants against the committed "
                         "bench_llm_paged.json")
    args = ap.parse_args(argv)

    if args.paged_trace:
        # a paged-vs-slot A/B needs room for the length spread: upgrade
        # any knob left at its load-sweep default to the trace config
        # (4 worst-case slots vs a byte-equal page pool)
        for k, v in {"max_seq": 512, "num_slots": 4, "requests": 48,
                     "max_new": 8}.items():
            if getattr(args, k) == ap.get_default(k):
                setattr(args, k, v)

    if args.prefix_trace:
        # the A/B needs prefill FLOPs to dominate jit dispatch overhead
        # and the whole-KV-buffer functional-update copies both paths
        # pay, or the measurement is noise: upgrade any knob the caller
        # left at its load-sweep default to the flop-dominant config
        for k, v in {"hidden": 512, "heads": 8, "layers": 6,
                     "max_seq": 512, "num_slots": 4, "requests": 32,
                     "max_new": 8}.items():
            if getattr(args, k) == ap.get_default(k):
                setattr(args, k, v)

    from paddle_tpu.core.monitor import StatRegistry
    from paddle_tpu.serving.llm import LLMEngine, LLMEngineConfig

    model = _synthetic_gpt(args.vocab, args.hidden, args.layers, args.heads,
                           max_pos=max(args.max_seq,
                                       args.baseline_new + 32))

    if args.prefix_trace:
        doc = run_prefix_ab(model, args)
        json.dump(doc, sys.stdout, indent=2)
        print()
        if args.check and not all(doc["check"].values()):
            print(f"FAIL: {doc['check']}", file=sys.stderr)
            return 1
        return 0

    if args.paged_trace:
        doc = run_paged_ab(model, args)
        json.dump(doc, sys.stdout, indent=2)
        print()
        if args.write_baseline:
            with open(args.paged_baseline, "w") as f:
                json.dump(doc, f, indent=2)
                f.write("\n")
            print(f"baseline written to {args.paged_baseline}",
                  file=sys.stderr)
        if args.check:
            problems = check_paged_doc(doc, args.paged_baseline)
            if problems:
                print("FAIL:", file=sys.stderr)
                for p in problems:
                    print(f"  - {p}", file=sys.stderr)
                return 1
        return 0
    prompt_lens = [int(s) for s in args.prompt_lens.split(",") if s.strip()]
    loads = [float(x) for x in args.loads.split(",") if x.strip()]

    def make_engine():
        return LLMEngine(model, LLMEngineConfig(
            num_slots=args.num_slots, max_seq=args.max_seq,
            max_queue=max(1024, args.requests),
            default_max_new_tokens=args.max_new),
            registry=StatRegistry())

    engine = make_engine() if args.share_engine else None
    sweeps = []
    for i, qps in enumerate(loads):
        eng = engine if engine is not None else make_engine()
        if engine is not None:
            eng.registry.reset()
        sweeps.append(run_sweep(eng, args.requests, qps, prompt_lens,
                                args.max_new, args.vocab, seed=i))
        if engine is None:
            eng.drain()
    if engine is not None:
        engine.drain()

    doc = {"bench": "llm-serving", "model": "synthetic-gpt",
           "vocab": args.vocab, "hidden": args.hidden,
           "layers": args.layers, "heads": args.heads,
           "num_slots": args.num_slots, "max_seq": args.max_seq,
           "max_new": args.max_new,
           "share_engine": bool(args.share_engine), "sweeps": sweeps}
    if not args.no_baseline:
        doc["baseline"] = run_baseline(
            model, args.baseline_batch,
            prompt_len=max(prompt_lens), new_tokens=args.baseline_new,
            vocab=args.vocab)
    json.dump(doc, sys.stdout, indent=2)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
