#!/usr/bin/env python
"""Forbid silently-swallowed failures in the resilience-critical paths.

The elastic fault-tolerance runtime (docs/fault_tolerance.md) depends on
failures *propagating*: a swallowed exception in the launcher, the elastic
supervisor, or the checkpoint layer turns a recoverable crash into silent
state corruption. This lint rejects, inside the directories below:

- bare ``except:`` handlers
- ``except Exception:`` / ``except BaseException:`` (alone or in a tuple)
  whose body does nothing (only ``pass`` / ``...``)

Catching Exception and then *acting* (logging, re-raising, returning an
explicit sentinel) is fine — the rule targets the do-nothing swallow.

Run directly (``python tools/lint_silent_except.py``; exit 1 on offenders)
or via the test suite (tests/test_resilience_lint.py, tier-1).
"""
from __future__ import annotations

import ast
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: directories where a silent swallow is a correctness bug, not a style nit
CHECKED_DIRS = (
    os.path.join("paddle_tpu", "distributed"),
    os.path.join("paddle_tpu", "incubate", "checkpoint"),
    os.path.join("paddle_tpu", "utils"),
)

_BROAD = {"Exception", "BaseException"}


def _names_in(expr):
    """Exception-class names referenced by an except clause's type expr."""
    if expr is None:
        return set()
    if isinstance(expr, ast.Name):
        return {expr.id}
    if isinstance(expr, ast.Attribute):
        return {expr.attr}
    if isinstance(expr, ast.Tuple):
        out = set()
        for elt in expr.elts:
            out |= _names_in(elt)
        return out
    return set()


def _body_is_noop(body):
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is Ellipsis):
            continue
        return False
    return True


def check_file(path):
    with open(path, "rb") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"syntax error: {e.msg}")]
    offenders = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            offenders.append(
                (path, node.lineno,
                 "bare 'except:' swallows everything incl. SystemExit"))
        elif _names_in(node.type) & _BROAD and _body_is_noop(node.body):
            offenders.append(
                (path, node.lineno,
                 "'except Exception: pass' silently swallows failures"))
    return offenders


def find_offenders(root=REPO_ROOT, dirs=CHECKED_DIRS):
    offenders = []
    for rel in dirs:
        base = os.path.join(root, rel)
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    offenders.extend(check_file(os.path.join(dirpath, fn)))
    return offenders


def main():
    offenders = find_offenders()
    for path, lineno, msg in offenders:
        print(f"{os.path.relpath(path, REPO_ROOT)}:{lineno}: {msg}")
    if offenders:
        print(f"{len(offenders)} silent-except offender(s); failures in "
              f"resilience paths must propagate or be handled explicitly "
              f"(docs/fault_tolerance.md)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
