#!/usr/bin/env python
"""Forbid silently-swallowed failures in the resilience-critical paths.

Shim: the actual rule now lives in the static-analysis framework as
PTA003 (tools/analyze/rules/pta003_silent_except.py) and runs with the
rest of the analyzer (``python -m tools.analyze``). This file keeps the
original standalone interface — ``check_file`` / ``find_offenders`` /
``main`` / ``CHECKED_DIRS`` — for tests/test_resilience_lint.py and for
anyone running ``python tools/lint_silent_except.py`` directly.
"""
from __future__ import annotations

import ast
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if REPO_ROOT not in sys.path:  # the test loads this file by path
    sys.path.insert(0, REPO_ROOT)

from tools.analyze.rules.pta003_silent_except import (  # noqa: E402
    iter_offenders,
)
from tools.analyze.rules import pta003_silent_except as _rule  # noqa: E402

#: directories where a silent swallow is a correctness bug, not a style nit
CHECKED_DIRS = tuple(os.path.join(*d.split("/")) for d in _rule.CHECKED_DIRS)


def check_file(path):
    with open(path, "rb") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"syntax error: {e.msg}")]
    return [(path, lineno, msg) for lineno, msg in iter_offenders(tree)]


def find_offenders(root=REPO_ROOT, dirs=CHECKED_DIRS):
    offenders = []
    for rel in dirs:
        base = os.path.join(root, rel)
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    offenders.extend(check_file(os.path.join(dirpath, fn)))
    return offenders


def main():
    offenders = find_offenders()
    for path, lineno, msg in offenders:
        print(f"{os.path.relpath(path, REPO_ROOT)}:{lineno}: {msg}")
    if offenders:
        print(f"{len(offenders)} silent-except offender(s); failures in "
              f"resilience paths must propagate or be handled explicitly "
              f"(docs/fault_tolerance.md)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
