#!/usr/bin/env python
"""Quantized hot-path sweep, printed as one JSON doc.

    python -m tools.bench_quant                  # full sweep
    python -m tools.bench_quant --check          # CI gate
    python -m tools.bench_quant --write-baseline # refresh committed baseline

Two lanes, mirroring the two quantized executables
(docs/quantization.md):

- **allreduce**: dense vs int8 vs bf16 compressed gradient exchange on
  the 8-virtual-device CPU mesh — analytic per-device wire bytes (the
  TPU-invariant quantity; CPU step times are reported but not gated) and
  the measured mean-gradient error of each wire format.
- **serving**: a tiny seeded GPT decoded f32 vs int8 (weights + KV) —
  KV-cache and weight bytes, slots-at-equal-memory ratio, decode logits
  error, and the warm-path retrace count.

``--check`` enforces the acceptance bars as exit codes for
``tools/run_tests.py --bench-quant``:

- int8 wire bytes >= 3x smaller than dense at every swept size;
- int8 KV fits >= 1.8x the slots of f32 in the same byte budget;
- int8 decode KV-row error <= 2% of the f32 row range (accuracy budget);
- warm decode retraces == 0 (one trace per shape, then pure execution).

The committed ``bench_quant_baseline.json`` pins the analytic ratios;
``--check`` also fails if a ratio regresses below its baseline (a wire-
format or cache-layout change that silently costs bytes)."""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# 8 virtual devices BEFORE jax import (same trick as tests/conftest.py)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

BASELINE = os.path.join(REPO, "bench_quant_baseline.json")

ALLREDUCE_SIZES = (1 << 20, 1 << 22)
WIRE_BAR = 3.0
SLOTS_BAR = 1.8
KV_ERR_BAR = 0.02


def bench_allreduce():
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    import paddle_tpu.distributed as dist

    dist.set_mesh(dist.build_mesh({"dp": 8}))
    rows = []
    for n in ALLREDUCE_SIZES:
        rng = np.random.default_rng(n & 0xFFFF)
        x = jnp.asarray(rng.standard_normal((8, n // 8)), jnp.float32)
        ref = np.asarray(x).mean(axis=0)
        row = {"nelems": n,
               "dense_wire_bytes": dist.dense_allreduce_wire_bytes(n, 8)}
        for wd in ("int8", "bf16"):
            fn = jax.jit(jax.shard_map(  # noqa: PTA008 -- one jit per benchmarked (size, wire) config by design; each runs once, there is no reused hot loop
                lambda v, wd=wd: dist.compressed_grad_sync(v, wire_dtype=wd),
                mesh=dist.get_mesh(), in_specs=P("dp"), out_specs=P(),
                check_vma=False))
            out = np.asarray(fn(x))  # compile + correctness
            t0 = time.perf_counter()
            for _ in range(3):
                fn(x).block_until_ready()
            row[f"{wd}_step_ms"] = (time.perf_counter() - t0) / 3 * 1e3
            row[f"{wd}_wire_bytes"] = dist.compressed_allreduce_wire_bytes(
                n, 8, wd)
            row[f"{wd}_ratio"] = (row["dense_wire_bytes"]
                                  / row[f"{wd}_wire_bytes"])
            row[f"{wd}_max_err"] = float(np.abs(out - ref).max())
        rows.append(row)
    dist.set_mesh(None)
    return rows


def bench_serving():
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving.cache import ExecutableCache
    from paddle_tpu.serving.llm.decode import (
        GPTStaticDecoder, SamplingParams, _QUANT_WEIGHT_KEYS, pack_sampling)
    from paddle_tpu.serving.llm.kvcache import dequantize_kv

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=256,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    net = GPTForCausalLM(cfg)
    net.eval()

    def leaf_bytes(t):
        return sum(x.nbytes for x in jax.tree_util.tree_leaves(t))

    out = {}
    decoded = {}
    for mode in ("float32", "int8"):
        cache = ExecutableCache()
        dec = GPTStaticDecoder(net, max_top_k=8, exec_cache=cache,
                               weight_dtype=mode, kv_dtype=mode)
        params = dec.params()
        kv = dec.new_kv(num_slots=2, max_seq=64)
        kv.alloc(), kv.alloc()
        samp = pack_sampling([SamplingParams()] * 2)
        finished = jnp.zeros((2,), bool)
        toks = jnp.asarray([[5, 9, 2, 11], [3, 1, 4, 1]], jnp.int32)
        nxt, finished = dec.prefill(
            kv, params, toks, jnp.asarray([4, 4], jnp.int32),
            jnp.asarray([0, 1], jnp.int32), finished, samp,
            jax.random.PRNGKey(0))
        # first step compiles (or re-traces the shared lru-cached fn for
        # this mode's arg structure); every later step must be pure
        # execution — that delta is the warm-retrace gate
        nxt, finished = dec.decode_step(kv, params, finished, nxt, samp,
                                        jax.random.PRNGKey(1))
        warm_start = dec.decode_fn(2, 64).trace_counter["traces"]
        steps, t0 = 32, time.perf_counter()
        for i in range(steps):
            nxt, finished = dec.decode_step(kv, params, finished, nxt,
                                            samp, jax.random.PRNGKey(i + 2))
        nxt.block_until_ready()
        dt = time.perf_counter() - t0
        w_bytes = sum(leaf_bytes(params["layers"][li][k])
                      for li in range(cfg.num_layers)
                      for k in _QUANT_WEIGHT_KEYS)
        decoded[mode] = {"k": np.asarray(dequantize_kv(kv.k)),
                         "retraces": dec.decode_fn(2, 64)
                         .trace_counter["traces"] - warm_start}
        out[mode] = {"kv_bytes": kv.kv_bytes(), "weight_bytes": w_bytes,
                     "tokens_per_s": 2 * steps / dt,
                     "warm_retraces": decoded[mode]["retraces"]}

    kf, kq = decoded["float32"]["k"], decoded["int8"]["k"]
    import numpy as np
    out["kv_row_rel_err"] = float(
        np.abs(kf - kq).max() / (np.abs(kf).max() + 1e-6))
    out["slots_ratio"] = (out["float32"]["kv_bytes"]
                          / out["int8"]["kv_bytes"])
    out["weight_ratio"] = (out["float32"]["weight_bytes"]
                           / out["int8"]["weight_bytes"])
    return out


def run_sweep():
    return {"version": 1,
            "allreduce": bench_allreduce(),
            "serving": bench_serving()}


def check(doc, baseline=None):
    problems = []
    for row in doc["allreduce"]:
        if row["int8_ratio"] < WIRE_BAR:
            problems.append(
                f"allreduce n={row['nelems']}: int8 wire ratio "
                f"{row['int8_ratio']:.2f} < {WIRE_BAR}")
    srv = doc["serving"]
    if srv["slots_ratio"] < SLOTS_BAR:
        problems.append(f"serving: slots ratio {srv['slots_ratio']:.2f} "
                        f"< {SLOTS_BAR}")
    if srv["kv_row_rel_err"] > KV_ERR_BAR:
        problems.append(f"serving: int8 KV row error "
                        f"{srv['kv_row_rel_err']:.4f} > {KV_ERR_BAR}")
    for mode in ("float32", "int8"):
        if srv[mode]["warm_retraces"]:
            problems.append(f"serving[{mode}]: "
                            f"{srv[mode]['warm_retraces']} warm retraces "
                            f"(must be 0)")
    if baseline:
        for row, base in zip(doc["allreduce"],
                             baseline.get("allreduce", [])):
            for k in ("int8_ratio", "bf16_ratio"):
                if row[k] < base[k] - 1e-6:
                    problems.append(
                        f"allreduce n={row['nelems']}: {k} regressed "
                        f"{base[k]:.3f} -> {row[k]:.3f}")
        bs = baseline.get("serving", {})
        for k in ("slots_ratio", "weight_ratio"):
            if k in bs and srv[k] < bs[k] - 1e-6:
                problems.append(f"serving: {k} regressed "
                                f"{bs[k]:.3f} -> {srv[k]:.3f}")
    return problems


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="gate the acceptance bars + baseline ratios")
    ap.add_argument("--write-baseline", action="store_true",
                    help="refresh the committed baseline (analytic "
                         "ratios only — timings are machine-local)")
    ap.add_argument("--baseline", default=BASELINE)
    args = ap.parse_args(argv)

    doc = run_sweep()
    print(json.dumps(doc, indent=1, sort_keys=True))

    if args.write_baseline:
        stable = {
            "version": 1,
            "allreduce": [
                {k: row[k] for k in ("nelems", "dense_wire_bytes",
                                     "int8_wire_bytes", "bf16_wire_bytes",
                                     "int8_ratio", "bf16_ratio")}
                for row in doc["allreduce"]],
            "serving": {k: doc["serving"][k]
                        for k in ("slots_ratio", "weight_ratio")},
        }
        with open(args.baseline, "w") as f:
            json.dump(stable, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"bench quant: baseline written to {args.baseline}",
              file=sys.stderr)
        return 0

    if args.check:
        baseline = None
        try:
            with open(args.baseline) as f:
                baseline = json.load(f)
        except FileNotFoundError:
            print(f"bench quant: no baseline at {args.baseline} "
                  f"(absolute bars only)", file=sys.stderr)
        problems = check(doc, baseline)
        if problems:
            print("FAIL:", file=sys.stderr)
            for p in problems:
                print(f"  - {p}", file=sys.stderr)
            return 1
        print("bench quant: OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
