#!/usr/bin/env python
"""Offered-load sweep for the replica router — throughput scaling,
per-replica balance, and the zero-post-warmup-recompiles invariant,
printed as one JSON document.

    python -m tools.bench_router                          # 1 vs 2 replicas
    python -m tools.bench_router --replica-counts 1,2,4
    python -m tools.bench_router --check-recompiles       # CI gate

Each sweep drives ``--requests`` mixed-size requests (unthrottled, or at
``--loads`` req/s) through a fresh :class:`~paddle_tpu.serving.Router`
over N single-device replicas of a jitted synthetic MLP. A warmup pass
covers every request size first, so the ``recompiles_post_warmup``
counter isolates steady-state compiles — it must be ZERO (each replica
engine compiled one executable per padded bucket during warmup and
reuses it for every later request; a nonzero count means the cache key
is unstable). ``--check-recompiles`` turns that invariant into an exit
code for ``tools/run_tests.py --bench-router``.

The throughput table is the capacity claim: N replicas = N engine worker
threads batching independently, so unthrottled throughput should scale
well above 1x (the acceptance bar is >=1.7x for 2 replicas) — reported
as ``speedup_vs_1`` per sweep, but not gated here because absolute CPU
throughput is machine-dependent.

``--device-ms`` models per-batch accelerator execution: after the jitted
compute, the model blocks that long with the GIL released — exactly how
an engine worker behaves while a real device runs its batch. This is
what makes replica scaling *measurable* here: on the CPU backend every
in-process XLA execution serializes (single client work queue, and CI
machines may have one core), so without it even a perfectly-balanced
router shows 1x. The routing layer — dispatch, balance, cache keys — is
what this bench is for; the model's FLOPs are stand-ins.
"""
from __future__ import annotations

import argparse
import copy
import json
import sys
import time
from concurrent.futures import wait


def _synthetic_model(dim: int = 64, device_ms: float = 2.0):
    """A jitted 2-layer MLP plus ``device_ms`` of simulated accelerator
    time per batch (a GIL-released block, like a real device wait)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    w1 = jnp.asarray(rng.randn(dim, 4 * dim).astype(np.float32))
    w2 = jnp.asarray(rng.randn(4 * dim, dim).astype(np.float32))

    @jax.jit
    def compute(x):
        return jnp.tanh(x @ w1) @ w2

    def fn(x):
        y = compute(x)
        jax.block_until_ready(y)
        if device_ms:
            time.sleep(device_ms / 1000.0)  # GIL released: replicas overlap
        return y

    return fn, dim


def _callable_factory(fn, base_cfg):
    """Engine factory over a plain callable (the bench's synthetic MLP),
    with the per-replica stat prefix the real factories apply."""
    from paddle_tpu.serving.engine import Engine

    def factory(replica):
        cfg = copy.copy(base_cfg)
        cfg.stat_prefix = f"{cfg.stat_prefix}.replica{replica.replica_id}"
        return Engine(fn, cfg, registry=replica.registry)
    return factory


def _total_misses(router):
    return sum(r.engine.cache.stats()["misses"] for r in router.replicas)


def run_sweep(router, requests, offered_qps, sizes, dim, seed=0):
    import numpy as np
    rng = np.random.RandomState(seed)
    # draw sizes randomly, not cycled: a fixed cycle correlates with the
    # router's rotating tie-break (e.g. 4 sizes over 2 replicas pins the
    # big requests to one replica), skewing rows while request counts
    # stay "balanced"
    draw = [sizes[rng.randint(len(sizes))] for _ in range(requests)]
    payloads = [rng.randn(s, dim).astype(np.float32) for s in draw]

    # warmup: every engine must see every padded-batch signature it can
    # meet later — each row bucket (coalesced batches pad up to the
    # max-batch bucket too). One request at a time, waited, so requests
    # don't coalesce into a shape that skips a bucket; the round-robin
    # tie-break spreads the n same-size requests over the n idle replicas.
    max_batch = router.replicas[0].engine.config.buckets.max_batch
    for s in sorted(set(sizes) | {max_batch}):
        for _ in router.replicas:
            router.submit([rng.randn(s, dim).astype(np.float32)]) \
                .result(timeout=120)
    misses_after_warmup = _total_misses(router)

    gap = 0.0 if not offered_qps else 1.0 / offered_qps
    t0 = time.monotonic()
    futs = []
    for i, x in enumerate(payloads):
        futs.append(router.submit([x]))
        if gap:
            # absolute schedule so slow submits don't lower the offered load
            sleep_until = t0 + (i + 1) * gap
            pause = sleep_until - time.monotonic()
            if pause > 0:
                time.sleep(pause)
    wait(futs, timeout=300)
    wall = time.monotonic() - t0
    errors = sum(1 for f in futs if f.exception() is not None)
    st = router.stats()
    reg = router.registry
    # per-replica latency histograms carry the replica prefix; merge by
    # taking the worst (routers care about the slowest replica's tail)
    p50 = max((reg.quantile(
        f"serving.replica{r.replica_id}.latency_ms", 0.50) or 0.0)
        for r in router.replicas)
    p95 = max((reg.quantile(
        f"serving.replica{r.replica_id}.latency_ms", 0.95) or 0.0)
        for r in router.replicas)
    return {
        "replicas": len(router.replicas),
        "offered_qps": offered_qps or None,
        "requests": requests,
        "errors": errors,
        "wall_s": round(wall, 4),
        "throughput_rps": round(requests / wall, 2),
        "p50_ms": round(p50, 3),
        "p95_ms": round(p95, 3),
        "balance_factor": round(st["balance_factor"], 4),
        "dispatched_per_replica": {
            k: v["dispatched"] for k, v in st["replicas"].items()},
        "recompiles_warmup": misses_after_warmup,
        "recompiles_post_warmup": _total_misses(router)
                                  - misses_after_warmup,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--replica-counts", default="1,2",
                    help="comma-separated replica counts to sweep")
    ap.add_argument("--requests", type=int, default=128)
    ap.add_argument("--loads", default="0",
                    help="comma-separated offered loads in req/s; 0 = "
                         "unthrottled")
    ap.add_argument("--sizes", default="1,2,4,8",
                    help="request row counts, cycled")
    ap.add_argument("--max-batch", type=int, default=16)
    ap.add_argument("--max-delay-ms", type=float, default=2.0)
    ap.add_argument("--dim", type=int, default=64,
                    help="synthetic model feature dim")
    ap.add_argument("--device-ms", type=float, default=10.0,
                    help="simulated accelerator time per batch (GIL-"
                         "released; 0 disables)")
    ap.add_argument("--check-recompiles", action="store_true",
                    help="exit 1 if any sweep saw a post-warmup recompile")
    args = ap.parse_args(argv)

    from paddle_tpu.core.monitor import StatRegistry
    from paddle_tpu.serving import EngineConfig, Router, RouterConfig

    fn, dim = _synthetic_model(args.dim, device_ms=args.device_ms)
    sizes = [int(s) for s in args.sizes.split(",") if s.strip()]
    loads = [float(x) for x in args.loads.split(",") if x.strip()]
    counts = [int(c) for c in args.replica_counts.split(",") if c.strip()]

    sweeps = []
    base_rps = {}
    for n in counts:
        for i, qps in enumerate(loads):
            cfg = EngineConfig(max_batch=args.max_batch,
                               max_batch_delay=args.max_delay_ms / 1000.0,
                               max_queue=max(1024, args.requests))
            router = Router(_callable_factory(fn, cfg),
                            RouterConfig(num_replicas=n,
                                         health_interval=0.1),
                            registry=StatRegistry())
            try:
                res = run_sweep(router, args.requests, qps, sizes, dim,
                                seed=i)
            finally:
                router.drain(timeout=60)
            key = qps
            if n == min(counts):
                base_rps[key] = res["throughput_rps"]
            base = base_rps.get(key)
            res["speedup_vs_1"] = (round(res["throughput_rps"] / base, 3)
                                   if base else None)
            sweeps.append(res)

    doc = {"bench": "router", "model": "synthetic-mlp", "dim": dim,
           "device_ms": args.device_ms, "max_batch": args.max_batch,
           "max_delay_ms": args.max_delay_ms, "sweeps": sweeps}
    json.dump(doc, sys.stdout, indent=2)
    print()
    if args.check_recompiles:
        bad = [s for s in sweeps if s["recompiles_post_warmup"] != 0]
        if bad:
            print(f"FAIL: {len(bad)} sweep(s) recompiled after warmup",
                  file=sys.stderr)
            return 1
        print("OK: zero post-warmup recompiles in every sweep",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
