#!/usr/bin/env python
"""PTA009 bench-audit gate: fail when the bench step paths pick up new
fusion breaks or host transfers.

Runs the trace audit over the bench entrypoints (``resnet_train_step``,
``gpt_train_step`` from :mod:`paddle_tpu.models.bench_audit`; the
serving-side ``llm_spec_decode_step`` from
:mod:`paddle_tpu.serving.llm.spec` — its one-fetch-per-tick contract is
exactly a host-transfer count; and the quantized hot paths
``compressed_allreduce_train_step`` / ``llm_int8_decode_step``, whose
quantize/dequantize stages must fuse in-graph) and
compares the per-entrypoint counts that move MFU — host transfers inside
the compiled region, large closed-over control-flow constants, missed
donation, retraces, and the HLO copy fraction — against the committed
``bench_audit_baseline.json``. The throughput gate
(check_bench_regression.py) sees a regression only after a TPU round;
this one catches the *cause* (a fusion break on the step path) on CPU in
CI, before any chip time is spent.

Usage:
    python tools/check_audit_regression.py              # run audit + gate
    python tools/check_audit_regression.py --report F   # gate a saved report
    python tools/check_audit_regression.py --write-baseline

Exit 1 on regression (or an entrypoint that fails to trace), 0 otherwise.
``--report`` consumes a ``trace_audit.json``-shaped file (the
``stats_payload`` schema), the seam the gate's own tests use to inject a
seeded regression.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "bench_audit_baseline.json")

#: the bench step paths under the gate
ENTRYPOINTS = ("resnet_train_step", "gpt_train_step",
               "llm_spec_decode_step",
               # paged-KV serving decode (serving/llm/paged/): the block
               # table rides the device step — must stay one host fetch
               # per tick, zero retraces after warmup
               "llm_paged_decode_step",
               # quantized hot paths (docs/quantization.md): the
               # compressed-gradient dp train step and the int8 serving
               # decode step — both must keep zero host transfers
               "compressed_allreduce_train_step", "llm_int8_decode_step",
               # long-context dp×sp train path: grads through the
               # ring-flash custom_vjp backward (sequence_parallel.py) —
               # both ring walks must stay fused, zero-host-transfer
               # device programs
               "gpt_ring_flash_train_step",
               # mesh topologies the collective_bytes gate covers
               # (fleet/audit_specs.py): the pp ppermute chain and the
               # ep all_to_all dispatch/combine pair
               "pipeline_train_step", "moe_train_step")

#: copy_fraction may drift this much absolutely before failing (XLA
#: version skew moves copy counts a little; a real fusion break moves a
#: lot — the hapi conv path regression that motivated PTA009 tripled it)
COPY_FRACTION_SLACK = 0.05

#: collective_bytes may grow this much relatively before failing (shape
#: tweaks in the audit specs move it a little; a comm regression — a
#: lost donation of the capacity factor, an extra ring round, an
#: accidental full-replica gather — moves it a lot)
COLLECTIVE_BYTES_SLACK = 0.05

#: unfused_boundary_bytes (PTA014) may grow this much relatively before
#: failing: XLA version skew nudges fusion decisions a little; a real
#: de-fusion — a new elementwise stage materializing before a matmul —
#: adds a whole activation's worth of HBM traffic
FUSION_BYTES_SLACK = 0.05


def summarize(payload):
    """Reduce a stats_payload to the gated per-entrypoint counters."""
    out = {}
    for name in ENTRYPOINTS:
        st = (payload.get("entrypoints") or {}).get(name)
        if st is None or st.get("error"):
            out[name] = {"error": (st or {}).get("error",
                                                 "entrypoint missing")}
            continue
        hlo = st.get("hlo") or {}
        instrs = int(hlo.get("instructions", 0)) or 1
        don = st.get("donation") or {}
        out[name] = {
            "host_transfers": len(st.get("transfers") or []),
            "large_consts": len(st.get("large_consts") or []),
            "donatable_inputs": int(don.get("donatable_inputs", 0)),
            "retraces": max(0, int(st.get("trace_count", 1)) - 1),
            "fingerprint_unstable":
                0 if st.get("fingerprint_stable", True) else 1,
            "copy_fraction": round(int(hlo.get("copies", 0)) / instrs, 4),
            "collective_bytes": int(st.get("collective_bytes", 0)),
            "collective_issues": len(st.get("collective_issues") or []),
            "unfused_boundary_bytes":
                int(st.get("unfused_boundary_bytes", 0)),
        }
    return out


def compare(baseline, current):
    """List of regression strings (empty == pass): any gated counter
    above baseline, copy_fraction above baseline + slack."""
    problems = []
    for name in ENTRYPOINTS:
        base, cur = baseline.get(name), current.get(name)
        if cur is None or "error" in cur:
            problems.append(
                f"{name}: failed to trace: "
                f"{(cur or {}).get('error', 'missing')}".strip())
            continue
        if base is None:
            problems.append(f"{name}: no baseline entry — rerun with "
                            f"--write-baseline")
            continue
        for key in ("host_transfers", "large_consts", "donatable_inputs",
                    "retraces", "fingerprint_unstable",
                    "collective_issues"):
            if cur.get(key, 0) > base.get(key, 0):
                problems.append(
                    f"{name}: {key} regressed "
                    f"{base.get(key, 0)} -> {cur.get(key, 0)}")
        allowed = base.get("copy_fraction", 0.0) + COPY_FRACTION_SLACK
        if cur.get("copy_fraction", 0.0) > allowed:
            problems.append(
                f"{name}: copy_fraction regressed "
                f"{base.get('copy_fraction', 0.0):.4f} -> "
                f"{cur.get('copy_fraction', 0.0):.4f} "
                f"(allowed <= {allowed:.4f}) — a fusion broke on the "
                f"step path")
        base_bytes = int(base.get("collective_bytes", 0))
        cur_bytes = int(cur.get("collective_bytes", 0))
        if cur_bytes > base_bytes * (1.0 + COLLECTIVE_BYTES_SLACK):
            problems.append(
                f"{name}: collective_bytes regressed "
                f"{base_bytes} -> {cur_bytes} (allowed <= "
                f"{int(base_bytes * (1.0 + COLLECTIVE_BYTES_SLACK))}) — "
                f"the step is putting more traffic on the wire per "
                f"iteration")
        base_fus = int(base.get("unfused_boundary_bytes", 0))
        cur_fus = int(cur.get("unfused_boundary_bytes", 0))
        if cur_fus > base_fus * (1.0 + FUSION_BYTES_SLACK):
            problems.append(
                f"{name}: unfused_boundary_bytes regressed "
                f"{base_fus} -> {cur_fus} (allowed <= "
                f"{int(base_fus * (1.0 + FUSION_BYTES_SLACK))}) — a "
                f"fusion boundary opened around a matmul; see "
                f"`python -m tools.analyze --only PTA014` for the "
                f"ranked misses")
    return problems


def run_bench_audit():
    """Trace just the bench entrypoints (forces CPU) and return the
    stats payload."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the ring-flash entrypoint shards over a dp×sp mesh: give the CPU
    # gate the same 8 virtual devices the test suite uses (conftest.py)
    # so its audited program is the multi-rank ring, not a 1×1 fallback.
    # Only provision when the flag is absent — never override an
    # operator's explicit device-count choice.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.analyze.trace import run_audit
    return run_audit(list(ENTRYPOINTS)).stats_payload()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--report", help="gate an existing trace_audit.json "
                                     "instead of running the audit")
    ap.add_argument("--baseline", default=BASELINE)
    ap.add_argument("--write-baseline", action="store_true",
                    help="record the current counts as the new baseline")
    args = ap.parse_args(argv)

    if args.report:
        with open(args.report) as f:
            payload = json.load(f)
    else:
        payload = run_bench_audit()
    if payload.get("error"):
        print(f"audit gate: trace audit unavailable:\n{payload['error']}")
        return 1
    current = summarize(payload)

    if args.write_baseline:
        with open(args.baseline, "w") as f:
            json.dump({"version": 1, "entrypoints": current}, f, indent=1,
                      sort_keys=True)
            f.write("\n")
        print(f"audit gate: baseline written to {args.baseline}")
        return 0

    try:
        with open(args.baseline) as f:
            baseline = json.load(f).get("entrypoints", {})
    except FileNotFoundError:
        print(f"audit gate: no baseline at {args.baseline}; run "
              f"--write-baseline first")
        return 1

    problems = compare(baseline, current)
    for name in ENTRYPOINTS:
        cur = current.get(name, {})
        print(f"audit gate [{name}]: " + (", ".join(
            f"{k}={v}" for k, v in sorted(cur.items()))))
    if problems:
        print("FAIL:")
        for p in problems:
            print(f"  - {p}")
        return 1
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
