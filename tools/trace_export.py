#!/usr/bin/env python
"""Export recorded paddle_tpu spans as Chrome/Perfetto trace JSON.

Two modes:

* **In-process** (the common one): call
  ``paddle_tpu.observability.export_chrome_trace(path)`` from the program
  that recorded the spans — the ring lives in that process.
* **Flight-dump conversion** (this CLI): convert the span records inside a
  crash ``flight_*.jsonl`` dump into a loadable trace::

      python tools/trace_export.py flight_20260805_1201_17.jsonl \
          -o trace.perfetto.json

Load the output at https://ui.perfetto.dev or chrome://tracing.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="convert a flight_*.jsonl dump (or a raw span-record "
                    "JSONL) to Chrome trace_event JSON")
    ap.add_argument("input", help="flight_*.jsonl dump, or '-' for stdin")
    ap.add_argument("-o", "--output", default="trace.perfetto.json",
                    help="output trace path (default %(default)s)")
    args = ap.parse_args(argv)

    from paddle_tpu.observability.export import to_trace_events

    fh = sys.stdin if args.input == "-" else open(args.input)
    spans, pid, other = [], 0, {}
    with fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("schema"):        # flight header
                pid = rec.get("pid", 0)
                other = {"flight_reason": rec.get("reason")}
            elif rec.get("kind") == "span" or (
                    "kind" not in rec and "ts_ns" in rec):
                spans.append(rec)
    doc = {"traceEvents": to_trace_events(spans, pid=pid),
           "displayTimeUnit": "ms", "otherData": other}
    with open(args.output, "w") as f:
        json.dump(doc, f)
    print(f"wrote {len(spans)} spans -> {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
