"""Shape buckets: the small closed set of padded shapes the engine runs.

XLA specializes an executable per concrete input shape, so an open-ended
request mix (batch 3, then 5, then 7, ...) means unbounded recompilation —
the shape-churn cost LazyTensor (arxiv 2102.13267) identifies. Bucketing
rounds every batch up to the next member of a fixed set (powers of two by
default, the same trick TVM-style compile-once stacks use), so after one
warmup pass every request hits a cached executable.

Padding rows are zeros and are sliced off before results are scattered
back; row-parallel models (anything per-example) produce bitwise-identical
rows whether or not padding rows ride along.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


def pow2_buckets(max_value: int, start: int = 1) -> Tuple[int, ...]:
    """(start, 2*start, ... , max_value) — max_value is always included."""
    out = []
    b = start
    while b < max_value:
        out.append(b)
        b *= 2
    out.append(max_value)
    return tuple(out)


class BucketSpec:
    """The batch (and optionally sequence) buckets the engine may run.

    ``batch_buckets`` bounds rows per dispatched batch; ``seq_buckets``
    (optional) pads axis 1 of rank>=2 inputs up to a bucket so variable
    sequence lengths also reuse executables. Sequence padding changes
    padded-token values (zeros), so it is only valid for models that mask
    padding — it is opt-in, unlike batch bucketing.
    """

    def __init__(self, batch_buckets: Sequence[int] = (),
                 seq_buckets: Optional[Sequence[int]] = None,
                 max_batch: int = 64):
        bb = tuple(sorted(set(int(b) for b in batch_buckets))) \
            or pow2_buckets(int(max_batch))
        if bb[0] < 1:
            raise ValueError(f"batch buckets must be >= 1, got {bb}")
        self.batch_buckets = bb
        self.seq_buckets = tuple(sorted(set(int(s) for s in seq_buckets))) \
            if seq_buckets else None

    @property
    def max_batch(self) -> int:
        return self.batch_buckets[-1]

    def batch_bucket_for(self, rows: int) -> Optional[int]:
        """Smallest bucket >= rows, or None when rows exceed every bucket."""
        for b in self.batch_buckets:
            if rows <= b:
                return b
        return None

    def seq_bucket_for(self, seq: Optional[int]) -> Optional[int]:
        """Smallest sequence bucket >= seq; unbucketed lengths (or no
        sequence bucketing configured) pass through unchanged."""
        if seq is None or self.seq_buckets is None:
            return seq
        for s in self.seq_buckets:
            if seq <= s:
                return s
        return seq

    def __repr__(self):
        return (f"BucketSpec(batch={list(self.batch_buckets)}, "
                f"seq={list(self.seq_buckets) if self.seq_buckets else None})")


def pad_rows(arrays: Sequence[np.ndarray], bucket_rows: int) -> List[np.ndarray]:
    """Zero-pad the leading axis of every array up to ``bucket_rows``."""
    out = []
    for a in arrays:
        rows = a.shape[0]
        if rows == bucket_rows:
            out.append(a)
            continue
        if rows > bucket_rows:
            raise ValueError(f"{rows} rows do not fit bucket {bucket_rows}")
        pad = np.zeros((bucket_rows - rows,) + a.shape[1:], dtype=a.dtype)
        out.append(np.concatenate([a, pad], axis=0))
    return out


def pad_seq(arrays: Sequence[np.ndarray], seq_bucket: Optional[int]) -> List[np.ndarray]:
    """Zero-pad axis 1 of rank>=2 arrays up to ``seq_bucket`` (no-op when
    seq bucketing is off or the array is already that long)."""
    if seq_bucket is None:
        return list(arrays)
    out = []
    for a in arrays:
        if a.ndim < 2 or a.shape[1] >= seq_bucket:
            out.append(a)
            continue
        width = [(0, 0)] * a.ndim
        width[1] = (0, seq_bucket - a.shape[1])
        out.append(np.pad(a, width))
    return out


def unpad_rows(arrays: Sequence[np.ndarray], rows: int) -> List[np.ndarray]:
    """Slice each output back to the real row count."""
    return [a[:rows] if getattr(a, "ndim", 0) > 0 else a for a in arrays]
