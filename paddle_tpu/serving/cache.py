"""ExecutableCache: compiled-callable cache keyed on (model, shapes, dtype).

The compile-once-reuse layer under both the serving engine and the
standalone :class:`~paddle_tpu.inference.Predictor`. An entry is whatever
``compile_fn`` returns — in practice a ``jax.jit``-wrapped call of the
deserialized StableHLO program, so each distinct input signature costs
exactly one XLA compile and every later hit is a cheap executable launch.
LRU-bounded with hit/miss/evict counters so recompile pressure is visible
(``/statsz`` surfaces them; zero misses after warmup is the steady state).
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from ..core import monitor as _mon
from ..observability import tracer as _tracer

#: signature element: ((dim, ...), dtype-string) per input array
SigT = Tuple[Tuple[Tuple[int, ...], str], ...]

_DEFAULT_CAPACITY_ENV = "PADDLE_TPU_EXEC_CACHE_SIZE"


def signature_of(arrays: Sequence[Any]) -> SigT:
    """Shape/dtype signature of a list of arrays (numpy or jax)."""
    return tuple((tuple(int(d) for d in a.shape), str(a.dtype))
                 for a in arrays)


class ExecutableCache:
    """LRU cache of compiled executables with observable counters."""

    def __init__(self, capacity: int = 128):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self._capacity = capacity
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Any, Any]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get_or_compile(self, key: Any, compile_fn: Callable[[], Any]) -> Any:
        """Return the cached executable for ``key``, compiling on miss.

        ``compile_fn`` runs outside the lock (XLA compiles can take
        seconds); concurrent misses on the same key race benignly — the
        first finisher's entry wins and the duplicate is dropped.
        """
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return entry
            self.misses += 1
        # compile hook: stamp every miss with its build duration (for jit
        # entries this is trace+lower; XLA compile itself may still be
        # deferred to first execution) — recompile pressure shows up as a
        # `jit.compile_ms` histogram and on the span timeline.
        t0 = time.perf_counter()
        with _tracer.span("jit/compile", {"cache_key": repr(key)[:200]}):
            compiled = compile_fn()
        _mon.stat_observe("jit.compile_ms",
                          (time.perf_counter() - t0) * 1e3)
        _mon.stat_add("jit.cache_misses", 1)
        with self._lock:
            winner = self._entries.setdefault(key, compiled)
            self._entries.move_to_end(key)
            while len(self._entries) > self._capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
        return winner

    def contains(self, key: Any) -> bool:
        with self._lock:
            return key in self._entries

    def clear(self):
        with self._lock:
            self._entries.clear()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"size": len(self._entries), "capacity": self._capacity,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}


_DEFAULT: Optional[ExecutableCache] = None
_DEFAULT_LOCK = threading.Lock()


def default_cache() -> ExecutableCache:
    """Process-wide cache (Predictors share it so two predictors over the
    same artifact reuse each other's executables). Capacity comes from
    ``PADDLE_TPU_EXEC_CACHE_SIZE`` (default 128)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            cap = int(os.environ.get(_DEFAULT_CAPACITY_ENV, "128") or "128")
            _DEFAULT = ExecutableCache(capacity=cap)
        return _DEFAULT


def _reset_default_cache_for_tests():
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None
