"""ExecutableCache: compiled-callable cache keyed on (model, shapes, dtype).

The compile-once-reuse layer under the serving engine, the standalone
:class:`~paddle_tpu.inference.Predictor`, the LLM scheduler and the
static decoder — ONE process-wide in-memory cache (``default_cache()``),
so two components over the same program reuse each other's executables.
An entry is whatever ``compile_fn`` returns — a ``jax.jit`` wrapper or an
AOT ``Compiled`` — so each distinct input signature costs exactly one XLA
compile and every later hit is a cheap executable launch. LRU-bounded
with hit/miss/evict counters published to the default StatRegistry
(``serving.executable_cache.*`` on ``/metricsz``; zero misses after
warmup is the steady state).

Persistence (fleet-wide, survives restarts) is two tiers under one root
(``PADDLE_TPU_COMPILE_CACHE`` or :func:`enable_persistent_compilation`):

* ``<root>/xla`` — JAX's own persistent compilation cache
  (``jax_compilation_cache_dir``): every ``jit`` in the process, not
  just serving, skips XLA backend compiles that any earlier process
  already paid for.
* ``<root>/executables`` — :class:`PersistentExecutableStore`: whole
  serialized AOT executables keyed by the cache's own process-stable
  signature tokens, loaded by ``get_or_compile(..., persist_key=...)``
  without issuing a compile request at all.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
import warnings
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from ..core import monitor as _mon
from ..observability import tracer as _tracer

#: signature element: ((dim, ...), dtype-string) per input array
SigT = Tuple[Tuple[Tuple[int, ...], str], ...]

_DEFAULT_CAPACITY_ENV = "PADDLE_TPU_EXEC_CACHE_SIZE"
_PERSIST_ENV = "PADDLE_TPU_COMPILE_CACHE"

#: /metricsz namespace for the shared cache's counters
_STAT_PREFIX = "serving.executable_cache."

#: bump when the on-disk executable entry format changes
_STORE_VERSION = 1


def signature_of(arrays: Sequence[Any]) -> SigT:
    """Shape/dtype signature of a list of arrays (numpy or jax)."""
    return tuple((tuple(int(d) for d in a.shape), str(a.dtype))
                 for a in arrays)


# -- persistent compilation (fleet-wide, survives restarts) -------------------

_PERSIST_ROOT: Optional[str] = None
_PERSIST_LOCK = threading.Lock()
_PERSIST_RESOLVED = False


def enable_persistent_compilation(path: Optional[str] = None) -> str:
    """Turn on the on-disk compilation tiers and return the cache root.

    Wires ``jax_compilation_cache_dir`` at ``<root>/xla`` (with the
    min-compile-time/min-entry-size floors dropped so every executable
    qualifies) and anchors the :class:`PersistentExecutableStore` at
    ``<root>/executables``. Idempotent; the first caller's root wins.
    Default root: ``$PADDLE_TPU_COMPILE_CACHE`` or
    ``~/.cache/paddle_tpu/compile``.
    """
    global _PERSIST_ROOT, _PERSIST_RESOLVED
    with _PERSIST_LOCK:
        if _PERSIST_ROOT is not None:
            return _PERSIST_ROOT
        root = (path or os.environ.get(_PERSIST_ENV, "").strip()
                or os.path.join(os.path.expanduser("~/.cache/paddle_tpu"),
                                "compile"))
        root = os.path.expanduser(root)
        try:
            import jax
            os.makedirs(os.path.join(root, "xla"), exist_ok=True)
            jax.config.update("jax_compilation_cache_dir",
                              os.path.join(root, "xla"))
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
            # jax latches "no cache" on the first compile; any import-time
            # jit before this point would otherwise pin the cache off for
            # the whole process
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc)
            _cc.reset_cache()
        except Exception as e:   # unwritable dir / exotic jax build
            warnings.warn(f"persistent compilation cache disabled: {e}")
        _PERSIST_ROOT = root
        _PERSIST_RESOLVED = True
        return root


def persistent_root() -> Optional[str]:
    """The active persistence root, auto-enabling from the environment on
    first call; None when persistence is off (no env var, no explicit
    :func:`enable_persistent_compilation`)."""
    global _PERSIST_RESOLVED
    with _PERSIST_LOCK:
        if _PERSIST_ROOT is not None or _PERSIST_RESOLVED:
            return _PERSIST_ROOT
        _PERSIST_RESOLVED = True
        if not os.environ.get(_PERSIST_ENV, "").strip():
            return None
    return enable_persistent_compilation()


def _reset_persistence_for_tests():
    global _PERSIST_ROOT, _PERSIST_RESOLVED, _STORE
    with _PERSIST_LOCK:
        _PERSIST_ROOT = None
        _PERSIST_RESOLVED = False
    with _STORE_LOCK:
        _STORE = None


class PersistentExecutableStore:
    """Whole serialized executables on disk, keyed by process-stable
    cache-key strings.

    Entries are ``pickle((payload, in_tree, out_tree))`` from
    ``jax.experimental.serialize_executable`` under a sha256 filename of
    (key, jax version, backend platform, store version) — a jax upgrade
    or platform change simply misses instead of loading an incompatible
    executable. All failure modes (corrupt file, version skew, unpickla-
    ble payload, unwritable dir) degrade to miss-with-warning: a bad
    store can never take down serving, the entry is recompiled and
    rewritten.
    """

    def __init__(self, directory: str):
        self.directory = directory

    def _path(self, key: str) -> str:
        import jax
        try:
            platform = jax.devices()[0].platform
        except Exception:
            platform = "unknown"
        tag = f"{_STORE_VERSION}|{jax.__version__}|{platform}|{key}"
        h = hashlib.sha256(tag.encode()).hexdigest()
        return os.path.join(self.directory, f"{h}.jaxexec")

    def load(self, key: str):
        """The deserialized executable for ``key``, or None."""
        from jax.experimental import serialize_executable as _se
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                payload, in_tree, out_tree = pickle.loads(f.read())
            exe = _se.deserialize_and_load(payload, in_tree, out_tree)
        except FileNotFoundError:
            _mon.stat_add(_STAT_PREFIX + "disk_misses", 1)
            return None
        except Exception as e:
            _mon.stat_add(_STAT_PREFIX + "disk_errors", 1)
            warnings.warn(
                f"persistent executable cache: dropping unreadable entry "
                f"{os.path.basename(path)} ({type(e).__name__}: {e}); "
                f"recompiling")
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        _mon.stat_add(_STAT_PREFIX + "disk_hits", 1)
        return exe

    def save(self, key: str, compiled: Any) -> bool:
        """Serialize ``compiled`` if it supports AOT serialization
        (``jax.stages.Compiled``); atomically write. False (with at most
        a warning) on anything else — callers treat persistence as an
        optimization, never state."""
        from jax.experimental import serialize_executable as _se
        try:
            blob = pickle.dumps(_se.serialize(compiled))
        except Exception:
            return False            # lazy jit wrapper etc. — memory-only
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(self.directory, exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError as e:
            warnings.warn(f"persistent executable cache: could not write "
                          f"{path}: {e}")
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        _mon.stat_add(_STAT_PREFIX + "disk_writes", 1)
        return True


_STORE: Optional[PersistentExecutableStore] = None
_STORE_LOCK = threading.Lock()


def persistent_store() -> Optional[PersistentExecutableStore]:
    """Process-wide executable store under the persistence root, or None
    when persistence is off."""
    global _STORE
    root = persistent_root()
    if root is None:
        return None
    with _STORE_LOCK:
        if _STORE is None or not _STORE.directory.startswith(root):
            _STORE = PersistentExecutableStore(
                os.path.join(root, "executables"))
        return _STORE


class ExecutableCache:
    """LRU cache of compiled executables with observable counters."""

    def __init__(self, capacity: int = 128):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self._capacity = capacity
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Any, Any]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get_or_compile(self, key: Any, compile_fn: Callable[[], Any], *,
                       persist_key: Optional[str] = None) -> Any:
        """Return the cached executable for ``key``, compiling on miss.

        ``compile_fn`` runs outside the lock (XLA compiles can take
        seconds); concurrent misses on the same key race benignly — the
        first finisher's entry wins and the duplicate is dropped.

        ``persist_key`` opts this entry into the on-disk executable tier
        (no-op when persistence is off). It MUST be process-stable —
        derived from artifact paths/signatures, never from ``id()`` — or
        a restarted process could load someone else's executable.
        Entries whose compiled object is not AOT-serializable silently
        stay memory-only.
        """
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                _mon.stat_add(_STAT_PREFIX + "hits", 1)
                return entry
            self.misses += 1
        _mon.stat_add(_STAT_PREFIX + "misses", 1)
        store = persistent_store() if persist_key else None
        compiled = store.load(persist_key) if store is not None else None
        from_disk = compiled is not None
        if compiled is None:
            # compile hook: stamp every miss with its build duration (for
            # jit entries this is trace+lower; XLA compile itself may
            # still be deferred to first execution) — recompile pressure
            # shows up as `jit.compile_ms` and on the span timeline.
            t0 = time.perf_counter()
            with _tracer.span("jit/compile",
                              {"cache_key": repr(key)[:200]}):
                compiled = compile_fn()
            _mon.stat_observe("jit.compile_ms",
                              (time.perf_counter() - t0) * 1e3)
            _mon.stat_add("jit.cache_misses", 1)
        with self._lock:
            winner = self._entries.setdefault(key, compiled)
            self._entries.move_to_end(key)
            while len(self._entries) > self._capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
                _mon.stat_add(_STAT_PREFIX + "evictions", 1)
            _mon.stat_set(_STAT_PREFIX + "size", len(self._entries))
        if store is not None and not from_disk and winner is compiled:
            store.save(persist_key, winner)
        return winner

    def contains(self, key: Any) -> bool:
        with self._lock:
            return key in self._entries

    def clear(self):
        with self._lock:
            self._entries.clear()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"size": len(self._entries), "capacity": self._capacity,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}


_DEFAULT: Optional[ExecutableCache] = None
_DEFAULT_LOCK = threading.Lock()


def default_cache() -> ExecutableCache:
    """Process-wide cache (Predictors share it so two predictors over the
    same artifact reuse each other's executables). Capacity comes from
    ``PADDLE_TPU_EXEC_CACHE_SIZE`` (default 128)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            cap = int(os.environ.get(_DEFAULT_CAPACITY_ENV, "128") or "128")
            _DEFAULT = ExecutableCache(capacity=cap)
        return _DEFAULT


def _reset_default_cache_for_tests():
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None
