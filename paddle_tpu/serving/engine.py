"""Engine: the async dynamic-batching inference facade.

One worker thread runs the dispatch loop: form a bucketed batch
(:class:`DynamicBatcher`), concatenate + zero-pad request rows up to the
bucket, execute through the shape-keyed :class:`ExecutableCache`, slice
the padded output apart, and resolve each request's future. Everything is
observable through a ``StatRegistry`` (queue depth, batch fill, latency
percentiles, recompiles) and drain is graceful: admission stops, queued
work flushes, every admitted future resolves.

Preemption wiring: ``engine.arm_preemption(guard)`` makes the worker begin
a drain the moment the elastic :class:`PreemptionGuard` observes SIGTERM —
serve traffic until the platform takes the machine, never strand a future.
``install_drain_signal_handler`` arms the engine's own SIGTERM hook via
the chained-handler substrate, so it composes with (not clobbers) the
guard's handler.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import InvalidStateError
from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np

from ..core import monitor as _mon
from ..distributed.elastic import ChainedSignalHandler, PreemptionGuard
from ..observability import flight as _flight
from ..observability import tracer as _otrace
from .batcher import Batch, DynamicBatcher
from .buckets import BucketSpec, pad_rows, pad_seq, unpad_rows
from .cache import ExecutableCache, default_cache, signature_of
from .queue import BatchQueue
from .request import (Deadline, EngineDraining, EngineKilled,
                      InferenceRequest, RequestTooLarge)

ModelT = Union[str, Callable[..., Any], "object"]


class EngineConfig:
    """Tunables for the serving engine (see docs/serving.md)."""

    def __init__(self,
                 batch_buckets: Sequence[int] = (),
                 seq_buckets: Optional[Sequence[int]] = None,
                 max_batch: int = 64,
                 max_queue: int = 256,
                 max_batch_delay: float = 0.005,
                 admission_block: bool = True,
                 admission_timeout: Optional[float] = 2.0,
                 oversize_policy: str = "split",
                 default_deadline: Optional[float] = None,
                 stat_prefix: str = "serving"):
        self.buckets = BucketSpec(batch_buckets, seq_buckets,
                                  max_batch=max_batch)
        self.max_queue = int(max_queue)
        self.max_batch_delay = float(max_batch_delay)
        self.admission_block = bool(admission_block)
        self.admission_timeout = admission_timeout
        if oversize_policy not in ("split", "reject"):
            raise ValueError(
                f"oversize_policy must be 'split' or 'reject', "
                f"got {oversize_policy!r}")
        self.oversize_policy = oversize_policy
        self.default_deadline = default_deadline
        self.stat_prefix = stat_prefix


class DrainableEngineBase:
    """Drain/preemption/signal plumbing shared by the classifier
    :class:`Engine` and the LLM :class:`~paddle_tpu.serving.llm.LLMEngine`.

    Subclasses call :meth:`_init_serving_base` in ``__init__``, own a
    ``BatchQueue`` in ``self._queue``, and run a single worker thread that
    polls :attr:`draining` — ``_on_drain_signal`` is flag-only
    (async-signal-safe: closing the queue takes its lock, which the
    interrupted thread may hold), and the worker performs the actual
    ``queue.close()`` at its next poll point.
    """

    def _init_serving_base(self, registry: Optional[_mon.StatRegistry],
                           stat_prefix: str):
        # activate env-configured persistent compilation before this
        # engine's first compile (no-op when PADDLE_TPU_COMPILE_CACHE is
        # unset and enable_persistent_compilation() was never called)
        from .cache import persistent_root
        persistent_root()
        self._registry = registry or _mon.default_registry()
        self._prefix = stat_prefix
        self._draining = threading.Event()
        self._stopped = threading.Event()
        self._guard: Optional[PreemptionGuard] = None
        self._signal_chain: Optional[ChainedSignalHandler] = None
        self._drain_signaled = False  # set (only) from _on_drain_signal
        self._admission_paused = threading.Event()
        self._killed = threading.Event()
        self._kill_reason = ""

    @property
    def registry(self) -> _mon.StatRegistry:
        return self._registry

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def arm_preemption(self, guard: Optional[PreemptionGuard] = None):
        """Begin a graceful drain when ``guard`` observes preemption. With
        no argument a fresh guard is installed (chained signal handlers)."""
        self._guard = guard if guard is not None else PreemptionGuard()
        return self._guard

    def install_drain_signal_handler(self, signals=None):
        """Arm SIGTERM/SIGINT (or ``signals``) to trigger drain, chaining —
        not replacing — any handler already installed (e.g. a
        PreemptionGuard's)."""
        if self._signal_chain is not None and self._signal_chain.installed:
            return self._signal_chain
        kwargs = {} if signals is None else {"signals": tuple(signals)}
        self._signal_chain = ChainedSignalHandler(
            self._on_drain_signal, **kwargs)
        self._signal_chain.install()
        return self._signal_chain

    def _on_drain_signal(self, signum, frame):
        """Async-signal-safe drain trigger: only sets the flag. Closing the
        queue takes its lock — if the signal lands while the interrupted
        thread holds that lock, a close() here would self-deadlock — so the
        worker loop performs the close at its next poll. The flight dump
        happens on the worker thread for the same reason (file IO here
        would run in signal context)."""
        self._drain_signaled = True
        self._draining.set()

    def begin_drain(self):
        """Stop admission and let the worker flush the queue (non-blocking).
        Thread-safe, but NOT for signal context: closing the queue acquires
        its lock — signal handlers must go through ``_on_drain_signal``."""
        self._draining.set()
        self._queue.close()

    # -- fleet control plane (pause / hard-kill) ----------------------------
    @property
    def admission_paused(self) -> bool:
        return self._admission_paused.is_set()

    def pause_admission(self):
        """Stop admitting new requests WITHOUT draining: queued and
        in-flight work completes, the worker stays alive, and
        :meth:`resume_admission` reopens the front door. The weight
        hot-swap path uses this to quiesce a replica."""
        self._admission_paused.set()

    def resume_admission(self):
        self._admission_paused.clear()

    @property
    def was_killed(self) -> bool:
        return self._killed.is_set()

    def kill(self, reason: str = "killed") -> List[dict]:
        """Hard-kill (in-process SIGKILL analog): fail every queued request
        with :class:`EngineKilled` immediately — unlike drain, nothing is
        flushed — and flag the worker to abort in-flight work at its next
        poll point. Returns one snapshot record per failed request
        (``{"req_id", "phase", "tokens"}``) so recovery paths can
        enumerate what was in the engine. Safe to call from any thread;
        idempotent."""
        self._kill_reason = str(reason)
        self._killed.set()
        self._draining.set()
        return self._queue.fail_all(
            lambda: EngineKilled(
                f"engine hard-killed ({self._kill_reason}); "
                f"request aborted before execution"))

    def _stat_add(self, name: str, v):
        self._registry.add(f"{self._prefix}.{name}", v)

    def _stat_set(self, name: str, v):
        self._registry.set(f"{self._prefix}.{name}", v)

    def _stat_observe(self, name: str, v):
        self._registry.observe(f"{self._prefix}.{name}", v)


class Engine(DrainableEngineBase):
    """submit()/submit_many()/drain() over a batched, cached model.

    ``model`` may be:
      * an :class:`~paddle_tpu.inference.Predictor` (or anything with a
        compatible ``run(list_of_arrays) -> list_of_arrays``),
      * a path prefix of a ``jit.save`` artifact (a Predictor is created),
      * a plain callable ``fn(*arrays) -> array-or-list`` (tests, benches).
    """

    def __init__(self, model: ModelT, config: Optional[EngineConfig] = None,
                 registry: Optional[_mon.StatRegistry] = None,
                 cache: Optional[ExecutableCache] = None):
        self._config = config or EngineConfig()
        self._init_serving_base(registry, self._config.stat_prefix)
        self._model_fn, self._cache, self._model_key, self._wrap_in_cache = \
            self._resolve_model(model, cache)
        self._queue = BatchQueue(max_size=self._config.max_queue)
        self._batcher = DynamicBatcher(
            self._queue, self._config.buckets,
            max_batch_delay=self._config.max_batch_delay)
        # admitted-but-unresolved futures, keyed to their request id so
        # kill() can return an exact snapshot of what was in flight
        self._inflight: dict = {}
        self._inflight_lock = threading.Lock()
        self._worker = threading.Thread(
            target=self._worker_loop, name="paddle-tpu-serving-worker",
            daemon=True)
        self._worker.start()

    # -- model resolution ---------------------------------------------------
    def _resolve_model(self, model: ModelT, cache: Optional[ExecutableCache]):
        if isinstance(model, str):
            from ..inference import Config, create_predictor
            model = create_predictor(Config(model))
        run = getattr(model, "run", None)
        if callable(run):
            # Predictor path: its run() already goes through the shared
            # default ExecutableCache; reuse that cache for stats so the
            # engine's recompile counter reflects reality.
            pred_cache = getattr(model, "_exec_cache", None)
            # pick the first cache that EXISTS (`is not None`), not the
            # first truthy one — an empty ExecutableCache has len() == 0
            # and is falsy, so `or`-chaining would silently drop it
            use = cache if cache is not None else pred_cache
            return (lambda arrays: run(arrays)), \
                (use if use is not None else default_cache()), \
                ("predictor", id(model)), False
        if callable(model):
            fn = model

            def _call(arrays: List[np.ndarray]) -> List[Any]:
                out = fn(*arrays)
                return list(out) if isinstance(out, (list, tuple)) else [out]
            # plain callables share the process-wide cache; the key holds
            # the fn OBJECT (not id(fn) — ids are reused after GC, and in
            # a shared cache a recycled id would alias two models). A miss
            # marks the first time a padded signature is seen (== a jit
            # compile when fn is jitted).
            return _call, \
                (cache if cache is not None else default_cache()), \
                ("callable", fn), True
        raise TypeError(
            f"model must be a Predictor, artifact path prefix, or callable; "
            f"got {type(model).__name__}")

    # -- public API ---------------------------------------------------------
    @property
    def config(self) -> EngineConfig:
        return self._config

    @property
    def cache(self) -> ExecutableCache:
        return self._cache

    def submit(self, inputs: Sequence[np.ndarray],
               deadline: Optional[Union[Deadline, float]] = None):
        """Enqueue one request; returns a ``concurrent.futures.Future``
        whose result is the list of output arrays (rows matching the
        request's rows)."""
        if self._killed.is_set():
            self._stat_add("rejected_killed", 1)
            raise EngineKilled(
                f"engine was hard-killed ({self._kill_reason}); "
                f"submit rejected")
        if self._draining.is_set():
            self._stat_add("rejected_draining", 1)
            raise EngineDraining("engine is draining; submit rejected")
        if self._admission_paused.is_set():
            self._stat_add("rejected_paused", 1)
            raise EngineDraining(
                "engine admission is paused (fleet control); "
                "submit rejected")
        if deadline is None and self._config.default_deadline is not None:
            deadline = self._config.default_deadline
        if deadline is not None and not isinstance(deadline, Deadline):
            deadline = Deadline(float(deadline))
        req = InferenceRequest(inputs, deadline=deadline)
        if (self._config.oversize_policy == "reject"
                and req.nrows > self._config.buckets.max_batch):
            self._stat_add("rejected_oversize", 1)
            raise RequestTooLarge(
                f"request has {req.nrows} rows but the largest batch bucket "
                f"is {self._config.buckets.max_batch} and oversize_policy="
                f"'reject'; split the request or raise max_batch")
        try:
            self._queue.put(req, block=self._config.admission_block,
                            timeout=self._config.admission_timeout)
        except Exception:
            self._stat_add("rejected_queue_full", 1)
            raise
        with self._inflight_lock:
            self._inflight[req.future] = req.req_id
        req.future.add_done_callback(self._forget_future)
        self._stat_set("queue_depth", len(self._queue))
        return req.future

    def submit_many(self, requests: Sequence[Sequence[np.ndarray]],
                    deadline: Optional[Union[Deadline, float]] = None):
        return [self.submit(inputs, deadline=deadline)
                for inputs in requests]

    def kill(self, reason: str = "killed") -> List[dict]:
        """Hard-kill, returning records for queued requests (failed here)
        AND the admitted-but-unresolved ones the worker will abort at its
        next poll point (``phase: "inflight"``)."""
        records = list(super().kill(reason))
        seen = {r["req_id"] for r in records}
        with self._inflight_lock:
            records += [{"req_id": rid, "phase": "inflight", "tokens": 0}
                        for rid in self._inflight.values()
                        if rid not in seen]
        return records

    def drain(self, timeout: Optional[float] = None) -> List:
        """Graceful drain: stop admission, flush every queued request, wait
        for the worker, and return the futures of all requests that were
        in flight when the drain began (all resolved on return)."""
        with self._inflight_lock:
            inflight = list(self._inflight)
        self.begin_drain()
        self._stopped.wait(timeout)
        if self._signal_chain is not None:
            self._signal_chain.uninstall()
        self._stat_set("queue_depth", 0)
        return inflight

    close = drain

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.drain()
        return False

    def stats(self) -> dict:
        """Scalar stats + histogram summaries + cache counters (the
        ``/statsz`` payload)."""
        pre = self._prefix + "."
        scalars = self._registry.stats_with_prefix(pre)
        hists = self._registry.histograms_with_prefix(pre)
        return {"stats": scalars, "histograms": hists,
                "executable_cache": self._cache.stats(),
                "draining": self.draining,
                "queue_depth": len(self._queue)}

    # -- worker -------------------------------------------------------------
    def _forget_future(self, fut):
        with self._inflight_lock:
            self._inflight.pop(fut, None)

    def _worker_loop(self):
        poll = max(0.01, self._config.max_batch_delay)
        try:
            while True:
                if self._killed.is_set():
                    break
                if self._guard is not None and self._guard.preempted \
                        and not self._draining.is_set():
                    self._stat_add("preemption_drains", 1)
                    self.begin_drain()
                elif self._draining.is_set() and not self._queue.closed:
                    # the flag came from _on_drain_signal (which cannot
                    # touch the queue lock); finish the drain here
                    self._queue.close()
                batch = self._batcher.next_batch(timeout=poll)
                self._stat_set("queue_depth", len(self._queue))
                self._stat_set("deadline_evicted",
                               self._queue.evicted_expired)
                if batch is None:
                    if self._draining.is_set() and len(self._queue) == 0:
                        break
                    continue
                self._execute(batch)
                self._publish_cache_stats()
        finally:
            if self._killed.is_set():
                # hard-kill: fail whatever was admitted but not yet
                # resolved (queued requests were failed by kill() itself;
                # this catches the batch the worker never finished)
                with self._inflight_lock:
                    victims = list(self._inflight)
                exc = EngineKilled(
                    f"engine hard-killed ({self._kill_reason}); "
                    f"in-flight request aborted")
                for fut in victims:
                    try:
                        fut.set_exception(exc)
                    except InvalidStateError:
                        pass  # resolved by a racing complete; verdict stands
                _flight.record_event(
                    "engine_killed",
                    {"engine": self._prefix, "reason": self._kill_reason,
                     "aborted": len(victims)})
            if self._drain_signaled:
                # SIGTERM-initiated drain: leave the post-mortem timeline
                # (worker thread — never in signal context)
                _flight.record_event("sigterm_drain",
                                     {"engine": self._prefix})
                _flight.dump_if_armed("sigterm_drain")
            self._stopped.set()

    def _publish_cache_stats(self):
        s = self._cache.stats()
        self._stat_set("cache.hits", s["hits"])
        self._stat_set("cache.misses", s["misses"])
        self._stat_set("cache.evictions", s["evictions"])
        self._stat_set("recompiles", s["misses"])

    def _dispatch(self, arrays: List[np.ndarray]) -> List[np.ndarray]:
        """Run one padded, bucket-shaped batch through the cached model.

        Predictor models already route through the shared ExecutableCache
        inside run(); wrapping them again here would double-count hits."""
        if self._wrap_in_cache:
            sig = signature_of(arrays)
            runner = self._cache.get_or_compile(
                (self._model_key, sig), lambda: self._model_fn)
            outs = runner(arrays)
        else:
            outs = self._model_fn(arrays)
        return [np.asarray(o) for o in outs]

    def _execute(self, batch: Batch):
        with _otrace.span("serving/execute_batch"):
            self._execute_inner(batch)

    def _execute_inner(self, batch: Batch):
        t0 = time.monotonic()
        reqs = batch.requests
        try:
            if batch.oversize:
                # one request wider than every bucket: run it alone in
                # max-bucket chunks and stitch the rows back together
                outs = self._execute_oversize(reqs[0], batch.seq_bucket)
                self._finish(reqs[0], outs)
            else:
                n_in = len(reqs[0].inputs)
                padded_inputs = [pad_seq(r.inputs, batch.seq_bucket)
                                 for r in reqs]
                cols = [np.concatenate([p[i] for p in padded_inputs], axis=0)
                        for i in range(n_in)]
                padded = pad_rows(cols, batch.bucket_rows)
                outs = self._dispatch(padded)
                outs = unpad_rows(outs, batch.rows)
                offset = 0
                for r in reqs:
                    self._finish(r, [o[offset:offset + r.nrows]
                                     if getattr(o, "ndim", 0) > 0 else o
                                     for o in outs])
                    offset += r.nrows
                self._stat_observe("batch_fill", batch.fill_ratio)
                self._stat_observe("batch_requests", len(reqs))
                if len(reqs) > 1:
                    self._stat_add("coalesced_batches", 1)
            self._stat_add("batches", 1)
            self._stat_add("rows", batch.rows)
            self._stat_observe("batch_exec_ms",
                               (time.monotonic() - t0) * 1000.0)
        except Exception as e:
            self._stat_add("batch_errors", 1)
            for r in reqs:
                r.fail(e)

    def _execute_oversize(self, req: InferenceRequest,
                          seq_bucket) -> List[np.ndarray]:
        spec = self._config.buckets
        step = spec.max_batch
        chunks: List[List[np.ndarray]] = []
        inputs = pad_seq(req.inputs, seq_bucket)
        for start in range(0, req.nrows, step):
            part = [a[start:start + step] for a in inputs]
            rows = part[0].shape[0]
            padded = pad_rows(part, spec.batch_bucket_for(rows))
            outs = self._dispatch(padded)
            chunks.append(unpad_rows(outs, rows))
        self._stat_add("oversize_splits", 1)
        return [np.concatenate([c[i] for c in chunks], axis=0)
                for i in range(len(chunks[0]))]

    def _finish(self, req: InferenceRequest, outs: List[np.ndarray]):
        if req.expired:
            req.fail_expired()
            return
        if not req.future.done():
            self._stat_observe(
                "latency_ms", (time.monotonic() - req.t_enqueue) * 1000.0)
            self._stat_add("completed", 1)
            req.future.set_result(outs)


# -- trace-audit registration (tools/analyze/trace, PTA009/PTA010) -----------

def _audit_serving_predict_spec():
    """The engine's hot path for a callable model: a functionalized Layer
    forward jitted per padded signature. Audited on a tiny Linear so the
    program is small but structurally the production one."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..core import audit
    from ..jit.functionalize import build_pure
    from .. import nn

    lin = nn.Linear(6, 3)
    params = list(lin.parameters())
    pure, _meta = build_pure(lin.forward, params)
    base_params = [np.asarray(p._data) for p in params]

    def predict(param_raws, x, key):
        # static_kwargs pinned to None: the serving engine calls the
        # forward with positional arrays only
        return pure(list(param_raws), (x,), key, None)

    def make_args(variant):
        rng = np.random.default_rng(77 + variant)
        param_raws = [jnp.asarray(b) for b in base_params]
        x = jnp.asarray(rng.standard_normal((4, 6)), jnp.float32)
        return (param_raws, x, jax.random.PRNGKey(variant))

    return audit.AuditSpec(fn=predict, make_args=make_args, jit_kwargs={})


def _register_audit_entrypoints():
    from ..core import audit
    audit.register_entrypoint("serving_predict", _audit_serving_predict_spec,
                              tags=("serving",))


_register_audit_entrypoints()
