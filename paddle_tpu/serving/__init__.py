"""paddle_tpu.serving: async dynamic-batching inference engine.

The traffic-facing layer over :mod:`paddle_tpu.inference`: concurrent
requests are admitted into a bounded :class:`BatchQueue`, coalesced by a
:class:`DynamicBatcher` into batches padded to a small closed set of shape
buckets, and executed through a shape-keyed :class:`ExecutableCache` so
after warmup no request ever waits on an XLA recompile. See
docs/serving.md for architecture and tuning.

Quick start::

    from paddle_tpu import serving
    engine = serving.Engine("/path/to/model")      # jit.save prefix
    fut = engine.submit([x])                        # -> Future
    y, = fut.result()
    engine.drain()                                  # graceful shutdown

Or over HTTP: ``python -m paddle_tpu.serving serve --model /path/to/model``.

LLM generation serving (static-slot KV cache + continuous batching) lives
in the lazily imported :mod:`paddle_tpu.serving.llm` submodule — see its
docstring and docs/serving.md "LLM serving"; the CLI entry point is
``python -m paddle_tpu.serving serve-llm``.
"""
from __future__ import annotations

from .buckets import BucketSpec, pow2_buckets  # noqa: F401
from .cache import ExecutableCache, default_cache, signature_of  # noqa: F401
from .queue import BatchQueue  # noqa: F401
from .batcher import Batch, DynamicBatcher  # noqa: F401
from .engine import Engine, EngineConfig  # noqa: F401
from .request import (  # noqa: F401
    Deadline, DeadlineExceeded, EngineDraining, EngineKilled,
    InferenceRequest, QueueFull, RequestTooLarge, ServingError,
    TokenStreamDivergence)
from .sharding import ShardingSpec, ResolvedSharding  # noqa: F401
from .replica import Replica  # noqa: F401
from .router import (  # noqa: F401
    NoHealthyReplicas, Router, RouterConfig,
    llm_replica_factory, predictor_replica_factory)

__all__ = [
    "Engine", "EngineConfig", "BucketSpec", "pow2_buckets",
    "ExecutableCache", "default_cache", "signature_of", "BatchQueue",
    "DynamicBatcher", "Batch", "InferenceRequest", "Deadline",
    "DeadlineExceeded", "EngineDraining", "EngineKilled", "QueueFull",
    "RequestTooLarge", "ServingError", "TokenStreamDivergence",
    "ShardingSpec", "ResolvedSharding",
    "Replica", "Router", "RouterConfig", "NoHealthyReplicas",
    "llm_replica_factory", "predictor_replica_factory", "llm", "fleet",
]


def __getattr__(name):
    # `serving.llm` pulls in jax at import time (compiled decode programs);
    # keep classifier serving importable without that cost by loading the
    # LLM submodule on first access. `serving.fleet` (autoscaler/swap/
    # replay control plane) stays lazy for the same reason — its swap path
    # imports the checkpoint stack.
    if name in ("llm", "fleet"):
        import importlib
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
