"""BatchQueue: bounded FIFO admission queue with deadline eviction.

Admission control is the backpressure point: ``put`` blocks up to the
caller's patience when the queue is full (or rejects immediately in
``block=False`` mode) and raises :class:`QueueFull` — callers see load
shedding as an explicit error instead of unbounded memory growth.
Deadline-expired requests are evicted at the head (FIFO order means the
head is the oldest, so expiry is observed in arrival order) and their
futures fail with ``DeadlineExceeded`` before any device work is wasted.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from ..observability import tracer as _otrace
from .request import EngineDraining, InferenceRequest, QueueFull


class BatchQueue:
    """Bounded FIFO of :class:`InferenceRequest` with condition-variable
    hand-off between submitters and the batcher worker."""

    def __init__(self, max_size: int = 256, clock=time.monotonic):
        if max_size < 1:
            raise ValueError("max_size must be >= 1")
        self._max = max_size
        self._clock = clock
        self._dq: "deque[InferenceRequest]" = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._closed = False
        self._evicted_expired = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._dq)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    @property
    def evicted_expired(self) -> int:
        """Deadline-evicted request count; read under the queue lock (the
        counter is updated inside ``take``'s critical section)."""
        with self._lock:
            return self._evicted_expired

    def close(self):
        """Stop admission (drain). Waiting putters fail with
        EngineDraining; takers drain the remaining items then see None."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def fail_all(self, exc_factory: Callable[[], BaseException]) -> list:
        """Hard-kill path: close admission and fail every queued request
        with ``exc_factory()`` (drain lets takers consume the backlog;
        a kill must not — the worker is already gone). Returns one
        snapshot record per request actually failed — ``{"req_id",
        "phase": "queued", "tokens"}`` — so recovery paths and tests can
        enumerate exactly what was dropped instead of just counting it.
        (``tokens`` is non-zero only for a replayed generation request
        that was re-queued mid-recovery.)"""
        with self._lock:
            self._closed = True
            victims = list(self._dq)
            self._dq.clear()
            self._not_empty.notify_all()
            self._not_full.notify_all()
        records = []
        for req in victims:
            if req.fail(exc_factory()):
                records.append({
                    "req_id": req.req_id, "phase": "queued",
                    "tokens": len(getattr(req, "tokens", ()) or ())})
        return records

    # -- producer side ------------------------------------------------------
    def put(self, req: InferenceRequest, block: bool = True,
            timeout: Optional[float] = None):
        # admission span: shows queue backpressure (blocked puts) on the
        # timeline next to the worker's execute spans
        with _otrace.span("serving/queue_put"):
            self._put(req, block, timeout)

    def _put(self, req: InferenceRequest, block: bool,
             timeout: Optional[float]):
        with self._not_full:
            if self._closed:
                raise EngineDraining("engine is draining; request rejected")
            if len(self._dq) >= self._max:
                if not block:
                    raise QueueFull(
                        f"queue at capacity ({self._max}); request rejected")
                end = None if timeout is None else self._clock() + timeout
                while len(self._dq) >= self._max and not self._closed:
                    remaining = None if end is None else end - self._clock()
                    if remaining is not None and remaining <= 0:
                        raise QueueFull(
                            f"queue stayed at capacity ({self._max}) for "
                            f"{timeout}s; request rejected")
                    self._not_full.wait(remaining)
                if self._closed:
                    raise EngineDraining(
                        "engine began draining while request waited for "
                        "queue space")
            self._dq.append(req)
            self._not_empty.notify()

    # -- consumer side ------------------------------------------------------
    def take(self, timeout: Optional[float] = None,
             fits: Optional[Callable[[InferenceRequest], bool]] = None
             ) -> Optional[InferenceRequest]:
        """Pop the head request, or None.

        None means: timed out empty, closed-and-empty, or the head exists
        but ``fits(head)`` is False (the caller's batch is full / shape-
        incompatible; the head stays queued for the next batch). Expired
        heads are evicted (future fails) and skipped.
        """
        end = None if timeout is None else self._clock() + timeout
        with self._not_empty:
            while True:
                while self._dq and self._dq[0].expired:
                    victim = self._dq.popleft()
                    victim.fail_expired()
                    self._evicted_expired += 1
                    self._not_full.notify()
                if self._dq:
                    head = self._dq[0]
                    if fits is not None and not fits(head):
                        return None
                    self._dq.popleft()
                    self._not_full.notify()
                    return head
                if self._closed:
                    return None
                remaining = None if end is None else end - self._clock()
                if remaining is not None and remaining <= 0:
                    return None
                self._not_empty.wait(remaining)

    def take_many(self, max_n: int, timeout: Optional[float] = None,
                  fits: Optional[Callable[[InferenceRequest], bool]] = None
                  ) -> list:
        """Pop up to ``max_n`` requests: block (per ``take`` semantics) for
        the first, then greedily drain without waiting. Used by the LLM
        scheduler to admit a burst of sequences into free slots in one
        tick. Returns a possibly-empty list."""
        out: list = []
        if max_n < 1:
            return out
        first = self.take(timeout=timeout, fits=fits)
        if first is None:
            return out
        out.append(first)
        while len(out) < max_n:
            nxt = self.take(timeout=0, fits=fits)
            if nxt is None:
                break
            out.append(nxt)
        return out
