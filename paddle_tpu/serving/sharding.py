"""GSPMD sharding substrate for serving: serializable specs + resolution.

Training already partitions through ``Mesh``/``NamedSharding``/
``PartitionSpec`` (``distributed.mesh``); this module carries the same
vocabulary to inference so a predictor artifact can be served
model-parallel. Three layers:

* :class:`ShardingSpec` — the JSON-serializable statement of intent
  (ordered mesh axis sizes + per-input and optional per-param
  ``PartitionSpec``s). ``jit.save(..., sharding=...)`` persists it as a
  ``<prefix>.pdsharding.json`` sidecar next to the StableHLO artifact so a
  replica can reconstruct ``NamedSharding`` on load without the model's
  Python code.
* :class:`ResolvedSharding` — the spec bound to concrete devices: a
  ``Mesh``, one ``NamedSharding`` per input/param, and a hashable
  ``token`` that joins the :class:`~paddle_tpu.serving.cache
  .ExecutableCache` key. The token includes the *device ids*, not just
  axis names/sizes: two replicas over different device subsets share the
  process-wide default cache and must never collide on an executable
  compiled for the other's devices (and neither may collide with the
  unsharded key, which is a plain ``(model_key, sig)`` 2-tuple).
* :func:`resolve` — binding with warn-and-fallback semantics: any
  mismatch (mesh larger than the visible device count, spec axes unknown
  to the mesh, input-count drift) warns and returns ``None``, and the
  caller serves replicated — a stale sidecar must never brick a
  predictor.
"""
from __future__ import annotations

import json
import os
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

#: sidecar filename suffix, appended to the jit.save artifact prefix
#: (sibling of ``<prefix>.pdmodel`` / ``<prefix>.pdiparams``)
SIDECAR_SUFFIX = ".pdsharding.json"

SIDECAR_FORMAT = 1


def spec_to_lists(spec) -> Optional[List]:
    """``PartitionSpec`` -> JSON-able nested lists (None stays None —
    replicated)."""
    if spec is None:
        return None
    return [list(ax) if isinstance(ax, (tuple, list)) else ax
            for ax in tuple(spec)]


def lists_to_spec(obj):
    """JSON nested lists -> ``PartitionSpec`` (None -> fully replicated)."""
    from jax.sharding import PartitionSpec
    if obj is None:
        return PartitionSpec()
    return PartitionSpec(*[tuple(ax) if isinstance(ax, list) else ax
                           for ax in obj])


def _spec_axes(spec) -> Tuple[str, ...]:
    """Flat mesh-axis names a PartitionSpec references."""
    out = []
    for ax in tuple(spec or ()):
        if ax is None:
            continue
        if isinstance(ax, (tuple, list)):
            out.extend(str(a) for a in ax)
        else:
            out.append(str(ax))
    return tuple(out)


def _spec_key(spec) -> Any:
    """Hashable identity of one PartitionSpec (for cache tokens)."""
    if spec is None:
        return None
    return tuple(tuple(ax) if isinstance(ax, (tuple, list)) else ax
                 for ax in tuple(spec))


def mesh_token(mesh) -> Tuple:
    """Hashable identity of a Mesh: axis names + shape + flat device ids.

    Device ids are load-bearing: replica 0's 4-device "model" mesh and
    replica 1's are identical in name and shape but their executables are
    pinned to disjoint devices."""
    return (tuple(str(n) for n in mesh.axis_names),
            tuple(int(s) for s in mesh.devices.shape),
            tuple(int(d.id) for d in mesh.devices.flat))


class ShardingSpec:
    """Serializable sharding statement: ``mesh_axes`` (ordered
    ``{name: size}``) plus per-input and optional per-param
    ``PartitionSpec``s (entries may be None == replicated; ``inputs`` /
    ``params`` may be None entirely == everything replicated)."""

    def __init__(self, mesh_axes: Dict[str, int],
                 inputs: Optional[Sequence] = None,
                 params: Optional[Sequence] = None):
        if not mesh_axes:
            raise ValueError("mesh_axes must name at least one axis")
        self.mesh_axes = {str(k): int(v) for k, v in mesh_axes.items()}
        self.inputs = self._norm(inputs)
        self.params = self._norm(params)

    @staticmethod
    def _norm(specs):
        from jax.sharding import PartitionSpec
        if specs is None:
            return None
        return [s if (s is None or isinstance(s, PartitionSpec))
                else lists_to_spec(s) for s in specs]

    def to_json_dict(self) -> dict:
        return {
            "format": SIDECAR_FORMAT,
            "mesh_axes": self.mesh_axes,
            "inputs": (None if self.inputs is None
                       else [spec_to_lists(s) for s in self.inputs]),
            "params": (None if self.params is None
                       else [spec_to_lists(s) for s in self.params]),
        }

    @classmethod
    def from_json_dict(cls, doc: dict) -> "ShardingSpec":
        return cls(doc["mesh_axes"], doc.get("inputs"), doc.get("params"))

    def __repr__(self):
        return (f"ShardingSpec(mesh_axes={self.mesh_axes}, "
                f"inputs={self.inputs}, params={self.params})")


# -- sidecar IO ---------------------------------------------------------------

def sidecar_path(prefix: str) -> str:
    return prefix + SIDECAR_SUFFIX


def save_sidecar(prefix: str, spec: ShardingSpec):
    """Write the sharding sidecar next to the artifact (tmp+replace, same
    torn-write discipline as the checkpoint health stamp)."""
    final = sidecar_path(prefix)
    tmp = final + ".tmp"
    with open(tmp, "w") as f:
        json.dump(spec.to_json_dict(), f, indent=1)
    os.replace(tmp, final)


def load_sidecar(prefix: str) -> Optional[ShardingSpec]:
    """Read the sidecar if present; a malformed one warns and reads as
    absent (the loader then serves replicated)."""
    full = sidecar_path(prefix)
    if not os.path.exists(full):
        return None
    try:
        with open(full) as f:
            doc = json.load(f)
        return ShardingSpec.from_json_dict(doc)
    except (OSError, ValueError, KeyError, TypeError) as e:
        warnings.warn(
            f"sharding sidecar {full} is unreadable ({e!r}); "
            f"serving replicated")
        return None


# -- resolution ---------------------------------------------------------------

class ResolvedSharding:
    """A ShardingSpec bound to concrete devices: the Mesh, one
    ``NamedSharding`` per input and per param, and the hashable ``token``
    that joins the ExecutableCache key."""

    def __init__(self, mesh, input_shardings: Tuple, param_shardings: Tuple,
                 input_specs: Sequence, param_specs: Sequence):
        self.mesh = mesh
        self.input_shardings = tuple(input_shardings)
        self.param_shardings = tuple(param_shardings)
        self.token = ("sharded", mesh_token(mesh),
                      tuple(_spec_key(s) for s in input_specs),
                      tuple(_spec_key(s) for s in param_specs))

    def __repr__(self):
        return (f"ResolvedSharding(mesh={dict(self.mesh.shape)}, "
                f"inputs={len(self.input_shardings)}, "
                f"params={len(self.param_shardings)})")


def build_submesh(mesh_axes: Dict[str, int],
                  devices: Optional[Sequence] = None):
    """Mesh over the first ``prod(sizes)`` of ``devices`` (default: all
    visible). Returns None (with a warning) when too few devices exist —
    the warn-and-fallback half of the sidecar contract."""
    import jax
    from jax.sharding import Mesh
    devs = list(devices) if devices is not None else list(jax.devices())
    names = tuple(mesh_axes.keys())
    sizes = tuple(int(s) for s in mesh_axes.values())
    total = int(np.prod(sizes))
    if total > len(devs):
        warnings.warn(
            f"sharding spec wants a {dict(mesh_axes)} mesh "
            f"({total} devices) but only {len(devs)} devices are "
            f"available; falling back to replicated execution")
        return None
    return Mesh(np.array(devs[:total]).reshape(sizes), names)


def resolve(spec: ShardingSpec, *, mesh=None,
            devices: Optional[Sequence] = None,
            n_inputs: Optional[int] = None,
            n_params: Optional[int] = None) -> Optional[ResolvedSharding]:
    """Bind ``spec`` to devices. Every mismatch warns and returns None so
    the caller falls back to the replicated single-device path."""
    from jax.sharding import NamedSharding, PartitionSpec
    if mesh is None:
        mesh = build_submesh(spec.mesh_axes, devices)
        if mesh is None:
            return None
    mesh_names = set(str(n) for n in mesh.axis_names)

    def _bind(specs, count, what):
        if specs is not None and count is not None \
                and len(specs) != count:
            warnings.warn(
                f"sharding spec names {len(specs)} {what} PartitionSpecs "
                f"but the artifact has {count} {what}s; falling back to "
                f"replicated execution")
            return None
        n = count if count is not None else len(specs or ())
        bound = []
        for i in range(n):
            s = specs[i] if specs is not None and i < len(specs) else None
            if s is None:
                s = PartitionSpec()
            unknown = [a for a in _spec_axes(s) if a not in mesh_names]
            if unknown:
                warnings.warn(
                    f"{what} PartitionSpec {s} references mesh axes "
                    f"{unknown} absent from mesh {dict(mesh.shape)}; "
                    f"falling back to replicated execution")
                return None
            bound.append(s)
        return bound

    in_specs = _bind(spec.inputs, n_inputs, "input")
    if in_specs is None:
        return None
    p_specs = _bind(spec.params, n_params, "param")
    if p_specs is None:
        return None
    return ResolvedSharding(
        mesh,
        tuple(NamedSharding(mesh, s) for s in in_specs),
        tuple(NamedSharding(mesh, s) for s in p_specs),
        in_specs, p_specs)
