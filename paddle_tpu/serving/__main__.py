"""``python -m paddle_tpu.serving serve --model /path/prefix`` — stand up
the dynamic-batching HTTP inference server over a jit.save artifact.

SIGTERM/SIGINT begin a graceful drain (chained with any PreemptionGuard):
admission stops, queued requests finish, /healthz flips to 503, process
exits cleanly.
"""
from __future__ import annotations

import argparse
import sys


def _parse_int_list(raw: str):
    return [int(x) for x in raw.split(",") if x.strip()]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m paddle_tpu.serving")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sv = sub.add_parser("serve", help="serve a jit.save artifact over HTTP")
    sv.add_argument("--model", required=True,
                    help="artifact path prefix (the X of X.pdmodel)")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8500,
                    help="0 binds an ephemeral port (printed on stdout as "
                         "PADDLE_TPU_SERVING_PORT=<port>)")
    sv.add_argument("--replicas", type=int, default=1,
                    help=">1 serves through a health-aware replica router")
    sv.add_argument("--model-parallel", type=int, default=1,
                    help="devices per replica ('model' mesh axis size; "
                         "GSPMD-partitioned predictor)")
    sv.add_argument("--buckets", default="",
                    help="comma-separated batch buckets (default: powers "
                         "of two up to --max-batch)")
    sv.add_argument("--seq-buckets", default="",
                    help="optional comma-separated sequence buckets "
                         "(requires a padding-masked model)")
    sv.add_argument("--max-batch", type=int, default=64)
    sv.add_argument("--max-queue", type=int, default=256)
    sv.add_argument("--max-delay-ms", type=float, default=5.0,
                    help="batcher coalescing window")
    sv.add_argument("--deadline-s", type=float, default=None,
                    help="default per-request deadline")
    sv.add_argument("--oversize", choices=("split", "reject"),
                    default="split")

    lv = sub.add_parser(
        "serve-llm",
        help="serve GPT generation (continuous batching) over HTTP")
    lv.add_argument("--state-dict", default=None,
                    help="framework_io.save'd GPTForCausalLM state dict "
                         "(omit for a randomly initialized model — smoke "
                         "tests only)")
    lv.add_argument("--vocab-size", type=int, default=50304)
    lv.add_argument("--hidden-size", type=int, default=768)
    lv.add_argument("--num-layers", type=int, default=12)
    lv.add_argument("--num-heads", type=int, default=12)
    lv.add_argument("--max-positions", type=int, default=1024)
    lv.add_argument("--host", default="127.0.0.1")
    lv.add_argument("--port", type=int, default=8500,
                    help="0 binds an ephemeral port (printed on stdout as "
                         "PADDLE_TPU_SERVING_PORT=<port>)")
    lv.add_argument("--replicas", type=int, default=1,
                    help=">1 serves through a health-aware replica router")
    lv.add_argument("--model-parallel", type=int, default=1,
                    help="devices per replica ('model' mesh axis size; "
                         "KV slots sharded over it)")
    lv.add_argument("--num-slots", type=int, default=8)
    lv.add_argument("--max-seq", type=int, default=512)
    lv.add_argument("--prefill-buckets", default="",
                    help="comma-separated prompt buckets (default: powers "
                         "of two up to --max-seq)")
    lv.add_argument("--max-queue", type=int, default=256)
    lv.add_argument("--deadline-s", type=float, default=None,
                    help="default per-request deadline")
    lv.add_argument("--max-new-tokens", type=int, default=64,
                    help="default generation budget per request")
    lv.add_argument("--no-warmup", action="store_true",
                    help="skip the ahead-of-time decode/prefill compiles")
    lv.add_argument("--prefix-cache", action="store_true",
                    help="enable cross-request prefix KV reuse (repeated "
                         "prompt prefixes skip their share of prefill)")
    lv.add_argument("--prefix-capacity-mb", type=float, default=256.0,
                    help="host-RAM byte budget for the prefix KV store")
    lv.add_argument("--spec-k", type=int, default=0,
                    help="draft tokens proposed per speculative decode "
                         "tick (0 disables speculative decoding)")
    lv.add_argument("--spec-draft-scale", type=int, default=4,
                    help="draft model shrink factor vs the target "
                         "(GPTConfig.draft); used when --spec-k > 0")
    lv.add_argument("--draft-state-dict", default=None,
                    help="framework_io.save'd state dict for the draft "
                         "model (omit for random draft weights — "
                         "acceptance will be ~0; smoke tests only)")
    lv.add_argument("--roles", default="",
                    help="comma-separated per-replica roles "
                         "(prefill|decode|mixed), one per --replicas: "
                         "disaggregated prefill/decode fleet with KV "
                         "handoff through a shared prefix store")
    lv.add_argument("--prefill-threshold", type=int, default=64,
                    help="prompts with at least this many tokens are "
                         "routed as prefill-phase")
    lv.add_argument("--no-handoff", action="store_true",
                    help="disable the prefill->decode KV handoff (role "
                         "routing only)")
    lv.add_argument("--autoscale", action="store_true",
                    help="run the SLO-aware autoscaler over the replica "
                         "set (requires --replicas > 1): replicas park "
                         "when calm and unpark on SLO breach")
    lv.add_argument("--slo-p95-ms", type=float, default=500.0,
                    help="autoscaler SLO: p95 request latency bound")
    lv.add_argument("--slo-max-queue", type=int, default=32,
                    help="autoscaler SLO: total queued-request bound")
    lv.add_argument("--min-replicas", type=int, default=1,
                    help="autoscaler floor; --replicas is the ceiling")
    lv.add_argument("--autoscale-interval-s", type=float, default=0.5,
                    help="autoscaler controller tick period")
    args = ap.parse_args(argv)

    if args.cmd == "serve-llm":
        return _serve_llm(args)

    from . import Engine, EngineConfig
    from .http import serve_forever

    cfg = EngineConfig(
        batch_buckets=_parse_int_list(args.buckets),
        seq_buckets=_parse_int_list(args.seq_buckets) or None,
        max_batch=args.max_batch,
        max_queue=args.max_queue,
        max_batch_delay=args.max_delay_ms / 1000.0,
        default_deadline=args.deadline_s,
        oversize_policy=args.oversize,
    )

    def _ready(httpd):
        host, port = httpd.server_address[:2]
        print(f"paddle_tpu.serving: listening on http://{host}:{port} "
              f"(buckets={list(cfg.buckets.batch_buckets)}, "
              f"delay={cfg.max_batch_delay * 1000:.1f}ms)", flush=True)
        # machine-readable line for --port 0 callers (supervisors, tests)
        print(f"PADDLE_TPU_SERVING_PORT={port}", flush=True)

    if args.replicas > 1 or args.model_parallel > 1:
        from .router import Router, RouterConfig, predictor_replica_factory
        axes = ({"model": args.model_parallel}
                if args.model_parallel > 1 else None)
        router = Router(
            predictor_replica_factory(args.model, cfg),
            RouterConfig(num_replicas=args.replicas, model_axes=axes,
                         kind="classifier"))
        router.install_drain_signal_handler()
        serve_forever(None, args.host, args.port, quiet=False,
                      ready_cb=_ready, router=router)
        router.drain()
        print("paddle_tpu.serving: drained, bye", flush=True)
        return 0

    engine = Engine(args.model, cfg)
    engine.install_drain_signal_handler()

    serve_forever(engine, args.host, args.port, quiet=False, ready_cb=_ready)
    engine.drain()
    print("paddle_tpu.serving: drained, bye", flush=True)
    return 0


def _serve_llm(args) -> int:
    from ..models.gpt import GPTConfig, GPTForCausalLM
    from .http import serve_forever
    from .llm import LLMEngine, LLMEngineConfig

    gcfg = GPTConfig(
        vocab_size=args.vocab_size, hidden_size=args.hidden_size,
        num_layers=args.num_layers, num_heads=args.num_heads,
        max_position_embeddings=args.max_positions,
        hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    model = GPTForCausalLM(gcfg)
    model.eval()
    if args.state_dict:
        from .. import framework_io
        model.set_state_dict(framework_io.load(args.state_dict))
    else:
        print("paddle_tpu.serving: WARNING serving a randomly initialized "
              "model (--state-dict not given)", flush=True)

    draft = None
    if args.spec_k > 0:
        draft = GPTForCausalLM(gcfg.draft(args.spec_draft_scale))
        draft.eval()
        if args.draft_state_dict:
            from .. import framework_io
            draft.set_state_dict(framework_io.load(args.draft_state_dict))
        else:
            print("paddle_tpu.serving: WARNING speculative draft model is "
                  "randomly initialized (--draft-state-dict not given); "
                  "acceptance will be ~0", flush=True)

    cfg = LLMEngineConfig(
        num_slots=args.num_slots, max_seq=args.max_seq,
        prefill_buckets=_parse_int_list(args.prefill_buckets) or None,
        max_queue=args.max_queue, default_deadline=args.deadline_s,
        default_max_new_tokens=args.max_new_tokens,
        warmup=not args.no_warmup,
        prefix_cache=args.prefix_cache,
        prefix_capacity_mb=args.prefix_capacity_mb,
        spec_k=args.spec_k)

    def _ready(httpd):
        host, port = httpd.server_address[:2]
        print(f"paddle_tpu.serving: LLM listening on http://{host}:{port} "
              f"(slots={cfg.num_slots}, max_seq={cfg.max_seq}, "
              f"prefill_buckets={list(cfg.prefill_buckets)})", flush=True)
        # machine-readable line for --port 0 callers (supervisors, tests)
        print(f"PADDLE_TPU_SERVING_PORT={port}", flush=True)

    roles = [r.strip() for r in args.roles.split(",") if r.strip()] or None
    if args.replicas > 1 or args.model_parallel > 1 or roles:
        from .router import Router, RouterConfig, llm_replica_factory
        axes = ({"model": args.model_parallel}
                if args.model_parallel > 1 else None)
        shared_store = None
        if args.prefix_cache or roles:
            # ONE store across replicas: prefix hits survive replica
            # hops, and it is the prefill->decode KV handoff channel
            from .llm import PrefixStore
            shared_store = PrefixStore(
                capacity_bytes=int(args.prefix_capacity_mb * (1 << 20)),
                block_tokens=cfg.prefix_block)
        router = Router(
            llm_replica_factory(
                lambda replica: model, cfg, roles=roles,
                prefix_store=shared_store,
                draft_model_factory=(
                    (lambda replica: draft) if draft is not None else None)),
            RouterConfig(num_replicas=args.replicas, model_axes=axes,
                         kind="llm", roles=roles,
                         prefill_threshold=args.prefill_threshold,
                         handoff=not args.no_handoff))
        router.install_drain_signal_handler()
        scaler = None
        if args.autoscale:
            from .fleet import SLO, Autoscaler, AutoscalerConfig
            scaler = Autoscaler(
                router,
                SLO(p95_ms=args.slo_p95_ms, max_queue=args.slo_max_queue,
                    min_replicas=args.min_replicas,
                    max_replicas=args.replicas),
                AutoscalerConfig(interval_s=args.autoscale_interval_s))
            scaler.start()
            print(f"paddle_tpu.serving: autoscaler on "
                  f"({args.min_replicas}..{args.replicas} replicas, "
                  f"p95<={args.slo_p95_ms}ms, "
                  f"queue<={args.slo_max_queue})", flush=True)
        serve_forever(None, args.host, args.port, quiet=False,
                      ready_cb=_ready, router=router)
        if scaler is not None:
            scaler.stop()
        router.drain()
        print("paddle_tpu.serving: drained, bye", flush=True)
        return 0

    engine = LLMEngine(model, cfg, draft_model=draft)
    engine.install_drain_signal_handler()

    serve_forever(None, args.host, args.port, quiet=False, ready_cb=_ready,
                  llm_engine=engine)
    engine.drain()
    print("paddle_tpu.serving: drained, bye", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
