"""Request objects and error taxonomy for the serving engine.

A request is a list of numpy input arrays whose leading axis is the row
(batch) dimension; the engine owns a ``concurrent.futures.Future`` per
request and resolves it with the list of output arrays (or an exception).
Deadlines reuse the :class:`~paddle_tpu.utils.resilience.Deadline`
substrate so the whole stack shares one wall-clock-budget idiom.
"""
from __future__ import annotations

import itertools
import time
from concurrent.futures import Future
from typing import List, Optional, Sequence

import numpy as np

from ..utils.resilience import Deadline, DeadlineExceeded  # noqa: F401

_REQ_IDS = itertools.count(1)

#: Request-phase / replica-role taxonomy for the disaggregated LLM fleet
#: (docs/serving.md "Disaggregated fleet"). A request is *prefill-phase*
#: when its dominant cost is the prompt prefill (long prompt), otherwise
#: *decode-phase*; a replica's role says which phases it serves ("mixed"
#: serves both — the non-disaggregated default).
PHASE_PREFILL = "prefill"
PHASE_DECODE = "decode"
REPLICA_ROLES = (PHASE_PREFILL, PHASE_DECODE, "mixed")


class ServingError(RuntimeError):
    """Base class for serving-side rejections."""


class QueueFull(ServingError):
    """Admission control rejected the request: the queue is at capacity and
    the configured backpressure wait elapsed."""


class EngineDraining(ServingError):
    """The engine is draining (preemption or explicit drain); no new
    requests are admitted."""


class RequestTooLarge(ServingError):
    """Request rows exceed the largest batch bucket and the engine is
    configured to reject (rather than split) oversized requests."""


class EngineKilled(ServingError):
    """The engine was hard-killed (the in-process analog of a replica
    SIGKILL). Queued requests fail with this error — retryable, they
    never produced partial output. In-flight generations on an engine
    with recovery enabled are NOT failed: they are evacuated and
    replayed onto surviving replicas (docs/fault_tolerance.md
    "Zero-loss serving"); only when no survivor can adopt a sequence
    does it fall back to this retryable failure."""


class TokenStreamDivergence(ServingError):
    """A resumed token stream disagreed with what the client already
    received. Raised by the :class:`~paddle_tpu.serving.llm.scheduler.
    GenerationRequest` resume-dedup guard when a migrated or replayed
    sequence would emit a duplicate, a gap, or a different token at an
    already-streamed position — the stream fails loudly instead of ever
    corrupting client-visible output. Retryable (a fresh submission
    regenerates from scratch); expected for sampled (non-greedy)
    streams recovered via replay, whose RNG path cannot be replayed
    bit-exactly across replicas."""


class InferenceRequest:
    """One queued inference call: inputs + deadline + result future."""

    __slots__ = ("req_id", "inputs", "nrows", "deadline", "future",
                 "t_enqueue")

    def __init__(self, inputs: Sequence[np.ndarray],
                 deadline: Optional[Deadline] = None,
                 clock=time.monotonic):
        if not inputs:
            raise ValueError("request needs at least one input array")
        arrays = [np.asarray(a) for a in inputs]
        rows = {a.shape[0] for a in arrays if a.ndim > 0}
        if len(rows) != 1:
            raise ValueError(
                f"all inputs must share the leading (row) dimension; "
                f"got shapes {[a.shape for a in arrays]}")
        self.req_id = next(_REQ_IDS)
        self.inputs: List[np.ndarray] = arrays
        self.nrows = arrays[0].shape[0]
        self.deadline = deadline
        self.future: Future = Future()
        self.t_enqueue = clock()

    @property
    def expired(self) -> bool:
        return self.deadline is not None and self.deadline.expired()

    def seq_len(self) -> Optional[int]:
        """Length of axis 1 of the first input, when it has one (the
        sequence dimension for token models)."""
        a = self.inputs[0]
        return int(a.shape[1]) if a.ndim >= 2 else None

    def fail(self, exc: BaseException) -> bool:
        """Resolve the future with ``exc`` (idempotent)."""
        if self.future.done():
            return False
        self.future.set_exception(exc)
        return True

    def fail_expired(self) -> bool:
        return self.fail(DeadlineExceeded(
            f"request {self.req_id} ({self.nrows} rows) exceeded its "
            f"{self.deadline.seconds}s deadline before dispatch"))
