"""Health-aware replica router: N engine workers over device subsets.

The serving-side complement of the elastic supervisor. Where the
supervisor respawns training *processes*, the :class:`Router` runs N
in-process :class:`~paddle_tpu.serving.replica.Replica` workers — each an
engine over its own slice of the device pool, optionally GSPMD-partitioned
over a per-replica sub-mesh — and keeps traffic flowing around the sick
ones:

* **dispatch** — least-outstanding-requests among admissible replicas
  (rotating tie-break), retrying on a racing drain; raises
  :class:`NoHealthyReplicas` only when every replica is out;
* **health sweep** — a daemon thread polls each replica's
  :meth:`~paddle_tpu.serving.replica.Replica.healthz` verdict, publishes
  per-replica labeled gauges, drains replicas that turn unhealthy, and
  resurrects DEAD ones through the shared
  :class:`~paddle_tpu.distributed.elastic.RestartBudget` (exponential
  backoff, same curve the supervisor uses) — each resurrection boots from
  the newest health-stamped checkpoint;
* **graceful drain** — SIGTERM (via the chained-handler substrate) or
  :meth:`drain` fans ``begin_drain`` out to every replica and waits for
  all engine workers to stop; in-flight futures all resolve.

Device math: with ``model_axes={"model": 4}`` and 8 visible devices,
``num_replicas=2`` gives each replica a 4-device sub-mesh — the 2×4
replica-by-model layout. Without ``model_axes`` the pool is split evenly
and replicas run single-device (mesh None).

**Disaggregated prefill/decode fleet** (``kind="llm"`` with ``roles``):
prompt prefill is a throughput-bound batch matmul while decode is a
latency-bound single-token step; co-locating them makes every long-prompt
admission stall the decode ticks of every other sequence on that replica.
With ``roles=("prefill", "decode", ...)`` the router classifies each
request by phase (prompt length >= ``prefill_threshold`` → prefill-phase)
and dispatches it only to replicas whose role serves that phase ("mixed"
serves both). When ``handoff`` is on and the replicas share ONE
:class:`~paddle_tpu.serving.llm.PrefixStore` (see
:func:`llm_replica_factory`'s ``prefix_store``), a prefill-phase request
is first run as a 1-token warmup on a prefill-role replica — its
admission exports the prompt's block-aligned K/V into the shared store —
and the real request is then dispatched decode-phase: the decode
replica's admission finds the prefix cached and prefills only the short
tail, so its resident decode batch barely notices the long prompt.
"""
from __future__ import annotations

import itertools
import threading
import time
import warnings
from typing import Callable, Dict, List, Optional, Sequence

from ..core import monitor as _mon
from ..distributed.elastic import ChainedSignalHandler, RestartBudget
from ..observability import flight as _flight
from .replica import DEAD, DRAINING, HEALTHY, Replica
from .request import (
    PHASE_DECODE, PHASE_PREFILL, REPLICA_ROLES, EngineDraining, ServingError)


class NoHealthyReplicas(ServingError):
    """Every replica is draining, dead, or marked unhealthy — the request
    cannot be placed anywhere."""


class RouterConfig:
    """Tunables for the replica router (see docs/serving.md)."""

    def __init__(self,
                 num_replicas: int = 2,
                 model_axes: Optional[Dict[str, int]] = None,
                 kind: str = "classifier",
                 health_interval: float = 0.2,
                 unhealthy_queue_depth: Optional[int] = None,
                 max_restarts: int = 3,
                 restart_backoff: float = 1.0,
                 restart_backoff_cap: float = 30.0,
                 auto_resurrect: bool = True,
                 checkpoint_root: Optional[str] = None,
                 stat_prefix: str = "serving.router",
                 roles: Optional[Sequence[str]] = None,
                 prefill_threshold: int = 64,
                 handoff: bool = True,
                 handoff_timeout: float = 30.0,
                 recovery: Optional[bool] = None):
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, got {num_replicas}")
        if kind not in ("classifier", "llm"):
            raise ValueError(
                f"kind must be 'classifier' or 'llm', got {kind!r}")
        if roles is not None:
            roles = tuple(str(r) for r in roles)
            if kind != "llm":
                raise ValueError("roles= is only meaningful for kind='llm'")
            if len(roles) != num_replicas:
                raise ValueError(
                    f"roles must name one role per replica: got "
                    f"{len(roles)} roles for {num_replicas} replicas")
            bad = [r for r in roles if r not in REPLICA_ROLES]
            if bad:
                raise ValueError(
                    f"invalid roles {bad}; each must be one of "
                    f"{REPLICA_ROLES}")
            # a fleet that cannot serve one of the phases would reject
            # every request of that phase at dispatch — fail at config time
            for phase in (PHASE_PREFILL, PHASE_DECODE):
                if not any(r in (phase, "mixed") for r in roles):
                    raise ValueError(
                        f"roles {roles} leave no replica serving the "
                        f"{phase} phase (need at least one {phase!r} or "
                        f"'mixed')")
        if prefill_threshold < 1:
            raise ValueError(
                f"prefill_threshold must be >= 1, got {prefill_threshold}")
        self.num_replicas = int(num_replicas)
        self.model_axes = dict(model_axes) if model_axes else None
        self.kind = kind
        self.health_interval = float(health_interval)
        self.unhealthy_queue_depth = unhealthy_queue_depth
        self.max_restarts = int(max_restarts)
        self.restart_backoff = float(restart_backoff)
        self.restart_backoff_cap = float(restart_backoff_cap)
        self.auto_resurrect = bool(auto_resurrect)
        self.checkpoint_root = checkpoint_root
        self.stat_prefix = stat_prefix
        self.roles = roles
        self.prefill_threshold = int(prefill_threshold)
        self.handoff = bool(handoff)
        self.handoff_timeout = float(handoff_timeout)
        # zero-loss serving (docs/fault_tolerance.md): arm the sequence
        # journal on every LLM engine boot and replay journaled sequences
        # onto survivors after a kill. Default: on for LLM fleets (the
        # only kind with sequences to lose), off for classifiers.
        self.recovery = (kind == "llm") if recovery is None else \
            bool(recovery)


class Router:
    """Dispatch facade over N health-tracked replicas.

    ``engine_factory(replica) -> engine`` builds each replica's engine
    (see :func:`predictor_replica_factory` / :func:`llm_replica_factory`);
    it reads ``replica.mesh``, ``replica.registry`` and
    ``replica.boot_checkpoint``.
    """

    def __init__(self, engine_factory: Callable[[Replica], object],
                 config: Optional[RouterConfig] = None,
                 registry: Optional[_mon.StatRegistry] = None,
                 devices: Optional[Sequence] = None,
                 health_source: Optional[Callable[[int], bool]] = None):
        self._config = config or RouterConfig()
        self._registry = registry or _mon.default_registry()
        self._prefix = self._config.stat_prefix
        self.budget = RestartBudget(self._config.max_restarts,
                                    self._config.restart_backoff,
                                    cap=self._config.restart_backoff_cap)
        self._draining = threading.Event()
        self._stopped = threading.Event()
        self._signal_chain: Optional[ChainedSignalHandler] = None
        self._drain_signaled = False   # set (only) from _on_drain_signal
        self._rr = itertools.count()   # rotating tie-break for dispatch
        self._resume_at: Dict[int, float] = {}  # health-thread-only
        self._fanned_out = False                # health-thread-only
        self._degraded_last = 0                 # health-thread-only
        self._parked: set = set()          # autoscaler-parked replica ids
        self._parked_lock = threading.Lock()
        self._trace_recorder = None        # replay.TraceRecorder hook
        # zero-loss serving: LLM fleets get a FleetMigrator (sequence
        # export/import for park + hot-swap) and, when recovery is on, a
        # per-replica kill callback that replays journaled sequences onto
        # survivors. Lazy import: fleet.migrate is control plane and the
        # classifier path must not pay for it.
        self.migrator = None
        recovery_cb = None
        if self._config.kind == "llm":
            from .fleet.migrate import FleetMigrator
            self.migrator = FleetMigrator(self, registry=self._registry)
            if self._config.recovery:
                recovery_cb = self._on_replica_killed
        self.replicas: List[Replica] = []
        for rid, sub in enumerate(self._split_devices(devices)):
            mesh = None
            if self._config.model_axes:
                from ..distributed.mesh import build_mesh
                mesh = build_mesh(dict(self._config.model_axes), devices=sub)
            src = (None if health_source is None
                   else (lambda r=rid: health_source(r)))
            self.replicas.append(Replica(
                rid, engine_factory, devices=sub, mesh=mesh,
                checkpoint_root=self._config.checkpoint_root,
                restart_budget=self.budget,
                unhealthy_queue_depth=self._config.unhealthy_queue_depth,
                health_source=src, registry=self._registry,
                recovery_cb=recovery_cb))
        self._health_thread = threading.Thread(
            target=self._health_loop, name="paddle-tpu-router-health",
            daemon=True)
        self._health_thread.start()

    def _split_devices(self, devices) -> List[Optional[List]]:
        """Contiguous per-replica device subsets. With ``model_axes`` each
        replica gets exactly ``prod(sizes)`` devices (fail fast when the
        pool is too small — a silently replicated "model-parallel" router
        would void the capacity math); without, the pool is split evenly
        (replicas may run single-device on the same default device when
        the pool has fewer devices than replicas)."""
        import jax
        n = self._config.num_replicas
        devs = list(devices) if devices is not None else list(jax.devices())
        if self._config.model_axes:
            per = 1
            for s in self._config.model_axes.values():
                per *= int(s)
            need = per * n
            if need > len(devs):
                raise ValueError(
                    f"router needs {n} x {dict(self._config.model_axes)} "
                    f"= {need} devices but only {len(devs)} are visible")
            return [devs[i * per:(i + 1) * per] for i in range(n)]
        if len(devs) >= n:
            per = len(devs) // n
            return [devs[i * per:(i + 1) * per] for i in range(n)]
        return [None] * n

    # -- dispatch ------------------------------------------------------------
    @property
    def config(self) -> RouterConfig:
        return self._config

    @property
    def kind(self) -> str:
        return self._config.kind

    @property
    def registry(self) -> _mon.StatRegistry:
        return self._registry

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def _role_of(self, replica_id: int) -> str:
        roles = self._config.roles
        return roles[replica_id] if roles is not None else "mixed"

    def _phase_of(self, args, kwargs) -> Optional[str]:
        """Classify an LLM request by phase: a prompt of
        ``prefill_threshold`` or more tokens is prefill-dominated. Returns
        None (no phase routing) for classifier routers, role-less configs,
        or calls whose prompt cannot be measured."""
        if self._config.kind != "llm" or self._config.roles is None:
            return None
        prompt = args[0] if args else kwargs.get("prompt")
        try:
            n = len(prompt)
        except TypeError:
            return None
        return (PHASE_PREFILL if n >= self._config.prefill_threshold
                else PHASE_DECODE)

    def _pick(self, tried, phase: Optional[str] = None) -> Optional[Replica]:
        cands = [r for r in self.replicas
                 if r.replica_id not in tried and r.admissible
                 and (phase is None
                      or self._role_of(r.replica_id) in (phase, "mixed"))]
        if not cands:
            return None
        low = min(r.outstanding for r in cands)
        mins = [r for r in cands if r.outstanding == low]
        return mins[next(self._rr) % len(mins)]

    def submit(self, *args, **kwargs):
        """Place one request on the least-loaded admissible replica whose
        role serves the request's phase (every replica, for phase-less
        routers). Returns whatever that replica's engine returns (a Future
        for classifier engines, a GenerationRequest for LLM engines).
        Retries on a replica that starts draining between pick and submit;
        raises :class:`NoHealthyReplicas` when no replica can take it.

        Prefill-phase requests go through the KV handoff when it is
        enabled and the fleet shares a prefix store (see the module
        docstring); otherwise they dispatch directly to a
        prefill-serving replica."""
        if self._draining.is_set():
            self._registry.add(f"{self._prefix}.rejected_draining", 1)
            raise EngineDraining("router is draining; submit rejected")
        phase = self._phase_of(args, kwargs)
        if phase == PHASE_PREFILL and self._config.handoff \
                and self._handoff_ready():
            out = self._handoff_submit(args, kwargs)
        else:
            out = self._dispatch(phase, args, kwargs)
        rec = self._trace_recorder
        if rec is not None:
            # record only ACCEPTED requests (rejections raised above) —
            # replay fidelity is about the traffic the fleet admitted
            try:
                rec.on_request(args, kwargs, phase)
            except Exception:
                # a broken recorder must never fail live traffic: count
                # it where /metricsz shows it and keep dispatching
                self._registry.add(
                    f"{self._prefix}.trace_recorder_errors", 1)
        return out

    def set_trace_recorder(self, recorder) -> None:
        """Install a :class:`~paddle_tpu.serving.fleet.replay
        .TraceRecorder` observing every accepted request (None removes
        it). The hook runs on the submitter's thread after dispatch."""
        self._trace_recorder = recorder

    def _dispatch(self, phase, args, kwargs):
        tried: set = set()
        relaxed = phase is None
        while True:
            r = self._pick(tried, None if relaxed else phase)
            if r is None:
                if not relaxed:
                    # every phase-matched replica is out — availability
                    # beats placement: serve from any admissible replica
                    relaxed = True
                    self._registry.add(f"{self._prefix}.phase_fallback", 1)
                    continue
                self._registry.add(f"{self._prefix}.rejected_no_replica", 1)
                raise NoHealthyReplicas(
                    f"no admissible replica among {len(self.replicas)} "
                    f"(states: {[x.state for x in self.replicas]})")
            try:
                out = r.submit(*args, **kwargs)
            except EngineDraining:
                # lost the race with a drain — route around it
                tried.add(r.replica_id)
                continue
            self._registry.add(f"{self._prefix}.dispatched", 1)
            if self._config.roles is not None:
                self._registry.add(
                    f"{self._prefix}.dispatched_role_"
                    f"{self._role_of(r.replica_id)}", 1)
                if phase is not None:
                    self._registry.add(
                        f"{self._prefix}.dispatched_phase_{phase}", 1)
            return out

    # -- fleet control plane (autoscaler) ------------------------------------
    def _on_replica_killed(self, replica: Replica) -> None:
        """Replica kill callback (crash recovery): replay the victim's
        journaled sequences onto survivors. Runs on its own daemon
        thread — the callback fires from inside :meth:`Replica.kill`,
        which may hold locks the recovery path (survivor queue puts,
        worker control calls) must not wait behind."""
        t = threading.Thread(
            target=lambda: self.migrator.recover_replica(replica),
            name=f"paddle-tpu-recover-{replica.replica_id}", daemon=True)
        t.start()

    def parked_ids(self) -> List[int]:
        """Replica ids intentionally out of service (autoscale-down)."""
        with self._parked_lock:
            return sorted(self._parked)

    def park(self, replica_id: int) -> bool:
        """Scale-down: take ``replica_id`` out of service and exclude it
        from health-loop resurrection until :meth:`unpark`. False when it
        is already parked. Parking is intentional capacity removal — it
        does not count as degradation and costs no restart budget.

        When the fleet supports live migration, parking does not wait
        for in-flight sequences to finish: admission is paused, every
        running sequence is exported onto the least-loaded siblings
        (paged KV pages travel with it; clients keep streaming), and
        the now-empty replica drains instantly. Sequences that could
        not be moved (report ``remaining`` > 0) finish under the old
        drain-and-wait behavior — migration never drops work."""
        r = self.replicas[replica_id]
        with self._parked_lock:
            if replica_id in self._parked:
                return False
            self._parked.add(replica_id)
        migrated = None
        if self.migrator is not None and \
                getattr(r.engine, "supports_migration", False):
            r.pause()   # stop admission while sequences leave
            migrated = self.migrator.migrate_replica(r, reason="park")
        r.begin_drain()
        self._registry.add(f"{self._prefix}.park_downs", 1)
        _flight.record_event("replica_park", {
            "replica": replica_id,
            "migrated": 0 if migrated is None else migrated["exported"]})
        return True

    def unpark(self, replica_id: int, *, boot_timeout: float = 5.0) -> bool:
        """Scale-up: return a parked replica to service through the
        budgeted boot path — one restart is claimed from the shared
        :class:`RestartBudget`, so a scale-up is a counted resurrection.
        Returns True when the replica booted here; False when it was not
        parked, its park-drain outlasted ``boot_timeout`` (the health loop
        finishes the boot at a later sweep), or the boot failed/budget is
        spent. Parked replicas are idle, so the drain wait normally
        resolves in one worker poll interval; callers run on the
        controller thread, never the dispatch path."""
        r = self.replicas[replica_id]
        with self._parked_lock:
            if replica_id not in self._parked:
                return False
            self._parked.discard(replica_id)
        self._registry.add(f"{self._prefix}.unpark_ups", 1)
        booted = False
        if r.drain(boot_timeout):   # flips DRAINING -> DEAD; idle => fast
            booted = r.resurrect(consume_budget=True)
            if booted:
                self._registry.add(f"{self._prefix}.resurrections", 1)
        _flight.record_event("replica_unpark",
                             {"replica": replica_id, "booted": booted})
        return booted

    def fleet_snapshot(self) -> dict:
        """The autoscaler's one-call control-plane view: per-replica state
        + load + latency, fleet aggregates, and restart-budget headroom.
        Pure host-side registry/accounting reads — never touches device
        values, so polling it adds zero host syncs to the hot path."""
        parked = set(self.parked_ids())
        reps = []
        for r in self.replicas:
            admissible = r.admissible
            reps.append({
                "replica": r.replica_id,
                "state": r.state,
                "parked": r.replica_id in parked,
                "paused": r.paused,
                "admissible": admissible,
                "outstanding": r.outstanding,
                "queue_depth": r.queue_depth(),
                "p95_ms": self._replica_p95(r),
                "completed": self._replica_completed(r),
                "slots_in_use": self._replica_slots_in_use(r),
            })
        active = [x for x in reps if x["admissible"]]
        stats = self._registry.stats_with_prefix(self._prefix + ".")
        return {
            "replicas": reps,
            "active_replicas": len(active),
            "parked": sorted(parked),
            "queue_depth": sum(x["queue_depth"] for x in reps),
            "outstanding": sum(x["outstanding"] for x in reps),
            # the fleet p95 is the WORST active replica: SLO breaches are
            # per-request, and requests land on one replica
            "p95_ms": max((x["p95_ms"] for x in active), default=0.0),
            # all-time completion count: the autoscaler diffs this per
            # tick so a stale latency reservoir (no traffic since the
            # spike) cannot hold a breach open forever
            "completed": sum(x["completed"] for x in reps),
            "rejected_no_replica": stats.get(
                f"{self._prefix}.rejected_no_replica", 0),
            "degraded": stats.get(f"{self._prefix}.degraded", 0),
            "budget_remaining": self.budget.remaining,
            "draining": self.draining,
        }

    def _replica_p95(self, r: Replica) -> float:
        """p95 request latency of one replica's engine from its histogram
        (0.0 before any traffic)."""
        engine = r.engine
        if engine is None:
            return 0.0
        ep = getattr(engine, "_prefix", None)
        reg = getattr(engine, "registry", None)
        if not ep or reg is None:
            return 0.0
        name = (f"{ep}.request_latency_ms" if self._config.kind == "llm"
                else f"{ep}.latency_ms")
        return reg.quantile(name, 0.95)

    def _replica_completed(self, r: Replica) -> int:
        """All-time completed-request count of one replica's engine (the
        latency histogram's observation count)."""
        engine = r.engine
        if engine is None:
            return 0
        ep = getattr(engine, "_prefix", None)
        reg = getattr(engine, "registry", None)
        if not ep or reg is None:
            return 0
        name = (f"{ep}.request_latency_ms" if self._config.kind == "llm"
                else f"{ep}.latency_ms")
        return int(reg.histogram(name).get("count", 0))

    def _replica_slots_in_use(self, r: Replica) -> int:
        batcher = getattr(r.engine, "_batcher", None)
        active = getattr(batcher, "active", 0)
        return int(active) if isinstance(active, int) else 0

    # -- prefill/decode KV handoff -------------------------------------------
    def _handoff_ready(self) -> bool:
        """The handoff pays off only when a dedicated prefill replica and
        a decode-serving replica share ONE PrefixStore object — otherwise
        the prefilled K/V is invisible to the decode replica and the
        warmup is pure waste."""
        roles = self._config.roles
        if roles is None or PHASE_PREFILL not in roles:
            return False
        stores = {}
        for r in self.replicas:
            store = getattr(r.engine, "prefix_store", None)
            if store is not None:
                stores[r.replica_id] = store
        for rid, store in stores.items():
            if roles[rid] != PHASE_PREFILL:
                continue
            for rid2, store2 in stores.items():
                if rid2 != rid and roles[rid2] in (PHASE_DECODE, "mixed") \
                        and store2 is store:
                    return True
        return False

    def _handoff_submit(self, args, kwargs):
        """KV handoff for a prefill-phase request: run a 1-token warmup
        generation on a prefill-role replica — its admission exports the
        prompt's block-aligned K/V into the SHARED prefix store — then
        dispatch the real request decode-phase, where admission finds the
        prefix cached and prefills only the tail. A failed or timed-out
        warmup degrades gracefully: the decode replica prefills the whole
        prompt itself (slower, never wrong)."""
        prompt = args[0] if args else kwargs.get("prompt")
        pre_kwargs = dict(kwargs)
        pre_kwargs.pop("prompt", None)
        pre_kwargs.update(max_new_tokens=1, stream=False, do_sample=False)
        try:
            pre = self._dispatch(PHASE_PREFILL, (prompt,), pre_kwargs)
            pre.result(timeout=self._config.handoff_timeout)
            self._registry.add(f"{self._prefix}.handoff_prefills", 1)
        except Exception as e:
            self._registry.add(f"{self._prefix}.handoff_failed", 1)
            warnings.warn(
                f"router: prefill handoff failed ({type(e).__name__}: "
                f"{e}); the decode replica will prefill locally")
        return self._dispatch(PHASE_DECODE, args, kwargs)

    # -- health loop ---------------------------------------------------------
    def _health_loop(self):
        try:
            while True:
                if self._draining.is_set():
                    if not self._fanned_out:
                        for r in self.replicas:
                            r.begin_drain()
                        self._fanned_out = True
                    if all(r.poll_drained() for r in self.replicas):
                        break
                else:
                    self._sweep()
                time.sleep(self._config.health_interval)
        finally:
            self._stopped.set()

    def _sweep(self):
        now = time.monotonic()
        parked = set(self.parked_ids())
        for r in self.replicas:
            h = r.healthz()
            rid = r.replica_id
            labels = {"replica": str(rid)}
            self._registry.set_labeled(
                f"{self._prefix}.replica_healthy", labels,
                1 if h["healthy"] else 0)
            self._registry.set_labeled(
                f"{self._prefix}.replica_outstanding", labels,
                h["outstanding"])
            self._registry.set_labeled(
                f"{self._prefix}.replica_queue_depth", labels,
                h["queue_depth"])
            self._registry.set_labeled(
                f"{self._prefix}.replica_restarts", labels, h["restarts"])
            self._registry.set_labeled(
                f"{self._prefix}.replica_parked", labels,
                1 if rid in parked else 0)
            self._registry.set_labeled(
                f"{self._prefix}.replica_p95_ms", labels,
                self._replica_p95(r))
            self._registry.set_labeled(
                f"{self._prefix}.replica_slots_in_use", labels,
                self._replica_slots_in_use(r))
            if self._config.roles is not None:
                # assignment gauge: constant 1 per (replica, role) pair so
                # dashboards can join per-replica series onto roles
                self._registry.set_labeled(
                    f"{self._prefix}.replica_role",
                    {"replica": str(rid), "role": self._role_of(rid)}, 1)
            state = h["state"]
            if state == HEALTHY and not h["healthy"]:
                warnings.warn(
                    f"router: draining replica {rid} "
                    f"(reasons: {h['reasons']})")
                r.begin_drain()
                self._registry.add(
                    f"{self._prefix}.drained_unhealthy", 1)
            elif state == DRAINING:
                r.poll_drained()
            elif state == DEAD and self._config.auto_resurrect:
                if rid in parked:
                    # parked is intentional: no resurrection, and any
                    # pending backoff schedule is void (unpark reboots)
                    self._resume_at.pop(rid, None)
                else:
                    self._maybe_resurrect(r, now)
        # degraded = replicas lost for good (budget exhausted, not parked):
        # the fleet is serving below its declared capacity
        degraded = sum(
            1 for x in self.replicas
            if x.replica_id not in parked
            and self._resume_at.get(x.replica_id) == float("inf"))
        self._registry.set(f"{self._prefix}.degraded", degraded)
        if degraded != self._degraded_last:
            _flight.record_event(
                "router_degraded_change",
                {"degraded": degraded, "was": self._degraded_last,
                 "budget_remaining": self.budget.remaining})
            self._degraded_last = degraded
        self._registry.set(
            f"{self._prefix}.active_replicas",
            sum(1 for x in self.replicas if x.admissible))
        self._registry.set(
            f"{self._prefix}.agg.queue_depth",
            sum(x.queue_depth() for x in self.replicas))

    def _maybe_resurrect(self, r: Replica, now: float):
        """Budgeted, backed-off resurrection (health-thread-only state).
        The budget is claimed HERE — scheduling the pause needs the
        post-consume count — so the replica is told not to claim again."""
        rid = r.replica_id
        due = self._resume_at.get(rid)
        if due is None:
            if self.budget.try_consume():
                self._resume_at[rid] = now + self.budget.pause()
            else:
                warnings.warn(
                    f"router: replica {rid} is DEAD and the restart "
                    f"budget ({self.budget.max_restarts}) is exhausted; "
                    f"it stays down")
                self._resume_at[rid] = float("inf")
            return
        if now < due:
            return
        if r.resurrect(consume_budget=False):
            del self._resume_at[rid]
            self._registry.add(f"{self._prefix}.resurrections", 1)
        else:
            # boot failed — claim another restart for the retry, or park
            if self.budget.try_consume():
                self._resume_at[rid] = now + self.budget.pause()
            else:
                self._resume_at[rid] = float("inf")

    # -- drain / signals -----------------------------------------------------
    def install_drain_signal_handler(self, signals=None):
        """Arm SIGTERM/SIGINT to begin a router-wide drain, chaining — not
        replacing — whatever handler was installed before."""
        if self._signal_chain is not None and self._signal_chain.installed:
            return self._signal_chain
        kwargs = {} if signals is None else {"signals": tuple(signals)}
        self._signal_chain = ChainedSignalHandler(
            self._on_drain_signal, **kwargs)
        self._signal_chain.install()
        return self._signal_chain

    def _on_drain_signal(self, signum, frame):
        """Flag-only (async-signal-safe): the health thread fans the drain
        out to the replicas at its next tick — replica/engine drains take
        queue locks the interrupted thread may hold."""
        self._drain_signaled = True
        self._draining.set()

    def begin_drain(self):
        """Stop admission; the health thread drains every replica."""
        self._draining.set()

    def drain(self, timeout: Optional[float] = None):
        """Graceful router-wide drain: stop admission, drain every
        replica, wait for all engine workers to stop."""
        self.begin_drain()
        self._stopped.wait(timeout)
        if self._signal_chain is not None:
            self._signal_chain.uninstall()

    close = drain

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.drain()
        return False

    # -- observability -------------------------------------------------------
    def healthz(self) -> dict:
        """Aggregate health: ``ok`` (all in-service replicas healthy) /
        ``degraded`` (capacity lost: an unhealthy replica, or one parked
        forever by an exhausted restart budget) / ``unhealthy`` (none
        admissible) / ``draining``. Parked (autoscaled-down) replicas are
        intentional capacity and do not count against the verdict."""
        reps = [r.healthz() for r in self.replicas]
        if self._config.roles is not None:
            for rid, h in enumerate(reps):
                h["role"] = self._role_of(rid)
        parked = set(self.parked_ids())
        for h in reps:
            h["parked"] = h["replica"] in parked
        in_service = [h for h in reps if not h["parked"]]
        stats = self._registry.stats_with_prefix(self._prefix + ".")
        budget_lost = stats.get(f"{self._prefix}.degraded", 0)
        if self._draining.is_set():
            status = "draining"
        elif in_service and all(h["healthy"] for h in in_service) \
                and not budget_lost:
            status = "ok"
        elif any(r.admissible for r in self.replicas):
            status = "degraded"
        else:
            status = "unhealthy"
        return {"status": status, "kind": self.kind, "replicas": reps,
                "parked": sorted(parked),
                "degraded_replicas": budget_lost,
                "budget_remaining": self.budget.remaining}

    def stats(self) -> dict:
        """Router counters + per-replica accounting + the balance factor
        (max dispatched / mean dispatched — 1.0 is a perfectly even
        spread)."""
        per = {str(r.replica_id): r.stats() for r in self.replicas}
        dispatched = [p["dispatched"] for p in per.values()]
        mean = sum(dispatched) / max(1, len(dispatched))
        balance = (max(dispatched) / mean) if mean > 0 else 1.0
        return {
            "stats": self._registry.stats_with_prefix(self._prefix + "."),
            "replicas": per,
            "num_replicas": len(self.replicas),
            "roles": (list(self._config.roles)
                      if self._config.roles is not None else None),
            "draining": self.draining,
            "total_dispatched": sum(dispatched),
            "balance_factor": balance,
        }

    def registries(self) -> List[_mon.StatRegistry]:
        """Every distinct StatRegistry behind this router (identity-
        deduped) — the /metricsz render set."""
        out = [self._registry]
        for r in self.replicas:
            engine = r.engine
            reg = getattr(engine, "registry", None)
            if reg is not None and all(reg is not x for x in out):
                out.append(reg)
        return out

    def __repr__(self):
        return (f"Router(kind={self.kind}, replicas={len(self.replicas)}, "
                f"draining={self.draining})")


# -- engine factories ---------------------------------------------------------

def predictor_replica_factory(model_prefix: str,
                              config=None) -> Callable[[Replica], object]:
    """Factory for classifier replicas: each builds a Predictor over the
    ``jit.save`` artifact at ``model_prefix`` (GSPMD-partitioned over the
    replica's sub-mesh when one exists — the artifact's sharding sidecar
    supplies the PartitionSpecs) wrapped in an
    :class:`~paddle_tpu.serving.engine.Engine` with a per-replica stat
    prefix."""
    import copy

    def factory(replica: Replica):
        from ..inference import Config as InferConfig, create_predictor
        from .engine import Engine, EngineConfig
        ic = InferConfig(model_prefix)
        if replica.mesh is not None:
            ic.enable_sharding(mesh=replica.mesh)
        pred = create_predictor(ic)
        cfg = copy.copy(config) if config is not None else EngineConfig()
        cfg.stat_prefix = f"{cfg.stat_prefix}.replica{replica.replica_id}"
        return Engine(pred, cfg, registry=replica.registry)
    return factory


def llm_replica_factory(model_factory: Callable[[Replica], object],
                        config=None, *,
                        roles: Optional[Sequence[str]] = None,
                        prefix_store=None,
                        draft_model_factory: Optional[
                            Callable[[Replica], object]] = None
                        ) -> Callable[[Replica], object]:
    """Factory for LLM replicas: ``model_factory(replica)`` builds (or
    restores — ``replica.boot_checkpoint`` names the newest health-stamped
    checkpoint) the GPT model; each replica gets an
    :class:`~paddle_tpu.serving.llm.LLMEngine` over its sub-mesh with a
    per-replica stat prefix (the trailing-dot namespace fix in
    ``LLMEngine.stats`` is what keeps two of these from sharing
    counters).

    Disaggregation hooks: ``roles`` stamps ``config.role`` per replica
    (pass the same sequence to :class:`RouterConfig` so routing and
    engine stats agree); ``prefix_store`` is the ONE shared
    :class:`~paddle_tpu.serving.llm.PrefixStore` every replica mounts —
    the prefill→decode KV handoff channel; ``draft_model_factory`` builds
    the speculative-decoding draft model for configs with ``spec_k > 0``.
    """
    import copy

    def factory(replica: Replica):
        from .llm import LLMEngine, LLMEngineConfig
        cfg = copy.copy(config) if config is not None else LLMEngineConfig()
        cfg.stat_prefix = f"{cfg.stat_prefix}.replica{replica.replica_id}"
        if roles is not None:
            cfg.role = roles[replica.replica_id]
        model = model_factory(replica)
        draft = (draft_model_factory(replica)
                 if draft_model_factory is not None else None)
        return LLMEngine(model, cfg, registry=replica.registry,
                         mesh=replica.mesh, draft_model=draft,
                         prefix_store=prefix_store)
    return factory
