"""Replica: one health-tracked serving worker over a device subset.

A :class:`Replica` wraps one engine (the classifier
:class:`~paddle_tpu.serving.engine.Engine` or the LLM
:class:`~paddle_tpu.serving.llm.LLMEngine`) plus the state the
:class:`~paddle_tpu.serving.router.Router` needs to route around it:

* a lifecycle state machine — STARTING → HEALTHY → DRAINING → DEAD, with
  DEAD → STARTING on :meth:`resurrect`;
* outstanding-request accounting (the router dispatches to the replica
  with the fewest requests in flight);
* a health verdict (:meth:`healthz`) combining the lifecycle state, the
  engine's drain flag, queue depth against a threshold, and an optional
  external ``health_source`` (typically the numerical-anomaly sentinel's
  ``healthy`` predicate);
* health-stamped boot — when ``checkpoint_root`` is given, each (re)start
  records the :func:`~paddle_tpu.incubate.checkpoint.sharded
  .newest_healthy_checkpoint` pick in :attr:`boot_checkpoint` *before*
  calling the engine factory, so the factory can restore exactly the
  state the sentinel vouched for.

The engine factory is ``factory(replica) -> engine``: it reads
``replica.mesh`` (the replica's device sub-mesh, for GSPMD partitioning)
and ``replica.boot_checkpoint`` and returns a started engine. Factories
for the two engine kinds live in :mod:`paddle_tpu.serving.router`.

Lock discipline: every mutable attribute (``_state``, ``_engine``,
``_outstanding``, ``_dispatched``, ``_completed``, ``_restarts``,
``_unhealthy_reason``, ``_boot_checkpoint``) is read and written under
``self._lock``; engine calls (submit/drain — they take the engine's own
locks) happen outside it.
"""
from __future__ import annotations

import errno
import os
import threading
import time
import warnings
from concurrent.futures import Future
from typing import Callable, Optional

from ..utils.resilience import fault_injector
from .request import EngineDraining

#: lifecycle states (plain strings so /healthz payloads serialize as-is)
STARTING = "STARTING"
HEALTHY = "HEALTHY"
DRAINING = "DRAINING"
DEAD = "DEAD"


class Replica:
    """One engine worker + the router-facing health/accounting shell."""

    def __init__(self, replica_id: int,
                 engine_factory: Callable[["Replica"], object], *,
                 devices=None, mesh=None,
                 checkpoint_root: Optional[str] = None,
                 restart_budget=None,
                 unhealthy_queue_depth: Optional[int] = None,
                 health_source: Optional[Callable[[], bool]] = None,
                 registry=None, clock=time.monotonic,
                 recovery_cb: Optional[Callable[["Replica"], None]] = None):
        self.replica_id = int(replica_id)
        #: StatRegistry the engine factory should hand its engine, so all
        #: replicas of one router publish into one scrape (per-replica
        #: stat prefixes keep their namespaces apart — see LLMEngine.stats)
        self.registry = registry
        self.devices = tuple(devices) if devices is not None else None
        self.mesh = mesh
        self.checkpoint_root = checkpoint_root
        self.restart_budget = restart_budget
        self.unhealthy_queue_depth = unhealthy_queue_depth
        self._health_source = health_source
        self._clock = clock
        self._factory = engine_factory
        self._lock = threading.Lock()
        self._state = STARTING
        self._engine = None
        self._outstanding = 0
        self._dispatched = 0
        self._completed = 0
        self._restarts = 0
        self._unhealthy_reason: Optional[str] = None
        self._boot_checkpoint: Optional[str] = None
        self._paused = False
        # zero-loss serving (docs/fault_tolerance.md): when set, every
        # engine this replica boots gets its crash-recovery journal
        # armed, and kill() invokes the callback so the router can
        # replay evacuated sequences onto survivors
        self._recovery_cb = recovery_cb
        #: snapshot records from the last kill() (id, phase, tokens) —
        #: what was in the engine at the moment it died
        self.last_kill_records: list = []
        self._boot()

    # -- boot / resurrect ----------------------------------------------------
    def _boot(self):
        """Pick the boot checkpoint, build the engine, go HEALTHY. Raises
        whatever the factory raises (first construction fails fast;
        :meth:`resurrect` catches)."""
        # chaos hook: `replica_boot` fires once per engine construction —
        # initial boot, resurrection, and autoscale-up all pass through
        # here, so one occurrence spec covers them all
        action = fault_injector().fire("replica_boot")
        if action == "fail":
            raise RuntimeError(
                f"fault injection: replica {self.replica_id} boot failed")
        if action == "disk_full":
            raise OSError(errno.ENOSPC,
                          f"fault injection: replica {self.replica_id} "
                          f"boot hit ENOSPC")
        if action == "slow_io":
            time.sleep(float(os.environ.get(
                "PADDLE_TPU_FAULT_SLOW_IO_S", "0.2")))
        ckpt = None
        if self.checkpoint_root is not None:
            from ..incubate.checkpoint.async_ckpt import cleanup_stale_staging
            from ..incubate.checkpoint.sharded import newest_healthy_checkpoint
            # a trainer killed mid-commit may have left *.tmp staging debris
            # next to the committed checkpoints; sweep it before the walk
            cleanup_stale_staging(self.checkpoint_root)
            ckpt = newest_healthy_checkpoint(self.checkpoint_root)
        with self._lock:
            self._boot_checkpoint = ckpt
            self._state = STARTING
        engine = self._factory(self)
        if self._recovery_cb is not None \
                and hasattr(engine, "enable_recovery"):
            # re-armed on EVERY boot: a resurrected engine instance is a
            # fresh object and must journal from its first tick
            engine.enable_recovery()
        with self._lock:
            self._engine = engine
            self._state = HEALTHY
            self._unhealthy_reason = None

    def resurrect(self, consume_budget: bool = True) -> bool:
        """Bring a DEAD replica back through a fresh health-stamped boot.

        With ``consume_budget`` (the default for direct callers), one
        restart is claimed from :attr:`restart_budget` first — False when
        the budget is spent. The router's health loop claims the budget
        itself (to schedule the backoff pause) and passes
        ``consume_budget=False``. A factory failure warns, leaves the
        replica DEAD, and returns False.
        """
        with self._lock:
            if self._state != DEAD:
                return False
        if consume_budget and self.restart_budget is not None \
                and not self.restart_budget.try_consume():
            return False
        try:
            self._boot()
        except Exception as e:
            warnings.warn(
                f"replica {self.replica_id} failed to resurrect: {e!r}")
            with self._lock:
                self._state = DEAD
            return False
        with self._lock:
            self._restarts += 1
        return True

    # -- dispatch ------------------------------------------------------------
    @property
    def engine(self):
        with self._lock:
            return self._engine

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def outstanding(self) -> int:
        with self._lock:
            return self._outstanding

    @property
    def admissible(self) -> bool:
        """May the router hand this replica a request right now?"""
        with self._lock:
            if self._state != HEALTHY or self._unhealthy_reason is not None \
                    or self._paused:
                return False
            engine = self._engine
        return engine is not None and not engine.draining

    @property
    def paused(self) -> bool:
        with self._lock:
            return self._paused

    def pause(self):
        """Fleet control: stop router dispatch to this replica WITHOUT
        marking it unhealthy (the health sweep must not drain it) and
        WITHOUT touching the engine — the weight-swap probe talks to the
        engine directly while the replica is paused."""
        with self._lock:
            self._paused = True

    def resume(self):
        with self._lock:
            self._paused = False

    def kill(self, reason: str = "killed") -> bool:
        """Hard-kill (the in-process SIGKILL analog): the replica goes
        DEAD immediately. Queued work fails retryably with
        :class:`~paddle_tpu.serving.request.EngineKilled`; in-flight
        work is aborted — or, when the recovery callback is wired,
        evacuated and handed to the router for replay onto survivors
        (docs/fault_tolerance.md "Zero-loss serving"). The router's
        health sweep sees DEAD and schedules a budgeted resurrection,
        exactly as for a drained-out replica."""
        with self._lock:
            if self._state == DEAD:
                return False
            self._state = DEAD
            engine = self._engine
        if engine is not None:
            self.last_kill_records = engine.kill(
                f"replica {self.replica_id}: {reason}")
            if self._recovery_cb is not None:
                try:
                    self._recovery_cb(self)
                except Exception as e:  # noqa: BLE001 -- recovery is best-effort; the kill verdict stands either way
                    warnings.warn(
                        f"replica {self.replica_id} recovery callback "
                        f"failed: {e!r}")
        return True

    @property
    def boot_checkpoint(self) -> Optional[str]:
        """The checkpoint the current engine instance booted from (None
        when no ``checkpoint_root`` was configured or nothing survived the
        newest-healthy walk)."""
        with self._lock:
            return self._boot_checkpoint

    def submit(self, *args, **kwargs):
        """Forward to the engine's ``submit``, with outstanding-request
        accounting. Returns whatever the engine returns (a Future for the
        classifier engine, a GenerationRequest for the LLM engine)."""
        with self._lock:
            if self._state != HEALTHY or self._unhealthy_reason is not None \
                    or self._paused:
                raise EngineDraining(
                    f"replica {self.replica_id} is "
                    + ("paused" if self._paused and self._state == HEALTHY
                       else self._state)
                    + (f" ({self._unhealthy_reason})"
                       if self._unhealthy_reason else ""))
            engine = self._engine
        out = engine.submit(*args, **kwargs)
        fut = out if isinstance(out, Future) else out.future
        with self._lock:
            self._outstanding += 1
            self._dispatched += 1
        fut.add_done_callback(self._on_done)
        return out

    def _on_done(self, _fut):
        with self._lock:
            self._outstanding = max(0, self._outstanding - 1)
            self._completed += 1

    # -- health --------------------------------------------------------------
    def mark_unhealthy(self, reason: str):
        """External verdict (sentinel divergence, operator action): stop
        admitting; the router's next sweep drains this replica."""
        with self._lock:
            self._unhealthy_reason = str(reason)

    def queue_depth(self) -> int:
        engine = self.engine
        if engine is None:
            return 0
        try:
            return len(engine._queue)
        except Exception:
            return 0

    def healthz(self) -> dict:
        """The per-replica health verdict: state + every reason it is not
        serving (empty ``reasons`` == healthy)."""
        with self._lock:
            state = self._state
            reason = self._unhealthy_reason
            engine = self._engine
            outstanding = self._outstanding
            restarts = self._restarts
            boot = self._boot_checkpoint
            paused = self._paused
        reasons = []
        if state != HEALTHY:
            reasons.append(f"state={state}")
        if reason is not None:
            reasons.append(f"marked_unhealthy: {reason}")
        if engine is not None and engine.draining and state == HEALTHY:
            reasons.append("engine_draining")
        depth = self.queue_depth()
        if self.unhealthy_queue_depth is not None \
                and depth > self.unhealthy_queue_depth:
            reasons.append(
                f"queue_depth {depth} > {self.unhealthy_queue_depth}")
        if self._health_source is not None:
            try:
                if not self._health_source():
                    reasons.append("health_source")
            except Exception as e:
                reasons.append(f"health_source_error: {e!r}")
        # NB: paused is deliberately NOT a reason — the health sweep drains
        # replicas whose healthz goes unhealthy, and a paused replica
        # (autoscale park / mid-swap) must stay bootable, not get drained
        return {
            "replica": self.replica_id,
            "state": state,
            "healthy": not reasons,
            "paused": paused,
            "reasons": reasons,
            "queue_depth": depth,
            "outstanding": outstanding,
            "restarts": restarts,
            "boot_checkpoint": boot,
        }

    # -- drain ---------------------------------------------------------------
    def begin_drain(self):
        """Stop admission and start the engine's graceful drain
        (non-blocking; :meth:`poll_drained` observes completion)."""
        with self._lock:
            if self._state in (DRAINING, DEAD):
                return
            self._state = DRAINING
            engine = self._engine
        if engine is not None:
            engine.begin_drain()

    def poll_drained(self) -> bool:
        """True once the engine worker has stopped; flips DRAINING → DEAD
        on first observation."""
        with self._lock:
            if self._state == DEAD:
                return True
            if self._state != DRAINING:
                return False
            engine = self._engine
        if engine is None or engine._stopped.is_set():
            with self._lock:
                self._state = DEAD
            return True
        return False

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Blocking drain: begin + wait for the engine worker to stop."""
        self.begin_drain()
        engine = self.engine
        if engine is not None:
            engine._stopped.wait(timeout)
        return self.poll_drained()

    def stats(self) -> dict:
        with self._lock:
            out = {
                "state": self._state,
                "paused": self._paused,
                "outstanding": self._outstanding,
                "dispatched": self._dispatched,
                "completed": self._completed,
                "restarts": self._restarts,
                "boot_checkpoint": self._boot_checkpoint,
            }
        out["queue_depth"] = self.queue_depth()
        return out

    def __repr__(self):
        return (f"Replica(id={self.replica_id}, state={self.state}, "
                f"outstanding={self.outstanding})")
