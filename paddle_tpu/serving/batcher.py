"""DynamicBatcher: coalesce queued requests into one bucketed batch.

Policy: take the head request, then keep taking compatible requests (same
sequence bucket, total rows still fit the largest batch bucket) for up to
``max_batch_delay`` seconds — the classic throughput/latency knob. The
resulting row count is rounded up to the smallest batch bucket, so the
dispatched shape always comes from the closed bucket set.

A head request larger than every bucket becomes an *oversize* batch of one
request; the engine either splits it into max-bucket chunks or rejects it
at submit time, per configuration.
"""
from __future__ import annotations

import time
from typing import List, Optional

from ..observability import tracer as _otrace
from .buckets import BucketSpec
from .queue import BatchQueue
from .request import InferenceRequest


class Batch:
    """One dispatchable unit: requests + the padded shape they will run at."""

    __slots__ = ("requests", "bucket_rows", "seq_bucket", "rows", "oversize")

    def __init__(self, requests: List[InferenceRequest],
                 bucket_rows: Optional[int], seq_bucket: Optional[int] = None,
                 oversize: bool = False):
        self.requests = requests
        self.rows = sum(r.nrows for r in requests)
        self.bucket_rows = bucket_rows
        self.seq_bucket = seq_bucket
        self.oversize = oversize

    @property
    def fill_ratio(self) -> float:
        if not self.bucket_rows:
            return 1.0
        return self.rows / float(self.bucket_rows)


class DynamicBatcher:
    """Pulls from a :class:`BatchQueue` and forms bucketed batches."""

    def __init__(self, queue: BatchQueue, buckets: BucketSpec,
                 max_batch_delay: float = 0.005, clock=time.monotonic):
        self._queue = queue
        self._buckets = buckets
        self._max_delay = max(0.0, float(max_batch_delay))
        self._clock = clock

    def next_batch(self, timeout: Optional[float] = None) -> Optional[Batch]:
        """Block up to ``timeout`` for a first request; then coalesce for at
        most ``max_batch_delay``. None on an empty-queue timeout flush."""
        first = self._queue.take(timeout=timeout)
        if first is None:
            return None
        # span covers the coalesce window only — the idle blocking take
        # above would otherwise fill the trace ring with empty polls
        with _otrace.span("serving/form_batch"):
            return self._coalesce(first)

    def _coalesce(self, first: InferenceRequest) -> Batch:
        spec = self._buckets
        if first.nrows > spec.max_batch:
            return Batch([first], bucket_rows=None,
                         seq_bucket=spec.seq_bucket_for(first.seq_len()),
                         oversize=True)

        seq_bucket = spec.seq_bucket_for(first.seq_len())
        requests = [first]
        rows = first.nrows
        t0 = self._clock()
        while rows < spec.max_batch:
            remaining = self._max_delay - (self._clock() - t0)
            if remaining <= 0:
                break
            budget = spec.max_batch - rows

            def _fits(r: InferenceRequest) -> bool:
                return (r.nrows <= budget
                        and spec.seq_bucket_for(r.seq_len()) == seq_bucket)

            nxt = self._queue.take(timeout=remaining, fits=_fits)
            if nxt is None:
                break
            requests.append(nxt)
            rows += nxt.nrows
        return Batch(requests, bucket_rows=spec.batch_bucket_for(rows),
                     seq_bucket=seq_bucket)
