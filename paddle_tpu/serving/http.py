"""Stdlib-only HTTP front-end over an :class:`Engine`.

Endpoints:
  * ``POST /predict`` — body ``{"inputs": [nested-list, ...],
    "dtypes": ["float32", ...] (optional), "deadline_s": float (optional)}``;
    responds ``{"outputs": [...], "shapes": [...], "req_ms": float}``.
  * ``GET /healthz`` — ``{"status": "ok"|"draining"}`` (503 while
    draining, so load balancers stop routing here during preemption).
  * ``GET /statsz`` — the engine's full stats payload: scalar counters,
    latency/fill histograms (p50/p95/p99), executable-cache hit/miss/evict.

Threading model: ``ThreadingHTTPServer`` handles each connection on its
own thread; handlers block on the request future, while the engine's
single worker thread does the batching — concurrent POSTs are exactly what
gives the batcher something to coalesce.
"""
from __future__ import annotations

import json
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from .request import DeadlineExceeded, ServingError


class ServingHTTPServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, addr, engine, quiet: bool = True):
        self.engine = engine
        self.quiet = quiet
        super().__init__(addr, _Handler)


class _Handler(BaseHTTPRequestHandler):
    # one engine per server process; found via self.server

    def log_message(self, fmt, *args):
        if not self.server.quiet:
            BaseHTTPRequestHandler.log_message(self, fmt, *args)

    def _send_json(self, code: int, payload: dict):
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        engine = self.server.engine
        if self.path == "/healthz":
            if engine.draining:
                self._send_json(503, {"status": "draining"})
            else:
                self._send_json(200, {"status": "ok"})
        elif self.path == "/statsz":
            self._send_json(200, engine.stats())
        else:
            self._send_json(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        if self.path != "/predict":
            self._send_json(404, {"error": f"no route {self.path}"})
            return
        engine = self.server.engine
        t0 = time.monotonic()
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n) or b"{}")
            raw_inputs = payload["inputs"]
            dtypes = payload.get("dtypes") or ["float32"] * len(raw_inputs)
            arrays = [np.asarray(a, dtype=np.dtype(d))
                      for a, d in zip(raw_inputs, dtypes)]
            fut = engine.submit(arrays, deadline=payload.get("deadline_s"))
            outs = fut.result(timeout=payload.get("timeout_s", 60.0))
        except (KeyError, ValueError, TypeError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        except DeadlineExceeded as e:
            self._send_json(504, {"error": str(e)})
            return
        except ServingError as e:
            self._send_json(503, {"error": str(e)})
            return
        self._send_json(200, {
            "outputs": [o.tolist() for o in outs],
            "shapes": [list(o.shape) for o in outs],
            "req_ms": (time.monotonic() - t0) * 1000.0,
        })


def make_server(engine, host: str = "127.0.0.1", port: int = 8500,
                quiet: bool = True) -> ServingHTTPServer:
    """Bind (port 0 picks a free one; see ``server.server_address``)."""
    return ServingHTTPServer((host, port), engine, quiet=quiet)


def serve_forever(engine, host: str = "127.0.0.1", port: int = 8500,
                  quiet: bool = False,
                  ready_cb: Optional[callable] = None):
    """Blocking serve loop; shuts the listener down once a drain begins and
    the queue has flushed."""
    httpd = make_server(engine, host, port, quiet=quiet)
    if ready_cb is not None:
        ready_cb(httpd)
    import threading

    def _watch_drain():
        engine._stopped.wait()
        httpd.shutdown()

    threading.Thread(target=_watch_drain, daemon=True).start()
    try:
        httpd.serve_forever(poll_interval=0.2)
    finally:
        httpd.server_close()
