"""Stdlib-only HTTP front-end over an :class:`Engine` and/or
:class:`~paddle_tpu.serving.llm.LLMEngine`.

Endpoints:
  * ``POST /predict`` — body ``{"inputs": [nested-list, ...],
    "dtypes": ["float32", ...] (optional), "deadline_s": float (optional)}``;
    responds ``{"outputs": [...], "shapes": [...], "req_ms": float}``.
  * ``POST /generate`` — body ``{"prompt": [token ids],
    "max_new_tokens": int, "do_sample": bool, "temperature": float,
    "top_k": int, "eos_token_id": int, "deadline_s": float,
    "stream": bool}``. Non-streaming responds ``{"tokens": [...],
    "finish_reason": "stop"|"length", "req_ms": float}``; with
    ``"stream": true`` the body is newline-delimited JSON — one
    ``{"token": t}`` line per generated token as the decode tick produces
    it, then a final ``{"done": true, "finish_reason": ...}`` line (the
    response is close-delimited, so readers consume until EOF).
  * ``GET /healthz`` — ``{"status": "ok"|"draining"}`` (503 while either
    engine drains, so load balancers stop routing here during preemption).
  * ``GET /statsz`` — the engine's full stats payload: scalar counters,
    latency/fill histograms (p50/p95/p99), executable-cache hit/miss/evict;
    with an LLM engine attached, its payload (slot occupancy, TTFT/TPOT,
    tokens/s) rides along under ``"llm"``.
  * ``GET /metricsz`` — the same registries in Prometheus text exposition
    (format 0.0.4) for standard scrapers; see docs/observability.md for a
    scrape-config example.

Threading model: ``ThreadingHTTPServer`` handles each connection on its
own thread; handlers block on the request future (or the token stream),
while each engine's single worker thread does the batching — concurrent
POSTs are exactly what gives the batchers something to coalesce.
"""
from __future__ import annotations

import json
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from .request import DeadlineExceeded, ServingError


class ServingHTTPServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, addr, engine, quiet: bool = True, llm_engine=None,
                 router=None):
        if engine is None and llm_engine is None and router is None:
            raise ValueError("need an engine, an llm_engine, or a router")
        self.engine = engine
        self.llm_engine = llm_engine
        # a Router routes /predict (kind="classifier") or /generate
        # (kind="llm") across its replicas; /healthz and /statsz expose
        # the aggregate + per-replica views
        self.router = router
        self.quiet = quiet
        super().__init__(addr, _Handler)


class _Handler(BaseHTTPRequestHandler):
    # engines per server process; found via self.server

    def log_message(self, fmt, *args):
        if not self.server.quiet:
            BaseHTTPRequestHandler.log_message(self, fmt, *args)

    def _send_json(self, code: int, payload: dict):
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        engine = self.server.engine
        llm = self.server.llm_engine
        router = self.server.router
        if self.path == "/healthz":
            draining = any(e.draining for e in (engine, llm, router)
                           if e is not None)
            if router is not None:
                agg = router.healthz()
                # degraded still serves (some replica is admissible);
                # draining/unhealthy means stop routing here
                code = 200 if agg["status"] in ("ok", "degraded") else 503
                if draining:
                    agg["status"] = "draining"
                    code = 503
                self._send_json(code, agg)
            elif draining:
                self._send_json(503, {"status": "draining"})
            else:
                payload = {"status": "ok"}
                if llm is not None:
                    # disaggregated fleets route by this (prefill/decode/
                    # mixed); load balancers can match phase to role
                    payload["role"] = llm.role
                self._send_json(200, payload)
        elif self.path == "/statsz":
            payload = engine.stats() if engine is not None else {}
            if llm is not None:
                payload["llm"] = llm.stats()
            if router is not None:
                payload["router"] = router.stats()
            self._send_json(200, payload)
        elif self.path == "/metricsz":
            self._do_metricsz(engine, llm, router)
        else:
            self._send_json(404, {"error": f"no route {self.path}"})

    def _do_metricsz(self, engine, llm, router=None):
        """Prometheus text exposition of every mounted engine's registry.
        Engines usually share the default registry (one render); distinct
        registries concatenate safely because their stat namespaces
        (``serving.`` vs ``serving.llm.``) sanitize to disjoint families.
        A router contributes its own registry plus every replica engine's
        (identity-deduped — per-replica series carry ``replica`` labels)."""
        from ..observability.metrics import CONTENT_TYPE, render_prometheus
        regs = []
        for e in (engine, llm):
            if e is not None and all(e.registry is not r for r in regs):
                regs.append(e.registry)
        if router is not None:
            for reg in router.registries():
                if all(reg is not r for r in regs):
                    regs.append(reg)
        body = "".join(render_prometheus(r) for r in regs).encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_payload(self) -> dict:
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n) or b"{}")

    def do_POST(self):
        if self.path == "/predict":
            self._do_predict()
        elif self.path == "/generate":
            self._do_generate()
        else:
            self._send_json(404, {"error": f"no route {self.path}"})

    def _do_predict(self):
        engine = self.server.engine
        router = self.server.router
        if engine is None and router is not None \
                and router.kind == "classifier":
            engine = router   # Router.submit has the Engine.submit shape
        if engine is None:
            self._send_json(503, {"error": "no classifier engine mounted"})
            return
        t0 = time.monotonic()
        try:
            payload = self._read_payload()
            raw_inputs = payload["inputs"]
            dtypes = payload.get("dtypes") or ["float32"] * len(raw_inputs)
            arrays = [np.asarray(a, dtype=np.dtype(d))
                      for a, d in zip(raw_inputs, dtypes)]
            fut = engine.submit(arrays, deadline=payload.get("deadline_s"))
            outs = fut.result(timeout=payload.get("timeout_s", 60.0))
        except (KeyError, ValueError, TypeError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        except DeadlineExceeded as e:
            self._send_json(504, {"error": str(e)})
            return
        except ServingError as e:
            self._send_json(503, {"error": str(e)})
            return
        self._send_json(200, {
            "outputs": [o.tolist() for o in outs],
            "shapes": [list(o.shape) for o in outs],
            "req_ms": (time.monotonic() - t0) * 1000.0,
        })

    def _do_generate(self):
        llm = self.server.llm_engine
        router = self.server.router
        if llm is None and router is not None and router.kind == "llm":
            llm = router      # Router.submit forwards LLMEngine.submit kwargs
        if llm is None:
            self._send_json(503, {"error": "no LLM engine mounted"})
            return
        t0 = time.monotonic()
        try:
            payload = self._read_payload()
            stream = bool(payload.get("stream", False))
            req = llm.submit(
                payload["prompt"],
                max_new_tokens=payload.get("max_new_tokens"),
                do_sample=bool(payload.get("do_sample", False)),
                temperature=float(payload.get("temperature", 1.0)),
                top_k=int(payload.get("top_k", 0)),
                eos_token_id=payload.get("eos_token_id"),
                deadline=payload.get("deadline_s"),
                stream=stream)
        except (KeyError, ValueError, TypeError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        except ServingError as e:
            self._send_json(503, {"error": str(e)})
            return
        timeout = payload.get("timeout_s", 120.0)
        if not stream:
            try:
                out = req.result(timeout=timeout)
            except DeadlineExceeded as e:
                self._send_json(504, {"error": str(e)})
                return
            except ServingError as e:
                self._send_json(503, {"error": str(e)})
                return
            self._send_json(200, {
                "tokens": out["tokens"],
                "finish_reason": out["finish_reason"],
                "req_ms": (time.monotonic() - t0) * 1000.0,
            })
            return
        # streaming: NDJSON, close-delimited (no Content-Length)
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Connection", "close")
        self.end_headers()

        def _line(obj):
            self.wfile.write((json.dumps(obj) + "\n").encode("utf-8"))
            self.wfile.flush()

        try:
            for tok in req.iter_tokens(timeout=timeout):
                _line({"token": int(tok)})
            _line({"done": True, "finish_reason": req.finish_reason,
                   "req_ms": (time.monotonic() - t0) * 1000.0})
        except BaseException as e:  # mid-stream failure -> error line
            try:
                _line({"error": f"{type(e).__name__}: {e}"})
            except OSError:
                pass  # client went away; nothing left to tell it


def make_server(engine, host: str = "127.0.0.1", port: int = 8500,
                quiet: bool = True, llm_engine=None,
                router=None) -> ServingHTTPServer:
    """Bind (port 0 picks a free one; see ``server.server_address``)."""
    return ServingHTTPServer((host, port), engine, quiet=quiet,
                             llm_engine=llm_engine, router=router)


def serve_forever(engine, host: str = "127.0.0.1", port: int = 8500,
                  quiet: bool = False,
                  ready_cb: Optional[callable] = None, llm_engine=None,
                  router=None):
    """Blocking serve loop; shuts the listener down once every mounted
    engine's drain completes (queue flushed, in-flight sequences done)."""
    httpd = make_server(engine, host, port, quiet=quiet,
                        llm_engine=llm_engine, router=router)
    if ready_cb is not None:
        ready_cb(httpd)
    import threading

    def _watch_drain():
        for e in (engine, llm_engine, router):
            if e is not None:
                e._stopped.wait()
        httpd.shutdown()

    threading.Thread(target=_watch_drain, daemon=True).start()
    try:
        httpd.serve_forever(poll_interval=0.2)
    finally:
        httpd.server_close()
