"""Self-driving serving fleet: autoscaler + weight hot-swap + replay.

The control plane that closes the loop between the telemetry the stack
already publishes (StatRegistry gauges, health sweeps, flight events) and
the levers it already has (Router park/unpark, RestartBudget-counted
resurrection, engine admission pause, checkpoint health stamps):

* :class:`SLO` / :class:`Autoscaler` — a controller thread polling
  :meth:`Router.fleet_snapshot` against a declared SLO, scaling the
  replica set with hysteresis + cooldown (docs/serving.md, "Fleet
  operations");
* :class:`WeightSwapper` — rolls a committed, health-stamped checkpoint
  across replicas one at a time with migrate-out → quiesce → swap →
  probe → readmit, and automatic rollback on a failed probe;
* :mod:`migrate` — zero-loss serving: :class:`FleetMigrator` moves
  running sequences (paged KV pages included) between replicas for
  park/swap, and replays :class:`SequenceJournal`-tracked sequences
  onto survivors after a replica kill (docs/fault_tolerance.md,
  "Zero-loss serving");
* :mod:`replay` — record/synthesize request traces and replay them with
  arrival-time fidelity (the chaos-harness substrate of
  ``tools/bench_fleet.py``).

Everything here is host-side control plane: polling snapshots, flipping
admission flags, loading checkpoints. None of it runs on the request hot
path (PTA002 lints this package with hot-path strictness to keep it so).
"""
from .autoscaler import SLO, Autoscaler, AutoscalerConfig  # noqa: F401
from .migrate import (MANIFEST_VERSION, FleetMigrator,  # noqa: F401
                      SequenceJournal, SequenceManifest)
from .replay import (TraceRecorder, TraceReplayer,  # noqa: F401
                     load_trace, save_trace, synthesize_trace)
from .swap import SwapError, WeightSwapper  # noqa: F401
