"""Zero-loss serving: live sequence migration + in-flight recovery.

A replica leaving the fleet used to mean one of two bad deals: *drain*
(park and weight-swap wait for every running generation to finish — slow
under long streams) or *drop* (``kill`` fails every in-flight request
with ``EngineKilled`` and the client restarts from token zero). The
paged KV cache makes a third deal cheap: a sequence's entire decode
state is a block table plus refcounted pages, so it can be exported,
shipped, and spliced into a sibling replica the same way COW prefix
pages already are.

Three cooperating pieces (docs/fault_tolerance.md "Zero-loss serving"):

* :class:`SequenceManifest` — the versioned host-side snapshot of one
  live sequence: prompt, generated tokens, sampling params, weights
  version, and the K/V page payloads (``GPTPagedDecoder.
  export_sequence``). Everything except the page rows is host-derivable
  (the decode invariant ``lengths = prompt_len + len(tokens) - 1``
  pins the resume position), so export costs ONE device fetch.
* :class:`SequenceJournal` — the crash-recovery half: a bounded ring of
  payload-free per-tick records (request id, prompt hash, tokens-so-far,
  sampling), flushed OFF the engine worker thread per the LazyTensor
  async-dispatch discipline — journaling adds zero host syncs to the
  decode tick. Records may lag the live stream by a few tokens; the
  replay path closes the gap by re-generating it, and the
  ``GenerationRequest`` dedup guard verifies every re-generated token
  against what the client already saw.
* :class:`FleetMigrator` — the router-side orchestrator. *Planned*
  migration (autoscaler park, ``WeightSwapper.roll``) exports every
  running sequence between ticks and imports it into the least-loaded
  same-weights-version sibling, re-binding the SAME
  ``GenerationRequest`` so the client's token iterator never notices.
  *Crash* recovery replays journaled sequences onto survivors by
  re-prefilling ``prompt + journaled_tokens`` through the shared prefix
  store; greedy streams come out bitwise-identical to an uninterrupted
  run (the dedup guard raises :class:`~paddle_tpu.serving.request.
  TokenStreamDivergence` rather than ever emitting a duplicate or gap).

Fault sites (``PADDLE_TPU_FAULT_SPEC``): ``seq_export`` (donor-side,
``fail``/``slow_io``), ``seq_import`` (target-side, ``fail`` forces the
next-target/replay fallback), ``journal_write`` (flush thread,
``drop`` keeps records stale — the dedup guard's chaos diet).

Execution discipline: export and import run on each engine's worker
thread BETWEEN decode ticks (``LLMEngine._run_on_worker``), so a
migration never interleaves with a compiled step and never retraces the
audited ``llm_paged_decode_step`` program. Every fallback ends in a
*retryable* failure, so the fleet's zero-drop promise survives even a
migration that goes completely sideways.
"""
from __future__ import annotations

import collections
import hashlib
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...core import monitor as _mon
from ...observability import flight as _flight
from ...utils.resilience import fault_injector
from ..request import EngineKilled

#: bump when the manifest layout changes; import refuses newer versions
#: (a rolling fleet can hold two builds briefly — never guess at fields)
MANIFEST_VERSION = 1


def prompt_fingerprint(prompt) -> str:
    """Stable payload-free identity of a prompt (journal records and
    manifests carry this instead of trusting object identity)."""
    arr = np.asarray(prompt, dtype=np.int32).reshape(-1)  # noqa: PTA002 -- hashing the caller's host-side prompt (list/ndarray), not a device value
    return hashlib.sha1(arr.tobytes()).hexdigest()


class SequenceManifest:
    """One live sequence, snapshotted for shipping.

    ``k_pages``/``v_pages`` are the stacked host page payloads from
    ``PagedKVCache.read_pages`` (index ``i`` backs logical page ``i``),
    or ``None`` for a *cold* manifest — a request that was still queued
    on the donor and just needs re-queueing, no state to splice.
    ``n_cached_tokens`` is the resume position: the number of logical
    rows the payload backs (``prompt_len + len(tokens) - 1`` — the last
    emitted token is by design not yet in the cache; the importing
    engine's next tick writes it).
    """

    __slots__ = ("version", "req", "prompt", "tokens", "sampling",
                 "weights_version", "n_cached_tokens", "page_size",
                 "sig", "k_pages", "v_pages", "source", "prompt_hash")

    def __init__(self, req, prompt, tokens, sampling, weights_version,
                 n_cached_tokens, page_size, sig, k_pages=None,
                 v_pages=None, source=None):
        self.version = MANIFEST_VERSION
        self.req = req
        self.prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)  # noqa: PTA002 -- manifests carry host-side prompts (list/ndarray), never device values
        self.tokens = list(tokens)
        self.sampling = sampling
        self.weights_version = None if weights_version is None \
            else int(weights_version)
        self.n_cached_tokens = int(n_cached_tokens)
        self.page_size = int(page_size)
        self.sig = sig
        self.k_pages = k_pages
        self.v_pages = v_pages
        self.source = source
        self.prompt_hash = prompt_fingerprint(self.prompt)

    @classmethod
    def for_queued(cls, req, source=None) -> "SequenceManifest":
        """Manifest for a request still queued on the donor: no device
        state, no emitted tokens — a plain re-queue moves it."""
        return cls(req, req.prompt, req.tokens, req.sampling,
                   weights_version=req.weights_version,
                   n_cached_tokens=0, page_size=0, sig=None,
                   source=source)

    @property
    def cold(self) -> bool:
        """True when there is no device state to splice (the request
        never reached a slot on the donor — just re-queue it)."""
        return self.k_pages is None

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.size)

    def __repr__(self):
        return (f"SequenceManifest(v{self.version}, "
                f"req={getattr(self.req, 'req_id', None)}, "
                f"prompt={self.prompt_len}, tokens={len(self.tokens)}, "
                f"cached={self.n_cached_tokens}, "
                f"{'cold' if self.cold else 'warm'})")


class JournalRecord:
    """One journaled sequence: payload-free, a few hundred bytes."""

    __slots__ = ("req", "req_id", "prompt_hash", "tokens", "sampling",
                 "weights_version", "t_flushed")

    def __init__(self, req, tokens, t_flushed):
        self.req = req
        self.req_id = req.req_id
        self.prompt_hash = prompt_fingerprint(req.prompt)
        self.tokens = list(tokens)
        self.sampling = req.sampling
        self.weights_version = req.weights_version
        self.t_flushed = t_flushed


class SequenceJournal:
    """Bounded ring of per-tick sequence records, flushed off-thread.

    The engine worker calls :meth:`note` once per tick with the live
    request set — an O(1) reference enqueue, no copying, no host sync
    (the async-dispatch discipline: the tick never pays for
    durability). A daemon flush thread snapshots each request's
    ``tokens`` list into the ring. Because the flush lags the tick, a
    record may be a few tokens STALE at crash time; recovery replays
    the gap deterministically and the dedup guard verifies it — lag is
    a latency cost, never a correctness cost.
    """

    def __init__(self, capacity: int = 1024,
                 registry: Optional[_mon.StatRegistry] = None,
                 stat_prefix: str = "serving.llm.journal",
                 flush_interval: float = 0.01, clock=time.monotonic):
        if capacity < 1:
            raise ValueError(f"journal capacity must be >= 1: {capacity}")
        self.capacity = int(capacity)
        self._registry = registry if registry is not None \
            else _mon.default_registry()
        self._prefix = stat_prefix
        self._clock = clock
        self._lock = threading.Lock()
        # newest note wins; older pending snapshots are superseded, so a
        # slow flusher drops intermediate states, never the newest
        self._pending = collections.deque(maxlen=8)
        self._records: "collections.OrderedDict[int, JournalRecord]" = \
            collections.OrderedDict()
        self.write_errors = 0
        self.flushes = 0
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._flush_interval = float(flush_interval)
        self._thread = threading.Thread(
            target=self._flush_loop, name="paddle-tpu-seq-journal",
            daemon=True)
        self._thread.start()

    # -- hot path (engine worker) --------------------------------------------
    def note(self, reqs):
        """Record the live request set as of this tick. O(1): stores
        references only; the flush thread does the copying."""
        self._pending.append(tuple(reqs))
        self._wake.set()

    # -- flush thread ---------------------------------------------------------
    def _flush_loop(self):
        while not self._stop.is_set():
            self._wake.wait(timeout=self._flush_interval)
            self._wake.clear()
            self.flush_pending()

    def flush_pending(self):
        """Drain queued notes into the ring (flush-thread body; also
        callable directly in tests for deterministic journals)."""
        batch = None
        while self._pending:
            try:
                batch = self._pending.popleft()
            except IndexError:      # racing producer on an empty deque
                break
        if batch is None:
            return
        action = fault_injector().fire("journal_write")
        if action == "drop":
            # simulated lost write: the ring keeps its STALE records —
            # exactly the state a real crash leaves behind
            return
        if action == "slow_io":
            time.sleep(float(os.environ.get(
                "PADDLE_TPU_FAULT_SLOW_IO_S", "1.0")))
        if action in ("fail", "disk_full"):
            self.write_errors += 1
            self._registry.add(f"{self._prefix}.write_errors", 1)
            return
        now = self._clock()
        with self._lock:
            for req in batch:
                if req.finish_reason is not None:
                    self._records.pop(req.req_id, None)
                    continue
                # list() snapshots under the GIL; _emit only appends, so
                # the copy is always a consistent prefix of the stream
                rec = JournalRecord(req, list(req.tokens), now)
                self._records[rec.req_id] = rec
                self._records.move_to_end(rec.req_id)
            while len(self._records) > self.capacity:
                self._records.popitem(last=False)
            self.flushes += 1
            n = len(self._records)
        self._registry.set(f"{self._prefix}.entries", n)

    # -- recovery read side ----------------------------------------------------
    def snapshot(self) -> List[JournalRecord]:
        """The current ring, newest-note order — deliberately WITHOUT a
        synchronous flush: recovery sees exactly what a real crash
        would have persisted."""
        with self._lock:
            return [rec for rec in self._records.values()
                    if rec.req.finish_reason is None]

    def lookup(self, req_id: int) -> Optional[JournalRecord]:
        with self._lock:
            return self._records.get(req_id)

    def forget(self, req_id: int):
        with self._lock:
            self._records.pop(req_id, None)

    def __len__(self):
        with self._lock:
            return len(self._records)

    def close(self):
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5)


class FleetMigrator:
    """Router-side migration + recovery orchestrator.

    Stateless between calls: every decision reads the router's live
    snapshot. All counters land under ``fleet.migrate.*`` on the
    router registry (and therefore ``/metricsz``).
    """

    def __init__(self, router,
                 registry: Optional[_mon.StatRegistry] = None,
                 stat_prefix: str = "fleet.migrate",
                 export_timeout: float = 30.0,
                 import_timeout: float = 30.0, clock=time.monotonic):
        self.router = router
        self._registry = registry if registry is not None \
            else router.registry
        self._prefix = stat_prefix
        self._export_timeout = float(export_timeout)
        self._import_timeout = float(import_timeout)
        self._clock = clock

    def _add(self, name, v=1):
        self._registry.add(f"{self._prefix}.{name}", v)

    # -- target selection ------------------------------------------------------
    def _targets(self, exclude_id: int) -> List:
        """Admissible siblings able to receive sequences, least-loaded
        first. (Version preference is applied by the caller: splicing
        KV computed under other weights would silently mix models — the
        hot-swap tests pin 'old OR new, never mixed'.)"""
        out = [r for r in self.router.replicas
               if r.replica_id != exclude_id and r.engine is not None
               and r.admissible]
        out.sort(key=lambda r: (r.outstanding, r.replica_id))
        return out

    # -- planned migration -----------------------------------------------------
    def migrate_replica(self, replica, *, reason: str = "migrate") -> Dict:
        """Move every running sequence off ``replica`` onto siblings.

        The donor must already have admission paused (park and swap
        both do). Returns a report; ``remaining`` > 0 means some
        sequences could not be moved (the caller falls back to the old
        drain-and-wait behavior for those — never a drop)."""
        report = {"reason": reason, "exported": 0, "imported": 0,
                  "replayed": 0, "requeued": 0, "failed": 0,
                  "remaining": 0, "error": None}
        engine = replica.engine
        if engine is None or not getattr(engine, "supports_migration",
                                         False):
            report["error"] = "unsupported"
            return report
        t0 = self._clock()
        try:
            manifests = engine.export_sequences(
                timeout=self._export_timeout)
        except Exception as e:  # noqa: BLE001 -- any export failure must fall back to drain, not crash the control plane
            report["error"] = f"export: {e!r}"
            self._add("export_failures")
            return report
        report["exported"] = len(manifests)
        self._add("sequences_exported", len(manifests))
        for man in manifests:
            man.source = replica.replica_id
            outcome = self._place(man, exclude_id=replica.replica_id)
            report[outcome] += 1
        report["remaining"] = int(getattr(engine._batcher, "active", 0))
        self._registry.observe(f"{self._prefix}.latency_ms",
                               (self._clock() - t0) * 1000.0)
        _flight.record_event("sequence_migrate", {
            "replica": replica.replica_id, "reason": reason,
            **{k: report[k] for k in
               ("exported", "imported", "replayed", "requeued",
                "failed")}})
        return report

    def _place(self, man: SequenceManifest, *, exclude_id: int) -> str:
        """One manifest onto the fleet. Returns the outcome counter
        name: imported | replayed | requeued | failed."""
        targets = self._targets(exclude_id)
        if man.weights_version is not None:
            # keep the stream on its weights generation when possible;
            # a cross-version replay is legal as LAST resort (the dedup
            # guard fails the stream loudly if it diverges)
            targets.sort(key=lambda r: getattr(
                r.engine, "weights_version", None) != man.weights_version)
        if man.cold:
            # no device state: a plain re-queue (or, for a mid-replay
            # request shipped payload-free, a dedup-guarded replay)
            for target in targets:
                if self._try(lambda t=target: t.engine.resubmit(man.req)):
                    self._add("sequences_requeued")
                    return "requeued"
            return self._fail(man.req, "no sibling could re-queue")
        for target in targets:
            if getattr(target.engine, "weights_version",
                       None) != man.weights_version:
                break    # sorted: only cross-version targets remain
            if self._try(lambda t=target: t.engine.import_sequence(
                    man, timeout=self._import_timeout)):
                self._add("sequences_imported")
                return "imported"
            self._add("import_failures")
        # page splice impossible (pool pressure, version skew, injected
        # faults): replay-resume through the prefix store instead —
        # slower, still token-exact for greedy streams
        for target in targets:
            if self._try(lambda t=target: t.engine.resubmit_for_recovery(
                    man.req, man.tokens)):
                self._add("sequences_replayed")
                return "replayed"
        return self._fail(man.req, "no sibling could adopt or replay")

    @staticmethod
    def _try(fn) -> bool:
        try:
            return bool(fn())
        except Exception:  # noqa: BLE001 -- a sick target must not sink the whole migration; the next target gets its chance
            return False

    def _fail(self, req, why: str) -> str:
        self._add("sequences_failed")
        # retryable by contract: the client resubmits from scratch, so
        # even the worst-case fallback is a retry, never a loss
        req.fail(EngineKilled(
            f"sequence migration failed for request "
            f"{getattr(req, 'req_id', '?')}: {why}; retry"))
        return "failed"

    # -- crash recovery --------------------------------------------------------
    def recover_replica(self, replica, *, wait_timeout: float = 30.0,
                        reason: str = "engine killed") -> Dict:
        """Replay a killed replica's journaled sequences onto survivors.

        Called after ``Replica.kill`` (or the health sweep declaring an
        engine dead). Waits for the donor worker to stop — its last act
        is evacuating in-flight requests WITHOUT failing them — then,
        for each evacuated request, re-prefills ``prompt +
        journaled_tokens`` on a survivor. The journal may lag the
        stream; the re-generated gap is verified token-by-token by the
        request's dedup guard before anything reaches the client."""
        report = {"reason": reason, "evacuated": 0, "replayed": 0,
                  "failed": 0}
        engine = replica.engine
        if engine is None:
            return report
        stopped = getattr(engine, "_stopped", None)
        if stopped is not None and not stopped.wait(wait_timeout):
            # the worker never exited: evacuation cannot be trusted —
            # leave the requests to the engine's own abort path
            report["failed"] = -1
            return report
        victims = engine.take_evacuated() \
            if hasattr(engine, "take_evacuated") else []
        journal = getattr(engine, "journal", None)
        report["evacuated"] = len(victims)
        for req in victims:
            if req.finish_reason is not None or req.future.done():
                continue
            rec = journal.lookup(req.req_id) if journal is not None \
                else None
            resume = list(rec.tokens) if rec is not None else []
            placed = False
            for target in self._targets(replica.replica_id):
                if self._try(lambda: target.engine.resubmit_for_recovery(
                        req, resume)):
                    placed = True
                    break
            if placed:
                report["replayed"] += 1
                self._add("sequences_recovered")
            else:
                report["failed"] += 1
                self._fail(req, "no survivor could replay")
        _flight.record_event("sequence_recover", {
            "replica": replica.replica_id, **{
                k: report[k] for k in ("evacuated", "replayed",
                                       "failed")}})
        return report

    def stats(self) -> Dict:
        return self._registry.stats_with_prefix(self._prefix + ".")
