"""Traffic traces: record from a live Router, replay with fidelity.

A trace is a list of records ``{"t": <seconds since trace start>,
"prompt_len": n, "phase": "prefill"|"decode"|null, "max_new_tokens": k}``
— arrival time and shape, never payload (prompts are regenerated
deterministically at replay, so traces are shareable). On disk it is
JSONL, one record per line, ordered by ``t``.

:class:`TraceRecorder` hooks ``Router.set_trace_recorder`` and captures
every ACCEPTED request. :func:`synthesize_trace` builds a seeded Poisson
storm when no recorded trace exists. :class:`TraceReplayer` replays a
trace against a router with arrival-time fidelity — each record is
dispatched at ``t0 + record.t`` regardless of how long earlier requests
took — and client-side retries: a request that fails with a retryable
serving error (killed replica, draining race, no-replica window) is
re-submitted up to ``max_retries`` times, exactly like a production
client treating 503s. A record whose every attempt fails is a **drop**;
the chaos gate asserts drops == 0.
"""
from __future__ import annotations

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutTimeout
from typing import Dict, List, Optional

import numpy as np

from ..request import ServingError


# -- trace capture / synthesis / persistence ----------------------------------

class TraceRecorder:
    """Router hook capturing (arrival offset, prompt length, phase) for
    every accepted request. Thread-safe; install via
    ``router.set_trace_recorder(recorder)``."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._t0: Optional[float] = None
        self.records: List[Dict] = []

    def on_request(self, args, kwargs, phase):
        now = self._clock()
        prompt = args[0] if args else kwargs.get("prompt")
        try:
            n = len(prompt)
        except TypeError:
            n = 1
        mnt = kwargs.get("max_new_tokens")
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            self.records.append({
                "t": round(now - self._t0, 6),
                "prompt_len": int(n),
                "phase": phase,
                "max_new_tokens": int(mnt) if mnt is not None else None,
            })

    def __len__(self):
        with self._lock:
            return len(self.records)

    def trace(self) -> List[Dict]:
        with self._lock:
            return list(self.records)


def synthesize_trace(n_requests: int, rate_rps: float, *, seed: int = 0,
                     prompt_len_range=(4, 24),
                     max_new_tokens: int = 8) -> List[Dict]:
    """A deterministic Poisson request storm: exponential interarrivals
    at ``rate_rps``, prompt lengths uniform over ``prompt_len_range``.
    Same seed → same trace, so baselines are reproducible."""
    rng = np.random.default_rng(seed)
    lo, hi = prompt_len_range
    t = 0.0
    out = []
    for _ in range(int(n_requests)):
        t += float(rng.exponential(1.0 / float(rate_rps)))
        out.append({
            "t": round(t, 6),
            "prompt_len": int(rng.integers(lo, hi + 1)),
            "phase": None,
            "max_new_tokens": int(max_new_tokens),
        })
    return out


def save_trace(records: List[Dict], path: str):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def load_trace(path: str) -> List[Dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    out.sort(key=lambda r: r.get("t", 0.0))
    return out


# -- replay -------------------------------------------------------------------

class TraceReplayer:
    """Replay a trace against a Router with arrival-time fidelity.

    The driver thread sleeps to each record's absolute schedule
    (``t0 + record.t`` — queueing delay never skews later arrivals) and
    hands the record to a pool worker, which submits, waits for the
    result, and retries retryable failures. ``run()`` blocks until every
    record resolved and returns the replay report."""

    #: failures a production client would retry (the request never
    #: produced output): hard-killed engine, drain/pause races, the
    #: window where no replica is admissible, and LLM-worker death.
    RETRYABLE = (ServingError, RuntimeError, TimeoutError, _FutTimeout)

    def __init__(self, router, trace: List[Dict], *,
                 vocab: int = 64, max_retries: int = 25,
                 retry_delay: float = 0.05,
                 request_timeout: float = 60.0,
                 default_max_new_tokens: int = 8,
                 workers: int = 32, clock=time.monotonic):
        self.router = router
        self.trace = list(trace)
        self.vocab = int(vocab)
        self.max_retries = int(max_retries)
        self.retry_delay = float(retry_delay)
        self.request_timeout = float(request_timeout)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.workers = int(workers)
        self._clock = clock
        self._lock = threading.Lock()
        self._completed = 0
        self._dropped = 0
        self._retries = 0
        self._latency_ms: List[float] = []
        self._arrival_lag_ms: List[float] = []
        self._versions: Dict[int, int] = {}   # weights_version -> count

    def _prompt_for(self, idx: int, n: int) -> List[int]:
        # deterministic per-record prompt: replays are comparable without
        # shipping payloads in the trace
        return [1 + (idx * 7 + j * 3) % (self.vocab - 1)
                for j in range(max(1, n))]

    def run(self) -> dict:
        t_start = self._clock()
        with ThreadPoolExecutor(
                max_workers=self.workers,
                thread_name_prefix="fleet-replay") as pool:
            futs = []
            for idx, rec in enumerate(self.trace):
                due = t_start + float(rec.get("t", 0.0))
                delay = due - self._clock()
                if delay > 0:
                    time.sleep(delay)
                lag = max(0.0, (self._clock() - due) * 1000.0)
                with self._lock:
                    self._arrival_lag_ms.append(lag)
                futs.append(pool.submit(self._one, idx, rec, due))
            for f in futs:
                f.result()
        wall = self._clock() - t_start
        return self.report(wall)

    def _one(self, idx: int, rec: Dict, due: float):
        prompt = self._prompt_for(idx, int(rec.get("prompt_len", 1)))
        mnt = rec.get("max_new_tokens") or self.default_max_new_tokens
        attempts = 0
        while attempts <= self.max_retries:
            attempts += 1
            try:
                out = self.router.submit(prompt, max_new_tokens=mnt)
                res = out.result(timeout=self.request_timeout)
                break
            except self.RETRYABLE:
                with self._lock:
                    self._retries += 1
                time.sleep(self.retry_delay)
        else:
            with self._lock:
                self._dropped += 1
            return
        latency = (self._clock() - due) * 1000.0
        with self._lock:
            self._completed += 1
            self._latency_ms.append(latency)
            if isinstance(res, dict) and "weights_version" in res:
                v = res["weights_version"]
                self._versions[v] = self._versions.get(v, 0) + 1

    @staticmethod
    def _q(xs: List[float], q: float) -> float:
        if not xs:
            return 0.0
        ys = sorted(xs)
        pos = min(max(q, 0.0), 1.0) * (len(ys) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(ys) - 1)
        return ys[lo] + (ys[hi] - ys[lo]) * (pos - lo)

    def report(self, wall_s: float) -> dict:
        with self._lock:
            return {
                "offered": len(self.trace),
                "completed": self._completed,
                "dropped": self._dropped,
                "retries": self._retries,
                "wall_s": wall_s,
                "latency_p50_ms": self._q(self._latency_ms, 0.50),
                "latency_p95_ms": self._q(self._latency_ms, 0.95),
                # proof of arrival fidelity: how late the driver actually
                # dispatched each record vs its schedule
                "arrival_lag_p95_ms": self._q(self._arrival_lag_ms, 0.95),
                # weights_version histogram of completed requests: during
                # a mid-storm roll both versions appear, mixed never
                "weights_versions": dict(self._versions),
            }
