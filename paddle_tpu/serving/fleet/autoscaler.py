"""SLO-aware autoscaler: the fleet's capacity controller.

One controller thread polls :meth:`Router.fleet_snapshot` every
``interval_s`` and compares it against the declared :class:`SLO`. The
loop is deliberately boring — a hysteresis window on both sides of the
decision plus a cooldown after every action, because serving load is
bursty and a controller that reacts to single-tick spikes oscillates:

* **breach** (any of: fleet p95 above ``slo.p95_ms``, total queue depth
  above ``slo.max_queue``, or requests rejected with no admissible
  replica since the last tick) for ``breach_ticks`` consecutive ticks →
  scale UP by unparking the lowest-id parked replica. The unpark goes
  through the Router's budgeted boot path, so a scale-up is a counted
  resurrection on the same RestartBudget/backoff curve the health loop
  uses.
* **calm** (no breach) for ``calm_ticks`` consecutive ticks with more
  than ``slo.min_replicas`` active → scale DOWN by parking the
  least-loaded active replica. On fleets with live sequence migration
  the park is immediate — in-flight sequences move to siblings with
  their KV pages and keep streaming (docs/fault_tolerance.md,
  "Zero-loss serving"); otherwise the park is a graceful drain and
  in-flight work finishes in place.

The controller never creates or destroys replicas — the Router owns
``max_replicas`` shells for its whole life and the autoscaler only moves
them between parked and serving. All reads are host-side registry and
accounting snapshots; the hot path never blocks on the controller.
"""
from __future__ import annotations

import threading
import time
import warnings
from typing import Optional

from ...core import monitor as _mon
from ...observability import flight as _flight
from ...observability import tracer as _otrace


class SLO:
    """The service-level objective the autoscaler defends."""

    def __init__(self, p95_ms: float = 500.0, max_queue: int = 32,
                 min_replicas: int = 1,
                 max_replicas: Optional[int] = None):
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas is not None and max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) < min_replicas "
                f"({min_replicas})")
        self.p95_ms = float(p95_ms)
        self.max_queue = int(max_queue)
        self.min_replicas = int(min_replicas)
        self.max_replicas = max_replicas

    def __repr__(self):
        return (f"SLO(p95_ms={self.p95_ms}, max_queue={self.max_queue}, "
                f"replicas=[{self.min_replicas}, {self.max_replicas}])")


class AutoscalerConfig:
    """Controller tunables (hysteresis, cadence, cooldown)."""

    def __init__(self, interval_s: float = 0.5, breach_ticks: int = 2,
                 calm_ticks: int = 5, cooldown_s: float = 2.0,
                 start_at_min: bool = True,
                 stat_prefix: str = "fleet.autoscale"):
        if breach_ticks < 1 or calm_ticks < 1:
            raise ValueError("breach_ticks and calm_ticks must be >= 1")
        self.interval_s = float(interval_s)
        self.breach_ticks = int(breach_ticks)
        self.calm_ticks = int(calm_ticks)
        self.cooldown_s = float(cooldown_s)
        # park down to min_replicas on start(): the Router boots every
        # shell, and serving the baseline load from min keeps the spare
        # capacity warm (compiled, parked) instead of idling in the path
        self.start_at_min = bool(start_at_min)
        self.stat_prefix = stat_prefix


class Autoscaler:
    """Scale a :class:`~paddle_tpu.serving.router.Router` between
    ``slo.min_replicas`` and ``slo.max_replicas`` (default: all shells).

    ``start()`` runs the controller thread; :meth:`tick` is public so
    tests and the replay harness can drive the decision loop
    deterministically without waiting out wall-clock intervals.
    """

    def __init__(self, router, slo: SLO,
                 config: Optional[AutoscalerConfig] = None,
                 registry: Optional[_mon.StatRegistry] = None,
                 clock=time.monotonic):
        self.router = router
        self.slo = slo
        self.config = config or AutoscalerConfig()
        self._registry = registry if registry is not None else router.registry
        self._prefix = self.config.stat_prefix
        self._clock = clock
        n = len(router.replicas)
        if slo.max_replicas is None:
            slo.max_replicas = n
        if slo.max_replicas > n:
            raise ValueError(
                f"slo.max_replicas={slo.max_replicas} exceeds the router's "
                f"{n} replica shells (the autoscaler never creates "
                f"replicas, it only parks/unparks the ones the Router "
                f"booted)")
        self._breach_run = 0          # controller-thread-only
        self._calm_run = 0
        self._cooldown_until = 0.0
        self._last_rejects = 0.0
        self._last_completed = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        if self.config.start_at_min:
            self._park_to_min()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="paddle-tpu-fleet-autoscaler",
            daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:
                # the controller must outlive a bad tick (a replica mid-
                # death can make snapshot reads race); count, warn, go on
                self._registry.add(f"{self._prefix}.tick_errors", 1)
                warnings.warn(f"autoscaler tick failed: {e!r}")
            self._stop.wait(self.config.interval_s)

    def _park_to_min(self):
        """Initial descent to min_replicas: park the highest-id active
        replicas so the lowest ids keep serving (matching unpark order)."""
        active = [x["replica"] for x in
                  self.router.fleet_snapshot()["replicas"]
                  if not x["parked"]]
        for rid in sorted(active, reverse=True):
            if len(active) <= self.slo.min_replicas:
                break
            self.router.park(rid)
            active.remove(rid)

    # -- the decision loop ---------------------------------------------------
    def tick(self) -> dict:
        """One controller decision: observe → classify → maybe act.
        Returns the decision record (also flight-logged on any action)."""
        with _otrace.span("fleet/autoscale_tick"):
            return self._tick_inner()

    def _tick_inner(self) -> dict:
        cfg, slo = self.config, self.slo
        now = self._clock()
        snap = self.router.fleet_snapshot()
        active = snap["active_replicas"]
        rejects = snap["rejected_no_replica"]
        reject_delta = max(0.0, rejects - self._last_rejects)
        self._last_rejects = rejects
        # latency samples live in a bounded reservoir that only refreshes
        # with traffic: a p95 reading is only evidence of a CURRENT breach
        # if requests completed since the last tick (the max() keeps the
        # watermark monotone across a dead replica's engine teardown)
        completed_delta = max(0, snap["completed"] - self._last_completed)
        self._last_completed = max(self._last_completed, snap["completed"])
        reasons = []
        if completed_delta > 0 and snap["p95_ms"] > slo.p95_ms:
            reasons.append(f"p95 {snap['p95_ms']:.1f}ms > {slo.p95_ms}ms")
        if snap["queue_depth"] > slo.max_queue:
            reasons.append(
                f"queue {snap['queue_depth']} > {slo.max_queue}")
        if reject_delta > 0:
            reasons.append(f"{int(reject_delta)} requests unplaceable")
        breach = bool(reasons)
        if breach:
            self._breach_run += 1
            self._calm_run = 0
        else:
            self._calm_run += 1
            self._breach_run = 0
        action = "hold"
        if breach and self._breach_run >= cfg.breach_ticks \
                and now >= self._cooldown_until:
            action = self._scale_up(snap) or "up_blocked"
        elif not breach and self._calm_run >= cfg.calm_ticks \
                and now >= self._cooldown_until \
                and active > slo.min_replicas:
            action = self._scale_down(snap) or "hold"
        if action in ("up", "down"):
            self._cooldown_until = now + cfg.cooldown_s
            self._breach_run = 0
            self._calm_run = 0
        self._registry.add(f"{self._prefix}.ticks_total", 1)
        self._registry.set(f"{self._prefix}.in_slo", 0 if breach else 1)
        self._registry.set(f"{self._prefix}.active_replicas", active)
        self._registry.set(f"{self._prefix}.breach_run", self._breach_run)
        return {"action": action, "breach": breach, "reasons": reasons,
                "active": active, "p95_ms": snap["p95_ms"],
                "queue_depth": snap["queue_depth"]}

    def _scale_up(self, snap: dict) -> Optional[str]:
        """Unpark the lowest-id parked replica (deterministic order keeps
        the fleet's identity stable across scale cycles)."""
        if snap["active_replicas"] >= self.slo.max_replicas:
            self._registry.add(f"{self._prefix}.up_at_max", 1)
            return None
        parked = snap["parked"]
        if not parked:
            # nothing to unpark: capacity was lost to an exhausted restart
            # budget, not to parking — only ops can fix that
            self._registry.add(f"{self._prefix}.up_blocked", 1)
            return None
        rid = parked[0]
        booted = self.router.unpark(rid)
        self._registry.add(f"{self._prefix}.scale_ups", 1)
        _flight.record_event(
            "autoscale_up",
            {"replica": rid, "booted": booted,
             "active": snap["active_replicas"],
             "p95_ms": snap["p95_ms"],
             "queue_depth": snap["queue_depth"]})
        return "up"

    def _scale_down(self, snap: dict) -> Optional[str]:
        """Park the least-loaded active replica (its drain finishes the
        in-flight work; nothing is dropped on a scale-down)."""
        cands = [x for x in snap["replicas"] if x["admissible"]]
        if len(cands) <= self.slo.min_replicas:
            return None
        victim = min(cands,
                     key=lambda x: (x["outstanding"], -x["replica"]))
        self.router.park(victim["replica"])
        self._registry.add(f"{self._prefix}.scale_downs", 1)
        _flight.record_event(
            "autoscale_down",
            {"replica": victim["replica"],
             "active": snap["active_replicas"]})
        return "down"

    def stats(self) -> dict:
        return self._registry.stats_with_prefix(self._prefix + ".")

    def __repr__(self):
        return (f"Autoscaler({self.slo!r}, "
                f"interval={self.config.interval_s}s)")
