"""Live weight hot-swap: roll a committed checkpoint across the fleet.

One replica at a time: pause (router stops dispatching, engine keeps its
in-flight work), migrate-out (running sequences move — paged KV pages
and all — onto siblings still serving the prior weights, so the quiesce
below is instant; fleets without live migration skip this and drain the
old way), quiesce (every slot retires into the paused admission
gate), swap (``set_state_dict`` + param re-extract — the decode/prefill
executables are keyed by spec and dtype, not parameter values, so the
persistent cache serves them unchanged and the roll costs zero
recompiles), probe (a short greedy generation straight into the engine,
version-checked), readmit. A failed probe rolls the replica back to the
weights it was serving before the swap — captured as a host-side numpy
snapshot immediately before the roll touches the model — and aborts the
rest of the roll.

Eligibility is gated BEFORE any replica is paused:
:func:`~paddle_tpu.incubate.checkpoint.sharded.swap_eligible` requires a
committed (two-phase) checkpoint directory, a healthy stamp, and a clean
checksum sweep — the same three gates the resurrection boot path
applies.

Chaos hook: the ``weight_swap`` fault site fires once per replica swap
(actions: ``fail`` / ``disk_full`` force the rollback path, ``slow_io``
stretches the swap window — see docs/fault_tolerance.md).
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import numpy as np

from ...core import monitor as _mon
from ...observability import flight as _flight
from ...observability import tracer as _otrace
from ...utils.resilience import fault_injector


class SwapError(RuntimeError):
    """A weight roll was refused (ineligible checkpoint) or a replica
    swap failed its probe (the replica was rolled back)."""


class WeightSwapper:
    """Roll health-stamped checkpoints across a ``kind="llm"`` Router."""

    def __init__(self, router, registry: Optional[_mon.StatRegistry] = None,
                 *, probe_prompt=None, probe_new_tokens: int = 2,
                 probe_timeout: float = 30.0, quiesce_timeout: float = 30.0,
                 stat_prefix: str = "fleet.swap", clock=time.monotonic):
        if router.kind != "llm":
            raise ValueError(
                "WeightSwapper drives LLMEngine replicas; classifier "
                "routers reload via predictor artifacts, not live swaps")
        self.router = router
        self._registry = registry if registry is not None else router.registry
        self._prefix = stat_prefix
        self._probe_prompt = (list(probe_prompt)
                              if probe_prompt is not None else [1, 2, 3])
        self._probe_new_tokens = int(probe_new_tokens)
        self._probe_timeout = float(probe_timeout)
        self._quiesce_timeout = float(quiesce_timeout)
        self._clock = clock

    # -- public API ----------------------------------------------------------
    def roll(self, checkpoint_path: str, *, verify: bool = True) -> dict:
        """Swap ``checkpoint_path`` onto every serving replica, one at a
        time. Returns the roll report; raises :class:`SwapError` without
        touching any replica when the checkpoint is not swap-eligible.

        A replica whose post-swap probe fails is rolled back to its prior
        weights and the roll is aborted (replicas already swapped stay on
        the new weights — re-issue the roll after fixing the checkpoint to
        converge, or roll the prior checkpoint to walk them back)."""
        from ...incubate.checkpoint.sharded import load_sharded, swap_eligible
        ok, reason = swap_eligible(checkpoint_path, verify=verify)
        if not ok:
            self._registry.add(f"{self._prefix}.refused", 1)
            raise SwapError(f"refusing weight roll: {reason}")
        state = load_sharded(checkpoint_path, verify=False)  # just verified
        weights = state["model"] if "model" in state else state
        self._registry.add(f"{self._prefix}.rolls", 1)
        report = {"checkpoint": checkpoint_path, "swapped": [],
                  "skipped": [], "rolled_back": None, "failed": None,
                  "downtime_ms": {}, "versions": {}, "aborted": False}
        _flight.record_event("weight_roll_begin",
                             {"checkpoint": checkpoint_path})
        for replica in self.router.replicas:
            rid = replica.replica_id
            if rid in set(self.router.parked_ids()) \
                    or replica.state != "HEALTHY":
                report["skipped"].append(rid)
                continue
            ok = self._swap_one(replica, weights, report)
            if not ok:
                report["aborted"] = True
                break
        _flight.record_event(
            "weight_roll_end",
            {"checkpoint": checkpoint_path,
             "swapped": report["swapped"],
             "rolled_back": report["rolled_back"],
             "aborted": report["aborted"]})
        return report

    # -- per-replica sequence ------------------------------------------------
    def _swap_one(self, replica, weights: Dict, report: dict) -> bool:
        rid = replica.replica_id
        engine = replica.engine
        with _otrace.span("fleet/weight_swap", {"replica": rid}):
            # rollback source: the weights this replica serves RIGHT NOW,
            # as host copies (state_dict() returns live tensor refs that
            # set_state_dict would overwrite in place)
            prior = {
                k: np.array(v.numpy())  # noqa: PTA002 -- once-per-swap rollback snapshot while paused, not on the token path
                for k, v in engine.decoder.model.state_dict().items()}
            t0 = self._clock()
            replica.pause()
            engine.pause_admission()
            try:
                action = fault_injector().fire("weight_swap")
                if action == "slow_io":
                    time.sleep(float(os.environ.get(
                        "PADDLE_TPU_FAULT_SLOW_IO_S", "0.2")))
                # zero-loss roll: instead of waiting for the quiesce to
                # drain every in-flight sequence through this (possibly
                # slow_io-widened) window, move them — KV pages and all —
                # onto siblings still serving the prior weights. The
                # swap's internal quiesce then completes instantly. Any
                # sequence migration could not move (no migrator, engine
                # without paged KV, no admissible sibling) simply rides
                # out the quiesce as before — a latency cost, never a
                # drop.
                migrator = getattr(self.router, "migrator", None)
                if migrator is not None and \
                        getattr(engine, "supports_migration", False):
                    mig = migrator.migrate_replica(replica, reason="swap")
                    report.setdefault("migrated", {})[rid] = (
                        mig["imported"] + mig["replayed"] + mig["requeued"])
                version = engine.swap_weights(
                    weights, timeout=self._quiesce_timeout)
                if action in ("fail", "disk_full"):
                    raise SwapError(
                        f"fault injection: weight swap on replica {rid} "
                        f"hit {action}")
                engine.resume_admission()
                if not self._probe(engine, version):
                    raise SwapError(
                        f"replica {rid} failed its post-swap probe")
            except Exception as e:
                self._rollback(replica, engine, prior, e, report)
                return False
            replica.resume()
            downtime = (self._clock() - t0) * 1000.0
            report["swapped"].append(rid)
            report["versions"][rid] = version
            report["downtime_ms"][rid] = downtime
            self._registry.add(f"{self._prefix}.replicas_swapped", 1)
            self._registry.observe(f"{self._prefix}.downtime_ms", downtime)
            _flight.record_event(
                "weight_swap_ok",
                {"replica": rid, "version": version,
                 "downtime_ms": downtime})
            return True

    def _probe(self, engine, expect_version: int) -> bool:
        """Health-check the swapped engine with a short greedy generation
        submitted DIRECTLY to the engine (the replica is paused, so no
        router traffic mixes into the probe window). The result must
        carry the expected weights version — the bitwise old-or-new
        guarantee made observable."""
        try:
            req = engine.submit(self._probe_prompt,
                                max_new_tokens=self._probe_new_tokens)
            res = req.result(timeout=self._probe_timeout)
        except Exception:
            return False
        return (res.get("weights_version") == expect_version
                and len(res.get("tokens", ())) >= 1)

    def _rollback(self, replica, engine, prior: Dict, cause: BaseException,
                  report: dict):
        """Swap the prior weights back and re-probe; a replica that fails
        even the rollback probe is marked unhealthy so the health sweep
        drains it and resurrects from the newest health-stamped
        checkpoint."""
        rid = replica.replica_id
        self._registry.add(f"{self._prefix}.rollbacks", 1)
        _flight.record_event(
            "weight_swap_rollback",
            {"replica": rid, "cause": f"{type(cause).__name__}: {cause}"})
        try:
            engine.pause_admission()
            version = engine.swap_weights(
                prior, timeout=self._quiesce_timeout)
            engine.resume_admission()
            ok = self._probe(engine, version)
        except Exception:
            ok = False
        if ok:
            replica.resume()
            report["rolled_back"] = rid
        else:
            # can't even serve the old weights: hand the replica to the
            # health sweep (drain -> DEAD -> budgeted resurrection from
            # the newest health-stamped checkpoint)
            self._registry.add(f"{self._prefix}.failed", 1)
            replica.mark_unhealthy("weight-swap rollback probe failed")
            replica.resume()
            report["failed"] = rid

    def stats(self) -> dict:
        return self._registry.stats_with_prefix(self._prefix + ".")
