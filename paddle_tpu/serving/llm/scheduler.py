"""ContinuousBatcher + LLMEngine: the token-level serving loop.

Classifier serving dispatches whole requests; LLM serving schedules at
token granularity. One worker thread runs ticks of the SINGLE compiled
decode step over all KV slots; between ticks, sequences join (bucketed
prefill into a free slot, straight off the shared :class:`BatchQueue`) or
leave (eos / length budget / mid-stream deadline eviction) — continuous
batching in the Orca sense: admission never waits for the current batch
to finish, and a finished sequence's slot is reusable on the very next
tick.

Host<->device traffic per tick is exactly one fetch: the ``[num_slots]``
next-token vector, which streaming delivery needs on host anyway. Slot
bookkeeping, finish detection, and deadline eviction are all host-side
reads of that vector plus counters the scheduler already tracks, so the
device never round-trips for control flow.

Drain semantics match the classifier engine: ``begin_drain`` (or SIGTERM
through the chained handler) stops admission, and the worker keeps
ticking until every in-flight sequence finishes and the queue is flushed
— preemption never strands a future mid-generation.
"""
from __future__ import annotations

import collections
import itertools
import os
import queue as _pyqueue
import threading
import time
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp

from ...core import monitor as _mon
from ...observability import flight as _flight
from ...observability import tracer as _otrace
from ..buckets import pow2_buckets
from ..cache import ExecutableCache, default_cache
from ..engine import DrainableEngineBase
from ..queue import BatchQueue
from ...utils.resilience import fault_injector
from ..request import (Deadline, DeadlineExceeded, EngineDraining,
                       EngineKilled, RequestTooLarge,
                       TokenStreamDivergence)
from .decode import GPTStaticDecoder, SamplingParams, pack_sampling
from .kvcache import StaticKVCache
from .prefix import PrefixStore
from .spec import GPTSpecDecoder

_REQ_IDS = itertools.count(1)
_STREAM_END = object()


class GenerationRequest:
    """One queued generation: prompt + sampling params + result future.

    Duck-types the queue contract of :class:`InferenceRequest` (``expired``
    / ``fail_expired`` / ``future``) so the shared :class:`BatchQueue`
    admission and head-of-line deadline eviction apply unchanged. The
    future resolves to ``{"tokens": [...], "finish_reason": ...}``; with
    ``stream=True``, :meth:`iter_tokens` yields tokens as ticks produce
    them.
    """

    __slots__ = ("req_id", "prompt", "sampling", "deadline", "future",
                 "t_enqueue", "t_first_token", "tokens", "finish_reason",
                 "_stream_q", "_clock", "_prefix_entry", "_t_last",
                 "weights_version", "_replay_pos", "_resume_offset")

    def __init__(self, prompt, sampling: SamplingParams,
                 deadline: Optional[Deadline] = None, stream: bool = False,
                 clock=time.monotonic):
        arr = np.asarray(prompt, dtype=np.int32).reshape(-1)  # noqa: PTA002 -- admission-time conversion of the caller's host-side prompt (list/ndarray), not a device value
        if arr.size < 1:
            raise ValueError("prompt must contain at least one token")
        self.req_id = next(_REQ_IDS)
        self.prompt = arr
        self.sampling = sampling
        self.deadline = deadline
        from concurrent.futures import Future
        self.future = Future()
        self._clock = clock
        self.t_enqueue = clock()
        self.t_first_token: Optional[float] = None
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self._stream_q = _pyqueue.Queue() if stream else None
        # prefix-store pin held while this request is in flight (the
        # batcher unpins on release/evict/abort) + inter-token clock
        self._prefix_entry = None
        self._t_last: Optional[float] = None
        # stamped at admission from the batcher's weight generation; the
        # whole generation runs on that one generation (hot-swap waits
        # for slots to quiesce), so the result is bitwise old-or-new
        self.weights_version: Optional[int] = None
        # resume-dedup guard (docs/fault_tolerance.md "Zero-loss
        # serving"): after a migration/replay rebind, `_replay_pos`
        # marks the next already-streamed token the engine must
        # re-verify before any NEW token may flow; `_resume_offset`
        # counts generated tokens folded into the rebuilt prompt so
        # `seq_len` stays invariant across resumes.
        self._replay_pos: Optional[int] = None
        self._resume_offset = 0

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.size)

    @property
    def seq_len(self) -> int:
        """Logical sequence length: ORIGINAL prompt + generated tokens.
        Invariant under resume (a replayed request's ``prompt`` holds
        already-generated tokens; ``_resume_offset`` backs them out), so
        capacity and length-budget checks never double-count."""
        return self.prompt_len - self._resume_offset + len(self.tokens)

    @property
    def expired(self) -> bool:
        return self.deadline is not None and self.deadline.expired()

    @property
    def nrows(self) -> int:          # queue/stats compatibility
        return 1

    def fail(self, exc: BaseException) -> bool:
        if self.future.done():
            return False
        self.future.set_exception(exc)
        if self._stream_q is not None:
            self._stream_q.put(exc)
            self._stream_q.put(_STREAM_END)
        return True

    def fail_expired(self) -> bool:
        return self.fail(DeadlineExceeded(
            f"generation request {self.req_id} exceeded its "
            f"{self.deadline.seconds}s deadline"))

    def begin_resume(self, n_resume: int) -> "GenerationRequest":
        """Rebind this request for resumption on another engine with
        ``n_resume`` generated tokens' worth of state restored (from a
        migrated KV splice or a journal replay). The prompt is rebuilt
        as ``original_prompt + tokens[:n_resume]`` so a plain prefill
        reconstructs the cache, and the dedup guard arms: every
        re-generated token in ``tokens[n_resume:]`` is VERIFIED against
        what the client already received and swallowed — the stream
        resumes at the exact next unseen token, or fails loudly with
        :class:`TokenStreamDivergence`. Raises (gap direction) when
        ``n_resume`` exceeds what the client has."""
        n = int(n_resume)
        if n < 0 or n > len(self.tokens):
            raise TokenStreamDivergence(
                f"request {self.req_id}: cannot resume at token {n}; "
                f"the client has {len(self.tokens)} — the restored "
                f"state is AHEAD of the stream and would emit a gap")
        base = self.prompt[:self.prompt.size - self._resume_offset]
        if n:
            self.prompt = np.concatenate(
                [base,
                 np.asarray(self.tokens[:n], np.int32)])  # noqa: PTA002 -- self.tokens is a host-side list of emitted ints, not a device value
        else:
            self.prompt = base
        self._resume_offset = n
        self._replay_pos = n if n < len(self.tokens) else None
        self._t_last = None
        return self

    def _emit(self, tok: int) -> bool:
        """Deliver one engine-produced token. During a resume replay the
        token is verified against the already-streamed transcript and
        swallowed (never re-delivered); a mismatch fails the request
        with :class:`TokenStreamDivergence` and returns False — the
        caller must then forget the slot without finishing."""
        if self._replay_pos is not None:
            pos = self._replay_pos
            if pos < len(self.tokens):
                if tok != self.tokens[pos]:
                    self.fail(TokenStreamDivergence(
                        f"request {self.req_id}: resumed stream produced "
                        f"token {tok} at position {pos} but the client "
                        f"already received {self.tokens[pos]} — refusing "
                        f"to corrupt the stream"))
                    return False
                self._replay_pos = pos + 1
                if self._replay_pos >= len(self.tokens):
                    self._replay_pos = None
                return True
            self._replay_pos = None
        if self.t_first_token is None:
            self.t_first_token = self._clock()
        self.tokens.append(tok)
        if self._stream_q is not None:
            self._stream_q.put(tok)
        return True

    def _finish(self, reason: str):
        self.finish_reason = reason
        if not self.future.done():
            self.future.set_result(
                {"tokens": list(self.tokens), "finish_reason": reason,
                 "req_id": self.req_id,
                 "weights_version": self.weights_version})
        if self._stream_q is not None:
            self._stream_q.put(_STREAM_END)

    def iter_tokens(self, timeout: Optional[float] = None):
        """Yield tokens as they are generated (``stream=True`` requests
        only); raises the failure exception on eviction/drain-abort."""
        if self._stream_q is None:
            raise ValueError("request was not submitted with stream=True")
        while True:
            item = self._stream_q.get(timeout=timeout)
            if item is _STREAM_END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def result(self, timeout: Optional[float] = None) -> dict:
        return self.future.result(timeout)


class LLMEngineConfig:
    """Tunables for the LLM serving engine (see docs/serving.md)."""

    def __init__(self,
                 num_slots: int = 8,
                 max_seq: int = 256,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 max_queue: int = 256,
                 admission_block: bool = True,
                 admission_timeout: Optional[float] = 2.0,
                 default_deadline: Optional[float] = None,
                 default_max_new_tokens: int = 64,
                 max_top_k: int = 64,
                 idle_poll: float = 0.01,
                 warmup: bool = True,
                 seed: int = 0,
                 measure_mfu: bool = False,
                 prefix_cache: bool = False,
                 prefix_block: int = 16,
                 prefix_capacity_mb: float = 256.0,
                 spec_k: int = 0,
                 role: str = "mixed",
                 weight_dtype: str = "float32",
                 kv_dtype: str = "float32",
                 kv_layout: str = "slot",
                 page_size: int = 16,
                 num_pages: Optional[int] = None,
                 paged_attn_impl: str = "auto",
                 stat_prefix: str = "serving.llm"):
        self.num_slots = int(num_slots)
        self.max_seq = int(max_seq)
        if prefill_buckets is None:
            prefill_buckets = pow2_buckets(self.max_seq,
                                           start=min(8, self.max_seq))
        buckets = tuple(sorted(set(int(b) for b in prefill_buckets)))
        if not buckets or buckets[0] < 1 or buckets[-1] > self.max_seq:
            raise ValueError(
                f"prefill buckets must lie in [1, max_seq={self.max_seq}]; "
                f"got {buckets}")
        self.prefill_buckets = buckets
        self.max_queue = int(max_queue)
        self.admission_block = bool(admission_block)
        self.admission_timeout = admission_timeout
        self.default_deadline = default_deadline
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.max_top_k = int(max_top_k)
        self.idle_poll = float(idle_poll)
        self.warmup = bool(warmup)
        self.seed = int(seed)
        # opt-in: publish `serving.llm.mfu` from XLA cost analysis of the
        # decode step (costs one extra compile at the first tick)
        self.measure_mfu = bool(measure_mfu)
        # disaggregated-fleet knobs (docs/serving.md "Disaggregated fleet")
        self.prefix_cache = bool(prefix_cache)
        self.prefix_block = int(prefix_block)
        self.prefix_capacity_mb = float(prefix_capacity_mb)
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        self.spec_k = int(spec_k)          # 0 disables speculative decode
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"role must be prefill/decode/mixed, got {role!r}")
        self.role = role
        # quantized serving (docs/quantization.md): int8 weights halve
        # parameter bytes; int8 KV halves cache bytes so slots-per-chip
        # doubles. Both dequantize inside the fused decode step.
        if weight_dtype not in ("float32", "int8"):
            raise ValueError(
                f"weight_dtype must be 'float32' or 'int8', got "
                f"{weight_dtype!r}")
        if kv_dtype not in ("float32", "int8"):
            raise ValueError(
                f"kv_dtype must be 'float32' or 'int8', got {kv_dtype!r}")
        if kv_dtype == "int8" and self.prefix_cache:
            raise ValueError(
                "prefix_cache requires a dense KV cache: the prefix "
                "export/insert path moves raw f32 rows between engines. "
                "Set kv_dtype='float32' or prefix_cache=False.")
        if kv_dtype == "int8" and self.spec_k > 0:
            raise ValueError(
                "speculative decoding (spec_k > 0) requires a dense KV "
                "cache: the verify/rollback path rewrites accepted rows "
                "in place. Set kv_dtype='float32' or spec_k=0.")
        self.weight_dtype = weight_dtype
        self.kv_dtype = kv_dtype
        # paged KV substrate (docs/serving.md "Paged KV cache"): fixed
        # page_size-token pages in one arena, admission on pages at
        # current lengths instead of worst-case max_seq slots
        if kv_layout not in ("slot", "paged"):
            raise ValueError(
                f"kv_layout must be 'slot' or 'paged', got {kv_layout!r}")
        if paged_attn_impl not in ("auto", "gather", "kernel"):
            raise ValueError(
                f"paged_attn_impl must be 'auto', 'gather' or 'kernel', "
                f"got {paged_attn_impl!r}")
        self.kv_layout = kv_layout
        self.page_size = int(page_size)
        self.num_pages = None if num_pages is None else int(num_pages)
        self.paged_attn_impl = paged_attn_impl
        if kv_layout == "paged":
            if self.page_size < 1 or self.max_seq % self.page_size:
                raise ValueError(
                    f"page_size {self.page_size} must divide "
                    f"max_seq {self.max_seq} (the gather lane's bitwise "
                    f"parity relies on whole-page rows)")
            if self.num_pages is not None and self.num_pages < \
                    self.max_seq // self.page_size:
                raise ValueError(
                    f"num_pages {self.num_pages} cannot hold even one "
                    f"max_seq sequence "
                    f"({self.max_seq // self.page_size} pages)")
        self.stat_prefix = stat_prefix

    @property
    def max_prompt_len(self) -> int:
        """Longest admissible prompt: must fit a bucket AND leave room for
        at least one generated token in the slot."""
        return min(self.prefill_buckets[-1], self.max_seq - 1)

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.prefill_buckets:
            if prompt_len <= b:
                return b
        raise RequestTooLarge(
            f"prompt of {prompt_len} tokens exceeds the largest prefill "
            f"bucket ({self.prefill_buckets[-1]})")


class ContinuousBatcher:
    """Slot-level scheduling state + the per-tick device interaction.

    Owns the :class:`StaticKVCache`, the per-slot device vectors
    (``finished``, ``last_tokens``, packed sampling params), and the
    slot -> request table. ``admit`` prefms prefill + first-token
    delivery; ``tick`` advances every active sequence one token and
    retires finished/evicted slots. Single-threaded by design: only the
    engine worker calls into it.
    """

    def __init__(self, decoder: GPTStaticDecoder, config: LLMEngineConfig,
                 registry: _mon.StatRegistry, clock=time.monotonic,
                 prefix_store: Optional[PrefixStore] = None,
                 spec_decoder: Optional[GPTSpecDecoder] = None):
        self.decoder = decoder
        self.config = config
        self._registry = registry
        self._prefix = config.stat_prefix
        self._clock = clock
        self.kv = decoder.new_kv(config.num_slots, config.max_seq)
        self._params = decoder.params()
        self.prefix_store = prefix_store
        self.spec = spec_decoder
        self.kv_draft: Optional[StaticKVCache] = None
        self._draft_params = None
        if spec_decoder is not None:
            # draft cache mirrors the target's slot/position geometry; one
            # shared lengths vector advances both in lockstep
            self.kv_draft = spec_decoder.new_draft_kv(config.num_slots,
                                                      config.max_seq)
            self._draft_params = spec_decoder.draft_params()
        self._spec_proposed = 0
        self._spec_accepted = 0
        #: monotonically increasing weight generation; bumped by the
        #: engine's swap_weights AFTER slots quiesce, read at admission
        self.weights_version = 0
        self._reqs: Dict[int, GenerationRequest] = {}
        self._slot_samp: List[SamplingParams] = [
            SamplingParams() for _ in range(config.num_slots)]
        self._samp_vecs = pack_sampling(self._slot_samp)
        self._finished = jnp.zeros((config.num_slots,), jnp.bool_)
        self._last = jnp.zeros((config.num_slots,), jnp.int32)
        self._rng = jax.random.PRNGKey(config.seed)
        # decode-step FLOPs (measure_mfu): measured lazily at first tick
        self._decode_flops: Optional[float] = None
        self._peak_flops: Optional[float] = None

    # -- introspection -------------------------------------------------------
    @property
    def active(self) -> int:
        return len(self._reqs)

    @property
    def free_slots(self) -> int:
        return self.kv.free_slots

    def refresh_params(self):
        """Re-extract model parameters (after a checkpoint reload)."""
        self._params = self.decoder.params()

    # -- internals -----------------------------------------------------------
    def _next_key(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _stat_add(self, name, v):
        self._registry.add(f"{self._prefix}.{name}", v)

    def _stat_set(self, name, v):
        self._registry.set(f"{self._prefix}.{name}", v)

    def _stat_observe(self, name, v):
        self._registry.observe(f"{self._prefix}.{name}", v)

    # -- scheduling ----------------------------------------------------------
    def admit(self, req: GenerationRequest):
        """Prefill ``req`` into a free slot and deliver its first token.
        The caller guarantees ``free_slots > 0`` and a bucket-fitting
        prompt (``submit`` validated both)."""
        with _otrace.span("serving.llm/prefill"):
            self._admit_inner(req)

    def _admit_inner(self, req: GenerationRequest):
        t0 = self._clock()
        slot = self.kv.alloc()
        req.weights_version = self.weights_version
        self._reqs[slot] = req
        self._slot_samp[slot] = req.sampling
        self._samp_vecs = pack_sampling(self._slot_samp)
        samp1 = pack_sampling([req.sampling])
        slot_arr = jnp.asarray([slot], jnp.int32)
        entry, reuse_n = None, 0
        if self.prefix_store is not None:
            # cap: at least one prompt token must prefill (logits source)
            entry, reuse_n = self.prefix_store.lookup(
                req.prompt, req.prompt_len - 1,
                self.decoder.prefix_sig(self.kv))
            # the PADDED tail bucket must fit behind the reused head —
            # dynamic_update_slice clamps out-of-range starts, which would
            # silently corrupt the reused rows. Shrink reuse block-wise
            # until offset + tail_bucket fits (rarely more than one step).
            while reuse_n > 0 and reuse_n + self.config.bucket_for(
                    req.prompt_len - reuse_n) > self.config.max_seq:
                reuse_n -= self.prefix_store.block_tokens
            if entry is not None and reuse_n <= 0:
                self.prefix_store.unpin(entry)
                entry, reuse_n = None, 0
        if reuse_n > 0:
            # hit: bulk-copy the cached head, prefill only the tail bucket
            self.decoder.insert_prefix(
                self.kv, entry.k[:, :reuse_n], entry.v[:, :reuse_n], slot)
            self.prefix_store.note_copied(
                int(entry.k[:, :reuse_n].nbytes
                    + entry.v[:, :reuse_n].nbytes))
            req._prefix_entry = entry       # stays pinned until release
            tail = req.prompt[reuse_n:]
            lt = self.config.bucket_for(int(tail.size))
            padded = np.zeros((1, lt), np.int32)
            padded[0, :tail.size] = tail
            nxt, self._finished = self.decoder.tail_prefill(
                self.kv, self._params, jnp.asarray(padded),
                jnp.asarray([int(tail.size)], jnp.int32),
                jnp.asarray([reuse_n], jnp.int32), slot_arr,
                self._finished, samp1, self._next_key())
            self._stat_add("prefix.reused_tokens", reuse_n)
        else:
            lp = self.config.bucket_for(req.prompt_len)
            padded = np.zeros((1, lp), np.int32)
            padded[0, :req.prompt_len] = req.prompt
            nxt, self._finished = self.decoder.prefill(
                self.kv, self._params, jnp.asarray(padded),
                jnp.asarray([req.prompt_len], jnp.int32), slot_arr,
                self._finished, samp1, self._next_key())
            if self.prefix_store is not None:
                # miss: export the block-aligned head for future requests
                blk = self.prefix_store.block_tokens
                n = (req.prompt_len // blk) * blk
                if n >= blk:
                    k_h, v_h = self.kv.host_slot_kv(slot, n)
                    ins = self.prefix_store.insert(
                        req.prompt[:n], k_h, v_h,
                        self.decoder.prefix_sig(self.kv))
                    if ins is not None:
                        req._prefix_entry = ins
        if self.spec is not None:
            # the draft cache never reuses prefixes (the draft is cheap and
            # its K/V is not stored); full-prompt prefill, keep K/V only
            lp = self.config.bucket_for(req.prompt_len)
            dpad = np.zeros((1, lp), np.int32)
            dpad[0, :req.prompt_len] = req.prompt
            self.spec.draft_prefill(
                self.kv_draft, self._draft_params, jnp.asarray(dpad),
                jnp.asarray([req.prompt_len], jnp.int32), slot_arr,
                self.kv.lengths, self._finished, samp1, self._next_key())
        self._last = self._last.at[jnp.asarray([slot])].set(nxt)
        # The admission-time fetch of the first generated token: streaming
        # TTFT requires it on host, and it doubles as the finish probe.
        tok = int(np.asarray(jax.device_get(nxt))[0])  # noqa: PTA002 -- one [1]-token fetch per admission; first-token delivery (TTFT) needs the value on host
        now = self._clock()
        self._stat_observe("prefill_ms", (now - t0) * 1000.0)
        self._stat_observe("ttft_ms", (now - req.t_enqueue) * 1000.0)
        self._stat_add("prefills", 1)
        if not req._emit(tok):
            self._forget(slot, req)
            return
        req._t_last = now
        self._stat_add("tokens_generated", 1)
        self._maybe_finish(slot, req, tok)

    def tick(self) -> int:
        """One decode tick: advance every slot through THE compiled step
        (1 token plain, 1..k+1 speculative), deliver tokens, retire
        finished slots. Returns the number of active sequences advanced."""
        if not self._reqs:
            return 0
        if self.spec is not None:
            if self._spec_room_ok():
                with _otrace.span("serving.llm/spec_tick"):
                    return self._spec_tick()
            # near the end of a slot row there is no room for k+1
            # candidate writes — run the plain one-token step instead
            self._stat_add("spec.fallback_ticks", 1)
        with _otrace.span("serving.llm/decode_tick"):
            return self._tick_inner()

    def _spec_room_ok(self) -> bool:
        """True when every ACTIVE slot can absorb k+1 candidate K/V rows:
        the next write position is ``prompt_len + len(tokens) - 1`` (the
        last emitted token is not yet in cache) and the verify step lands
        rows up to position + k."""
        k = self.spec.k
        for req in self._reqs.values():
            pos = req.seq_len - 1
            if pos + k + 1 > self.config.max_seq:
                return False
        return True

    def _spec_tick(self) -> int:
        t0 = self._clock()
        self._finished, self._last, out_dev = self.spec.step(
            self.kv, self.kv_draft, self._params, self._draft_params,
            self._finished, self._last, self._samp_vecs, self._next_key())
        # THE one host fetch of the tick: the packed [S, k+2]
        # (count | tokens...) matrix — same budget as the plain tick's
        # next-token vector, just wider.
        out = np.asarray(jax.device_get(out_dev))  # noqa: PTA002 -- the single per-tick packed emit fetch; token streaming requires host delivery
        n = len(self._reqs)
        dt = max(self._clock() - t0, 1e-9)
        total = 0
        for slot, req in list(self._reqs.items()):
            if req.expired:
                self._evict(slot, req)
                continue
            n_emit = int(out[slot, 0])
            toks = out[slot, 1:1 + n_emit]
            if not req.sampling.do_sample:
                # acceptance accounting is a greedy-lane concept; sampling
                # slots take one verified token per tick by construction
                self._spec_proposed += self.spec.k
                self._spec_accepted += n_emit - 1
                self._stat_add("spec.proposed", self.spec.k)
                self._stat_add("spec.accepted", n_emit - 1)
            total += self._emit_many(slot, req, toks)
        self._stat_observe("decode_tick_ms", dt * 1000.0)
        # per-token time: the tick advanced each slot by total/n tokens on
        # average, so normalize to stay comparable with the plain tick
        self._stat_observe("tpot_ms", dt * 1000.0 * n / max(1, total))
        self._stat_add("tokens_generated", total)
        self._stat_set("tokens_per_sec", total / dt)
        self._stat_add("spec.ticks", 1)
        if self._spec_proposed:
            self._stat_set("spec.acceptance_rate",
                           self._spec_accepted / self._spec_proposed)
        return n

    def _emit_many(self, slot: int, req: GenerationRequest, toks) -> int:
        """Deliver a spec tick's emitted tokens in order, stopping at the
        first finish condition (same eos-before-budget order as
        :meth:`_maybe_finish`; surplus device-side tokens are discarded —
        the slot is released, so the cache divergence is unobservable)."""
        emitted = 0
        now = self._clock()
        if req._t_last is not None:
            self._stat_observe("intertoken_ms",
                               (now - req._t_last) * 1000.0)
        req._t_last = now
        s = req.sampling
        for tok in toks:
            tok = int(tok)
            if not req._emit(tok):
                self._forget(slot, req)
                break
            emitted += 1
            if s.eos_token_id is not None and tok == int(s.eos_token_id):
                self._release(slot, req, "stop")
                break
            if len(req.tokens) >= s.max_new_tokens \
                    or req.seq_len >= self.config.max_seq:
                self._release(slot, req, "length")
                break
        return emitted

    def _tick_inner(self) -> int:
        if self.config.measure_mfu and self._decode_flops is None:
            self._measure_decode_flops()
        t0 = self._clock()
        nxt, self._finished = self.decoder.decode_step(
            self.kv, self._params, self._finished, self._last,
            self._samp_vecs, self._next_key())
        self._last = nxt
        # THE one host fetch of the tick: the [num_slots] next-token
        # vector. Streaming delivery and host-side finish detection both
        # consume it, so this sync is the feature, not an accident.
        toks = np.asarray(jax.device_get(nxt))  # noqa: PTA002 -- the single per-tick [num_slots] fetch; token streaming requires host delivery
        n = len(self._reqs)
        dt = max(self._clock() - t0, 1e-9)
        self._stat_observe("decode_tick_ms", dt * 1000.0)
        self._stat_observe("tpot_ms", dt * 1000.0)
        self._stat_add("tokens_generated", n)
        self._stat_set("tokens_per_sec", n / dt)
        if self._decode_flops:
            # tick wall time includes the sanctioned token fetch, so this
            # is delivered MFU, not device-only MFU
            self._stat_set("mfu", self._decode_flops / dt / self._peak_flops)
        now = self._clock()
        for slot, req in list(self._reqs.items()):
            if req.expired:
                self._evict(slot, req)
                continue
            tok = int(toks[slot])
            if not req._emit(tok):
                self._forget(slot, req)
                continue
            if req._t_last is not None:
                self._stat_observe("intertoken_ms",
                                   (now - req._t_last) * 1000.0)
            req._t_last = now
            self._maybe_finish(slot, req, tok)
        return n

    def _measure_decode_flops(self):
        """XLA cost analysis of THE decode step (once, at first tick when
        ``measure_mfu``): compiles the raw program a second time to read
        its flops without executing. Failure disables MFU, never decode."""
        from ...observability import stepmeter as _sm
        from .decode import build_decode_step
        raw = build_decode_step(self.decoder.spec, self.decoder.max_top_k)
        with _otrace.span("observability/cost_analysis"):
            flops = _sm.compiled_flops(
                raw, self._params, self.kv.k, self.kv.v, self.kv.lengths,
                self._finished, self._last, *self._samp_vecs,
                jax.random.PRNGKey(0))
        self._peak_flops = _sm.default_peak_flops()
        self._decode_flops = flops if flops else 0.0
        if flops:
            self._stat_set("decode_flops_per_tick", flops)

    def _maybe_finish(self, slot: int, req: GenerationRequest, tok: int):
        s = req.sampling
        if s.eos_token_id is not None and tok == int(s.eos_token_id):
            self._release(slot, req, "stop")
        elif len(req.tokens) >= s.max_new_tokens:
            self._release(slot, req, "length")
        elif req.seq_len >= self.config.max_seq:
            self._release(slot, req, "length")

    def _unpin_prefix(self, req: GenerationRequest):
        """Drop the request's prefix-store pin (if any) the moment the
        request leaves the engine — eviction of its entry becomes legal
        again. Every exit path (release/evict/abort) funnels through
        this."""
        if req._prefix_entry is not None and self.prefix_store is not None:
            self.prefix_store.unpin(req._prefix_entry)
            req._prefix_entry = None

    def _release(self, slot: int, req: GenerationRequest, reason: str):
        del self._reqs[slot]
        self.kv.free(slot)
        self._unpin_prefix(req)
        req._finish(reason)
        self._stat_add("completed", 1)
        self._stat_observe("request_latency_ms",
                           (self._clock() - req.t_enqueue) * 1000.0)

    def _evict(self, slot: int, req: GenerationRequest):
        """Mid-stream deadline eviction: the slot is reclaimed and the
        future fails — a stalled consumer cannot pin a slot forever."""
        del self._reqs[slot]
        self.kv.free(slot)
        self._unpin_prefix(req)
        req.fail(DeadlineExceeded(
            f"generation request {req.req_id} exceeded its "
            f"{req.deadline.seconds}s deadline after "
            f"{len(req.tokens)} tokens"))
        self._stat_add("evicted_midstream", 1)

    def _forget(self, slot: int, req: GenerationRequest):
        """Reclaim a slot whose request already resolved (the dedup
        guard failed it mid-replay): free resources, touch neither the
        future nor the stream."""
        del self._reqs[slot]
        self.kv.free(slot)
        self._unpin_prefix(req)
        self._stat_add("stream_divergence", 1)

    def evacuate(self) -> List[GenerationRequest]:
        """Detach every in-flight request WITHOUT failing it — the
        zero-loss half of a hard kill. Slots and prefix pins are
        reclaimed; the futures stay pending for the router's recovery
        replay (docs/fault_tolerance.md "Zero-loss serving")."""
        out: List[GenerationRequest] = []
        for slot, req in list(self._reqs.items()):
            del self._reqs[slot]
            self.kv.free(slot)
            self._unpin_prefix(req)
            out.append(req)
        return out

    def abort_all(self, exc_factory):
        """Fail every in-flight sequence (forced shutdown, not drain)."""
        for slot, req in list(self._reqs.items()):
            del self._reqs[slot]
            self.kv.free(slot)
            self._unpin_prefix(req)
            req.fail(exc_factory(req))

    # -- warmup --------------------------------------------------------------
    def warmup(self):
        """Compile the decode step and every prefill bucket up front so no
        request pays a trace. Runs dummy work through the real buffers,
        then resets slot state — junk K/V is masked by the zeroed
        lengths."""
        t0 = self._clock()
        samp = pack_sampling([SamplingParams()])
        slot0 = jnp.asarray([0], jnp.int32)
        for lp in self.config.prefill_buckets:
            self.decoder.prefill(
                self.kv, self._params, jnp.zeros((1, lp), jnp.int32),
                jnp.asarray([lp], jnp.int32), slot0,
                self._finished, samp, self._next_key())
            if self.prefix_store is not None:
                # one trace per tail bucket covers every reuse offset —
                # `starts` is a traced device argument, not a shape
                self.decoder.tail_prefill(
                    self.kv, self._params, jnp.zeros((1, lp), jnp.int32),
                    jnp.asarray([lp], jnp.int32),
                    jnp.zeros((1,), jnp.int32), slot0,
                    self._finished, samp, self._next_key())
            if self.spec is not None:
                self.spec.draft_prefill(
                    self.kv_draft, self._draft_params,
                    jnp.zeros((1, lp), jnp.int32),
                    jnp.asarray([lp], jnp.int32), slot0, self.kv.lengths,
                    self._finished, samp, self._next_key())
        nxt, _ = self.decoder.decode_step(
            self.kv, self._params, self._finished, self._last,
            self._samp_vecs, self._next_key())
        if self.spec is not None:
            # the spec step needs headroom for k+1 candidate rows; warmup
            # state after the bucket loop has lengths == largest bucket,
            # so reset first and trace against zeroed lengths
            self.kv.reset()
            self.kv_draft.reset()
            _, _, out = self.spec.step(
                self.kv, self.kv_draft, self._params, self._draft_params,
                jnp.zeros((self.config.num_slots,), jnp.bool_),
                jnp.zeros((self.config.num_slots,), jnp.int32),
                self._samp_vecs, self._next_key())
            out.block_until_ready()  # noqa: PTA002 -- warmup barrier: ensure compiles finish before serving starts
        nxt.block_until_ready()  # noqa: PTA002 -- warmup barrier: ensure compiles finish before serving starts
        self.kv.reset()
        if self.kv_draft is not None:
            self.kv_draft.reset()
        self._finished = jnp.zeros((self.config.num_slots,), jnp.bool_)
        self._last = jnp.zeros((self.config.num_slots,), jnp.int32)
        self._stat_set("warmup_ms", (self._clock() - t0) * 1000.0)


class LLMEngine(DrainableEngineBase):
    """submit()/drain() continuous-batching generation over one GPT model.

    Construction compiles (optionally) and starts the worker thread; from
    then on every decode tick reuses the one compiled step. Graceful
    drain — explicit, SIGTERM via :meth:`install_drain_signal_handler`,
    or preemption via :meth:`arm_preemption` — stops admission and
    finishes every in-flight AND queued sequence before the worker exits.
    """

    def __init__(self, model, config: Optional[LLMEngineConfig] = None,
                 registry: Optional[_mon.StatRegistry] = None,
                 cache: Optional[ExecutableCache] = None,
                 mesh=None, slot_axis: str = "model",
                 draft_model=None,
                 prefix_store: Optional[PrefixStore] = None):
        self._config = config or LLMEngineConfig()
        self._init_serving_base(registry, self._config.stat_prefix)
        # `is not None`, not truthiness: an empty ExecutableCache has
        # len() == 0 and is falsy, so `cache or ...` would drop it.
        # Default: the ONE process-wide cache (serving/cache.py) — the
        # LLM engine shares executables and counters with Predictors and
        # batch engines instead of holding a private per-engine cache.
        self._cache = cache if cache is not None else default_cache()
        if self._config.kv_layout == "paged":
            # lazy import: paged/batcher imports this module's classes
            from .paged import (GPTPagedDecoder, GPTPagedSpecDecoder,
                                PagedBatcher)
            if mesh is not None:
                raise NotImplementedError(
                    "kv_layout='paged' over a slot-sharded mesh is not "
                    "supported yet — use kv_layout='slot' with a mesh")
            if prefix_store is not None:
                raise NotImplementedError(
                    "paged engines share prefix pages inside their own "
                    "arena; an external PrefixStore cannot be attached "
                    "— set prefix_cache=True instead")
            self._decoder = GPTPagedDecoder(
                model, max_top_k=self._config.max_top_k,
                exec_cache=self._cache,
                weight_dtype=self._config.weight_dtype,
                kv_dtype=self._config.kv_dtype,
                page_size=self._config.page_size,
                num_pages=self._config.num_pages,
                attn_impl=self._config.paged_attn_impl)
            spec_decoder = None
            if self._config.spec_k > 0:
                if draft_model is None:
                    raise ValueError(
                        "spec_k > 0 requires a draft_model (the small "
                        "GPT that proposes candidate tokens)")
                spec_decoder = GPTPagedSpecDecoder(
                    self._decoder, draft_model, k=self._config.spec_k,
                    exec_cache=self._cache)
            self._batcher = PagedBatcher(
                self._decoder, self._config, self._registry,
                spec_decoder=spec_decoder)
            # the batcher builds its PagedPrefixStore (it needs the live
            # arena); surface it on the engine like the host store
            self._prefix_store = self._batcher.prefix_store
        else:
            self._decoder = GPTStaticDecoder(
                model, max_top_k=self._config.max_top_k,
                exec_cache=self._cache,
                mesh=mesh, slot_axis=slot_axis,
                weight_dtype=self._config.weight_dtype,
                kv_dtype=self._config.kv_dtype)
            # prefix reuse: an explicit store (the disaggregated fleet
            # shares ONE across replicas for the prefill->decode KV
            # handoff) enables it even when the config flag is off
            self._prefix_store = prefix_store
            if prefix_store is not None and self._config.kv_dtype == "int8":
                raise ValueError(
                    "a shared PrefixStore requires a dense KV cache "
                    "(kv_dtype='float32'): prefix export/insert moves raw "
                    "f32 rows between engines")
            if self._prefix_store is None and self._config.prefix_cache:
                self._prefix_store = PrefixStore(
                    capacity_bytes=int(
                        self._config.prefix_capacity_mb * (1 << 20)),
                    block_tokens=self._config.prefix_block,
                    registry=self._registry,
                    stat_prefix=f"{self._config.stat_prefix}.prefix")
            spec_decoder = None
            if self._config.spec_k > 0:
                if draft_model is None:
                    raise ValueError(
                        "spec_k > 0 requires a draft_model (the small GPT "
                        "that proposes candidate tokens)")
                spec_decoder = GPTSpecDecoder(
                    self._decoder, draft_model, k=self._config.spec_k,
                    exec_cache=self._cache)
            self._batcher = ContinuousBatcher(
                self._decoder, self._config, self._registry,
                prefix_store=self._prefix_store, spec_decoder=spec_decoder)
        self._queue = BatchQueue(max_size=self._config.max_queue)
        # between-tick control plane (docs/fault_tolerance.md "Zero-loss
        # serving"): closures queued here run ON the worker thread at the
        # top of its loop — never concurrent with a decode tick. The
        # sequence export/import paths ride this so migration can touch
        # batcher state without a lock on the hot path.
        self._ctl: "collections.deque" = collections.deque()
        #: crash-recovery journal; armed by :meth:`enable_recovery`
        self.journal = None
        self._evacuated: List[GenerationRequest] = []
        if self._config.warmup:
            self._batcher.warmup()
        self._worker = threading.Thread(
            target=self._worker_loop, name="paddle-tpu-llm-worker",
            daemon=True)
        self._worker.start()

    # -- public API ----------------------------------------------------------
    @property
    def config(self) -> LLMEngineConfig:
        return self._config

    @property
    def cache(self) -> ExecutableCache:
        return self._cache

    @property
    def decoder(self) -> GPTStaticDecoder:
        return self._decoder

    @property
    def prefix_store(self) -> Optional[PrefixStore]:
        return self._prefix_store

    @property
    def role(self) -> str:
        return self._config.role

    def submit(self, prompt, *, max_new_tokens: Optional[int] = None,
               do_sample: bool = False, temperature: float = 1.0,
               top_k: int = 0, eos_token_id: Optional[int] = None,
               deadline: Optional[Union[Deadline, float]] = None,
               stream: bool = False) -> GenerationRequest:
        """Enqueue one prompt; returns the :class:`GenerationRequest`
        (``.future`` for the full result, ``.iter_tokens()`` when
        ``stream=True``)."""
        if self._killed.is_set():
            self._stat_add("rejected_killed", 1)
            raise EngineKilled(
                f"engine was hard-killed ({self._kill_reason}); "
                f"submit rejected")
        if self._draining.is_set():
            self._stat_add("rejected_draining", 1)
            raise EngineDraining("engine is draining; submit rejected")
        if self._admission_paused.is_set():
            self._stat_add("rejected_paused", 1)
            raise EngineDraining(
                "engine admission is paused (fleet control); "
                "submit rejected")
        arr = np.asarray(prompt, dtype=np.int32).reshape(-1)  # noqa: PTA002 -- admission-time conversion of the caller's host-side prompt, not a device value
        if arr.size > self._config.max_prompt_len:
            self._stat_add("rejected_oversize", 1)
            raise RequestTooLarge(
                f"prompt of {arr.size} tokens exceeds max_prompt_len="
                f"{self._config.max_prompt_len} (largest prefill bucket "
                f"capped at max_seq-1)")
        if top_k > self._decoder.max_top_k:
            raise ValueError(
                f"top_k={top_k} exceeds the engine's compiled "
                f"max_top_k={self._decoder.max_top_k}")
        if max_new_tokens is None:
            max_new_tokens = self._config.default_max_new_tokens
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if deadline is None and self._config.default_deadline is not None:
            deadline = self._config.default_deadline
        if deadline is not None and not isinstance(deadline, Deadline):
            deadline = Deadline(float(deadline))
        samp = SamplingParams(
            do_sample=bool(do_sample), temperature=float(temperature),
            top_k=int(top_k), eos_token_id=eos_token_id,
            max_new_tokens=int(max_new_tokens))
        req = GenerationRequest(arr, samp, deadline=deadline, stream=stream)
        try:
            self._queue.put(req, block=self._config.admission_block,
                            timeout=self._config.admission_timeout)
        except Exception:
            self._stat_add("rejected_queue_full", 1)
            raise
        self._stat_set("queue_depth", len(self._queue))
        return req

    def generate(self, prompt, **kw) -> dict:
        """Synchronous convenience: submit + wait."""
        return self.submit(prompt, **kw).result()

    @property
    def weights_version(self) -> int:
        return self._batcher.weights_version

    def swap_weights(self, state_dict: dict, *, timeout: float = 30.0,
                     poll: float = 0.005) -> int:
        """Live weight hot-swap: install ``state_dict`` into the model and
        re-extract params, WITHOUT tearing down the engine or recompiling
        (the decode/prefill executables are keyed by spec + dtypes, not by
        parameter values, so the persistent cache serves them unchanged).

        The caller must :meth:`pause_admission` first; this method then
        waits until every in-flight slot retires and the queue is empty —
        the swap happens only on a quiesced engine, which is what makes it
        bitwise-safe: a generation is computed entirely by the old weights
        or entirely by the new ones, never a mix. Returns the new weights
        version (stamped into every subsequent request's result).
        """
        if not (self._admission_paused.is_set() or self._draining.is_set()):
            raise RuntimeError(
                "swap_weights requires pause_admission() first: in-flight "
                "sequences must quiesce before params change under them")
        deadline = time.monotonic() + timeout
        while self._batcher.active > 0 or len(self._queue) > 0:
            if self._killed.is_set():
                raise EngineKilled(
                    f"engine hard-killed ({self._kill_reason}) while "
                    f"quiescing for a weight swap")
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"engine did not quiesce within {timeout}s "
                    f"(active={self._batcher.active}, "
                    f"queued={len(self._queue)}); weight swap aborted")
            time.sleep(poll)
        # engine is quiesced AND admission is closed: the worker cannot
        # touch params (admit/tick need a request) until we finish, so
        # mutating the model + re-extracting here is single-writer
        with _otrace.span("serving.llm/weight_swap"):
            misses_before = self._cache.stats()["misses"]
            self._decoder.model.set_state_dict(state_dict)
            self._batcher.refresh_params()
            self._batcher.weights_version += 1
        self._stat_add("weight_swaps", 1)
        self._stat_set("weights_version", self._batcher.weights_version)
        _flight.record_event(
            "weight_swap",
            {"engine": self._prefix,
             "version": self._batcher.weights_version,
             "cache_misses_before": misses_before})
        return self._batcher.weights_version

    # -- zero-loss serving: migration + crash recovery -----------------------
    # (docs/fault_tolerance.md "Zero-loss serving")
    def _run_on_worker(self, fn, timeout: float = 30.0):
        """Run ``fn`` on the engine worker at the top of its next loop
        iteration — i.e. BETWEEN decode ticks, never concurrent with
        one. Blocks the caller until serviced; re-raises whatever ``fn``
        raised. A worker that exits first fails the call with
        :class:`EngineKilled` instead of hanging it."""
        if self._stopped.is_set():
            raise EngineKilled(
                f"engine worker already stopped "
                f"({self._kill_reason or 'drained'})")
        box: Dict[str, object] = {}
        ev = threading.Event()
        self._ctl.append((fn, box, ev))
        if not ev.wait(timeout):
            raise TimeoutError(
                f"engine worker did not service the control call within "
                f"{timeout}s")
        if "exc" in box:
            raise box["exc"]
        return box.get("ret")

    @property
    def supports_migration(self) -> bool:
        """True when live sequences can be exported/imported as page
        payloads — the paged KV substrate only (slot-layout engines
        still get crash recovery via journal replay)."""
        return bool(getattr(self._batcher, "supports_export", False))

    def export_sequences(self, *, timeout: float = 30.0) -> List:
        """Snapshot-and-detach every live sequence — plus the engine's
        still-queued backlog, shipped cold — into host-side
        :class:`~paddle_tpu.serving.fleet.migrate.SequenceManifest`
        objects. The caller (migrator) should have paused admission
        first. Runs on the worker between ticks; on return the engine
        holds none of the exported requests and their futures are still
        pending — ownership transfers to the caller."""
        if not self.supports_migration:
            raise NotImplementedError(
                "sequence export requires the paged KV cache "
                "(kv_layout='paged')")
        action = fault_injector().fire("seq_export")
        if action == "slow_io":
            time.sleep(float(os.environ.get(
                "PADDLE_TPU_FAULT_SLOW_IO_S", "1.0")))
        elif action is not None:
            raise RuntimeError(f"injected seq_export fault: {action}")
        from ..fleet.migrate import SequenceManifest

        def _export():
            mans = self._batcher.export_all()
            if len(self._queue):
                for req in self._queue.take_many(
                        len(self._queue), timeout=0.0):
                    mans.append(SequenceManifest.for_queued(req))
            return mans
        mans = self._run_on_worker(_export, timeout=timeout)
        self._stat_add("migrated_out", len(mans))
        self._stat_set("queue_depth", len(self._queue))
        return mans

    def import_sequence(self, manifest, *, timeout: float = 30.0) -> bool:
        """Splice a migrated sequence into this engine and resume it at
        the exact next token. Returns False when the engine cannot
        adopt it (manifest/weights-version mismatch, pool pressure,
        injected faults) — the migrator falls back to replay then."""
        if self._killed.is_set() or self._draining.is_set() \
                or self._stopped.is_set() or not self.supports_migration:
            return False
        from ..fleet.migrate import MANIFEST_VERSION
        if manifest.version != MANIFEST_VERSION or manifest.cold:
            return False
        if manifest.weights_version != self.weights_version:
            # KV computed under other weights must never continue under
            # these — the hot-swap bitwise contract is old OR new
            return False
        action = fault_injector().fire("seq_import")
        if action == "slow_io":
            time.sleep(float(os.environ.get(
                "PADDLE_TPU_FAULT_SLOW_IO_S", "1.0")))
        elif action is not None:
            return False
        ok = bool(self._run_on_worker(
            lambda: self._batcher.import_manifest(manifest),
            timeout=timeout))
        if ok:
            self._stat_add("migrated_in", 1)
        return ok

    def resubmit(self, req: GenerationRequest) -> bool:
        """Adopt a request that never started decoding on its donor (a
        migrated admission-queue entry): nothing was streamed, so it
        re-queues as if freshly submitted."""
        if self._killed.is_set() or self._draining.is_set() \
                or self._stopped.is_set():
            return False
        if req.tokens:       # defensive: partially-streamed → replay path
            return self.resubmit_for_recovery(req, req.tokens)
        self._queue.put(req, block=False)
        self._stat_set("queue_depth", len(self._queue))
        return True

    def resubmit_for_recovery(self, req: GenerationRequest,
                              resume_tokens) -> bool:
        """Adopt an evacuated request from a dead sibling by REPLAY:
        re-prefill ``original_prompt + resume_tokens`` (the journaled
        transcript, possibly a few tokens stale) and let the dedup
        guard verify-and-swallow the re-generated gap. Greedy streams
        come out bitwise-identical to an uninterrupted run; a sampled
        stream that diverges fails loudly instead of corrupting
        output."""
        if self._killed.is_set() or self._draining.is_set() \
                or self._stopped.is_set():
            return False
        resume = [int(t) for t in resume_tokens]
        n = min(len(resume), len(req.tokens))
        if resume[:n] != req.tokens[:n]:
            exc = TokenStreamDivergence(
                f"request {req.req_id}: journaled transcript diverges "
                f"from the client stream within the first {n} tokens")
            req.fail(exc)
            raise exc
        # the rebuilt prompt must stay admissible; shrinking the resume
        # point is always safe — the gap is re-generated and verified
        cap = self._config.max_prompt_len \
            - (req.prompt_len - req._resume_offset)
        req.begin_resume(max(0, min(n, cap)))
        self._queue.put(req, block=False)
        self._stat_add("recovered", 1)
        self._stat_set("queue_depth", len(self._queue))
        return True

    def enable_recovery(self, capacity: int = 1024):
        """Arm crash recovery (idempotent): the worker notes the live
        request set every tick into a :class:`~paddle_tpu.serving.
        fleet.migrate.SequenceJournal` (flushed off-thread), and a
        subsequent :meth:`kill` EVACUATES in-flight requests — futures
        left pending — instead of failing them, so the router can
        replay them onto survivors."""
        if self.journal is None:
            from ..fleet.migrate import SequenceJournal
            self.journal = SequenceJournal(
                capacity=capacity, registry=self._registry,
                stat_prefix=f"{self._prefix}.journal")
        return self.journal

    def take_evacuated(self) -> List[GenerationRequest]:
        """Hand over the requests the worker detached at kill time
        (futures still pending). Ownership transfers to the caller —
        anything not replayed or failed there would leak."""
        out, self._evacuated = self._evacuated, []
        return out

    def kill(self, reason: str = "killed") -> List[dict]:
        """Hard-kill, returning a snapshot record per affected request
        (id, phase, tokens emitted): queued requests fail retryably;
        in-flight generations are evacuated for replay when recovery is
        armed, aborted with :class:`EngineKilled` otherwise."""
        journaled = self.journal is not None
        inflight = [{"req_id": r.req_id, "phase": "decode",
                     "tokens": len(r.tokens), "evacuated": journaled}
                    for r in list(self._batcher._reqs.values())]
        return list(super().kill(reason)) + inflight

    def drain(self, timeout: Optional[float] = None) -> List:
        """Graceful drain: stop admission, finish every in-flight and
        queued sequence, stop the worker. Returns the requests that were
        in flight when the drain began (all resolved on return)."""
        inflight = list(self._batcher._reqs.values())
        self.begin_drain()
        self._stopped.wait(timeout)
        if self._signal_chain is not None:
            self._signal_chain.uninstall()
        if self.journal is not None:
            self.journal.close()
        self._stat_set("queue_depth", 0)
        return inflight

    close = drain

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.drain()
        return False

    def stats(self) -> dict:
        """Scalar stats + histogram summaries + cache counters + slot
        occupancy (the ``/statsz`` payload for the LLM engine)."""
        # NB: trailing dot — a bare startswith(self._prefix) would leak a
        # sibling engine's "serving.llm.replica1.*" counters into the
        # "serving.llm.replica0" payload (and vice versa) when several
        # in-process replicas share one registry.
        pre = self._prefix + "."
        return {
            "stats": self._registry.stats_with_prefix(pre),
            "histograms":
                self._registry.histograms_with_prefix(pre),
            "executable_cache": self._cache.stats(),
            "draining": self.draining,
            "queue_depth": len(self._queue),
            "slots": {"total": self._config.num_slots,
                      "in_use": self._batcher.active,
                      "free": self._batcher.free_slots},
            "role": self._config.role,
            "spec_k": self._config.spec_k,
            "prefix_store": (self._prefix_store.stats()
                             if self._prefix_store is not None else None),
            "kv_layout": self._config.kv_layout,
            "pages": ({"total": self._batcher.kv.pool.num_pages,
                       "free": self._batcher.kv.pool.free_pages,
                       "cow_splits": self._batcher.kv.cow_splits,
                       "pending": len(self._batcher._pending)}
                      if self._config.kv_layout == "paged" else None),
        }

    # -- worker --------------------------------------------------------------
    def _worker_loop(self):
        cfg = self._config
        try:
            while True:
                # between-tick control plane: migration export/import
                # closures run here, on the worker, never mid-tick
                while self._ctl:
                    fn, box, ev = self._ctl.popleft()
                    try:
                        box["ret"] = fn()
                    except BaseException as e:  # noqa: BLE001 -- boxed and re-raised on the calling thread
                        box["exc"] = e
                    finally:
                        ev.set()
                if self._killed.is_set():
                    # hard-kill: queued requests were failed by kill()
                    # itself. With recovery armed, in-flight sequences are
                    # EVACUATED (futures pending, for the router's replay);
                    # otherwise aborted as before. Either way this is a
                    # commanded death, not a worker crash, so no re-raise /
                    # no noisy daemon-thread traceback.
                    n = self._batcher.active
                    if self.journal is not None:
                        self._evacuated.extend(self._batcher.evacuate())
                    else:
                        self._batcher.abort_all(
                            lambda req: EngineKilled(
                                f"engine hard-killed ({self._kill_reason}) "
                                f"with request {req.req_id} in flight after "
                                f"{len(req.tokens)} tokens"))
                    _flight.record_event(
                        "engine_killed",
                        {"engine": self._prefix,
                         "reason": self._kill_reason,
                         "aborted": 0 if self.journal is not None else n,
                         "evacuated": n if self.journal is not None else 0})
                    return
                if self._guard is not None and self._guard.preempted \
                        and not self._draining.is_set():
                    self._stat_add("preemption_drains", 1)
                    self.begin_drain()
                elif self._draining.is_set() and not self._queue.closed:
                    # flag set by the async-signal-safe handler; complete
                    # the drain outside signal context
                    self._queue.close()
                free = self._batcher.free_slots
                if free > 0:
                    timeout = 0.0 if self._batcher.active else cfg.idle_poll
                    for req in self._queue.take_many(free, timeout=timeout):
                        self._batcher.admit(req)
                self._stat_set("queue_depth", len(self._queue))
                self._stat_set("deadline_evicted_queued",
                               self._queue.evicted_expired)
                self._stat_set("slots_in_use", self._batcher.active)
                if self._batcher.active:
                    self._batcher.tick()
                    if self.journal is not None and self._batcher.active:
                        # O(1) reference enqueue; the journal's flush
                        # thread does the copying (async-dispatch
                        # discipline: the tick never pays for durability)
                        self.journal.note(self._batcher._reqs.values())
                elif self._draining.is_set() and len(self._queue) == 0:
                    break
                self._publish_cache_stats()
        except BaseException as e:  # worker death must not strand futures
            _flight.record_event(
                "llm_worker_death",
                {"error": f"{type(e).__name__}: {e}",
                 "active": self._batcher.active,
                 "queued": len(self._queue)})
            _flight.dump_if_armed("llm_worker_death")
            self._batcher.abort_all(
                lambda req, e=e: RuntimeError(
                    f"LLM worker died while request {req.req_id} was in "
                    f"flight: {e!r}"))
            raise
        finally:
            # unblock any control-plane caller racing the worker's exit
            while self._ctl:
                fn, box, ev = self._ctl.popleft()
                box["exc"] = EngineKilled(
                    "engine worker exited before servicing the control "
                    "call")
                ev.set()
            if self._drain_signaled:
                _flight.record_event("sigterm_drain",
                                     {"engine": self._prefix})
                _flight.dump_if_armed("sigterm_drain")
            self._stopped.set()

    def _publish_cache_stats(self):
        s = self._cache.stats()
        self._stat_set("cache.hits", s["hits"])
        self._stat_set("cache.misses", s["misses"])
        self._stat_set("recompiles", s["misses"])
