"""Paged KV cache: block-table memory manager, paged attention, COW
prefix sharing (docs/serving.md "Paged KV cache").

The vLLM/PagedAttention design grafted under the static-slot LLM stack:
``pool`` owns the page arena + refcounted free list, ``decode``/``spec``
mirror the slot decode programs with the block table threaded through,
``prefix`` shares prefix pages by refcount (COW on divergence), and
``batcher`` admits on pages-at-current-lengths. Select with
``LLMEngineConfig(kv_layout="paged")``.
"""
from .batcher import PagedBatcher
from .decode import (GPTPagedDecoder, build_paged_decode_step,
                     build_paged_prefill_fn, build_paged_tail_prefill_fn,
                     get_paged_decode_step, get_paged_prefill_fn,
                     get_paged_tail_prefill_fn)
from .pool import (PagedKVCache, PagePool, PagesExhausted,
                   paged_gather_rows, paged_write_prompt_rows,
                   paged_write_rows, pages_for_tokens)
from .prefix import PagedPrefixEntry, PagedPrefixStore
from .spec import (GPTPagedSpecDecoder, build_paged_spec_decode_step,
                   get_paged_spec_decode_step)

__all__ = [
    "PagePool",
    "PagedKVCache",
    "PagesExhausted",
    "pages_for_tokens",
    "paged_write_rows",
    "paged_write_prompt_rows",
    "paged_gather_rows",
    "build_paged_decode_step",
    "build_paged_prefill_fn",
    "build_paged_tail_prefill_fn",
    "get_paged_decode_step",
    "get_paged_prefill_fn",
    "get_paged_tail_prefill_fn",
    "GPTPagedDecoder",
    "build_paged_spec_decode_step",
    "get_paged_spec_decode_step",
    "GPTPagedSpecDecoder",
    "PagedPrefixEntry",
    "PagedPrefixStore",
    "PagedBatcher",
]
