"""Paged prefill + decode step builders and the GPTPagedDecoder façade.

Same contracts as ``serving/llm/decode.py`` with ONE extra device input
threaded through every program: the ``[num_slots, pages_per_seq]`` block
table. The forward math is untouched — only where K/V rows live changes
(scatter into the page arena instead of ``dynamic_update_slice`` into a
slot row; gather back through the block table instead of reading the
slot row directly).

Bitwise parity with the slot path (the acceptance contract): the cache
enforces ``max_seq % page_size == 0``, so ``paged_gather_rows``
reconstructs a ``[S, max_seq, H, D]`` tensor shape-identical to a slot
buffer's layer view. Valid rows hold identical values (same projections,
same int8 quantization granularity), junk rows differ but carry the same
``-1e9`` additive mask, whose softmax weight is exactly 0.0 in f32 —
identical shapes, identical reduction order, bitwise-equal logits. The
greedy-lane parity test pins it.

Two attention implementations sit behind ``attn_impl``:

- ``"gather"`` — materialize the gathered rows in-graph and run the
  slot path's exact matmul/softmax (the parity lane; default off-TPU).
- ``"kernel"`` — the Pallas paged-attention kernel
  (``ops/paged_attention.py``) walks the block table inside the grid,
  never materializing the gather (the TPU fast path; float-equal, not
  bitwise — blocked online-softmax sums in a different order).
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..decode import (GPTDecodeSpec, GPTStaticDecoder, _AUDIT_SPEC,
                      _AUDIT_TOP_K, _audit_params, _block_prefill,
                      _layer_norm, _mm, _sample)
from ..kvcache import (dequantize_kv, is_quantized_kv, kv_layer_view,
                       kv_stack_layers, valid_mask)
from .pool import (PagedKVCache, paged_gather_rows,
                   paged_write_prompt_rows, paged_write_rows,
                   pages_for_tokens)


def _write_page_index(block_tables, positions, page_size):
    """(physical page, in-page offset) of each slot's write position.
    Out-of-range positions (inactive slots whose lengths keep advancing)
    clip to the last table entry, which for a freed slot is the trash
    page — the paged analogue of the slot path's clamped
    ``dynamic_update_slice`` on inactive rows."""
    idx = jnp.clip(positions // page_size, 0,
                   block_tables.shape[1] - 1)
    pid = jnp.take_along_axis(block_tables, idx[:, None], axis=1)[:, 0]
    return pid, positions % page_size


def _paged_block_decode(spec, lp, h, kb, vb, block_tables, pid, ppos,
                        positions, mask, scale, attn_impl):
    """One pre-norm block for a single new token per slot — the paged
    twin of ``decode._block_decode``. ``kb``/``vb``: this layer's
    ``[P+1, page, H, D]`` arena view; the token's K/V is scattered at
    (``pid``, ``ppos``) before attending."""
    s = h.shape[0]
    x = _layer_norm(h, lp["n1w"], lp["n1b"], spec.ln_epsilon)
    q = (_mm(x, lp["qw"]) + lp["qb"]).reshape(s, spec.num_heads,
                                              spec.head_dim)
    kn = (_mm(x, lp["kw"]) + lp["kb"]).reshape(s, spec.num_heads,
                                               spec.head_dim)
    vn = (_mm(x, lp["vw"]) + lp["vb"]).reshape(s, spec.num_heads,
                                               spec.head_dim)
    kb = paged_write_rows(kb, kn, pid, ppos)
    vb = paged_write_rows(vb, vn, pid, ppos)
    if attn_impl == "kernel":
        from ....ops.paged_attention import paged_attention
        out = paged_attention(q, kb, vb, block_tables, positions,
                              scale=scale).reshape(s, spec.hidden_size)
    else:
        kd = dequantize_kv(paged_gather_rows(kb, block_tables), h.dtype)
        vd = dequantize_kv(paged_gather_rows(vb, block_tables), h.dtype)
        qh = (q * scale)[:, :, None, :]                   # [S, H, 1, D]
        kt = jnp.transpose(kd, (0, 2, 1, 3))              # [S, H, max, D]
        vt = jnp.transpose(vd, (0, 2, 1, 3))
        prod = jnp.matmul(qh, jnp.swapaxes(kt, -1, -2))   # [S, H, 1, max]
        weights = jax.nn.softmax(prod + mask, axis=-1)
        out = jnp.matmul(weights, vt)                     # [S, H, 1, D]
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(s,
                                                       spec.hidden_size)
    h = h + (_mm(out, lp["ow"]) + lp["ob"])
    x = _layer_norm(h, lp["n2w"], lp["n2b"], spec.ln_epsilon)
    ffn = jax.nn.gelu(_mm(x, lp["w1"]) + lp["b1"], approximate=False)
    return h + (_mm(ffn, lp["w2"]) + lp["b2"]), kb, vb


# -- the compiled programs ---------------------------------------------------

def build_paged_decode_step(spec: GPTDecodeSpec, max_top_k: int,
                            page_size: int, attn_impl: str = "gather"):
    """The RAW (un-jitted) paged decode step — the auditable program
    (PTA009 entrypoint ``llm_paged_decode_step``).

    step(params, kbuf, vbuf, block_tables, lengths, finished,
         last_tokens, temperature, top_k, do_sample, eos, key)
      -> (kbuf, vbuf, lengths+1, finished, next_tokens)

    The block table is read-only inside the step (page mapping is host
    policy, applied between ticks); arenas flow through functionally.
    """
    if attn_impl not in ("gather", "kernel"):
        raise ValueError(f"attn_impl must be 'gather' or 'kernel', got "
                         f"{attn_impl!r}")
    scale = 1.0 / np.sqrt(spec.head_dim)
    max_pos = spec.max_position_embeddings

    def _step(params, kbuf, vbuf, block_tables, lengths, finished,
              last_tokens, temperature, top_k, do_sample, eos, key):
        max_seq = block_tables.shape[1] * page_size
        positions = lengths                   # write position per slot
        posc = jnp.clip(positions, 0, max_pos - 1)
        h = params["tok"][last_tokens] + params["pos"][posc]      # [S, E]
        mask = (valid_mask(positions, max_seq, h.dtype)
                if attn_impl == "gather" else None)
        pid, ppos = _write_page_index(block_tables, positions, page_size)
        new_k, new_v = [], []
        for li, lp in enumerate(params["layers"]):
            h, kb, vb = _paged_block_decode(
                spec, lp, h, kv_layer_view(kbuf, li),
                kv_layer_view(vbuf, li), block_tables, pid, ppos,
                positions, mask, scale, attn_impl)
            new_k.append(kb)
            new_v.append(vb)
        kbuf = kv_stack_layers(new_k)
        vbuf = kv_stack_layers(new_v)
        h = _layer_norm(h, params["fnw"], params["fnb"], spec.ln_epsilon)
        lraw = (h @ params["tok"].T).astype(jnp.float32)          # [S, V]
        nxt = _sample(lraw, temperature, top_k, do_sample, key, max_top_k)
        nxt = jnp.where(finished & (eos >= 0), eos, nxt)
        finished = finished | ((nxt == eos) & (eos >= 0))
        return kbuf, vbuf, lengths + 1, finished, nxt

    return _step


@functools.lru_cache(maxsize=64)
def get_paged_decode_step(spec: GPTDecodeSpec, max_top_k: int,
                          page_size: int, attn_impl: str):
    """Jitted paged decode step; ``trace_counter`` contract matches
    ``get_decode_step`` (one trace per (num_pages, num_slots) shape)."""
    counter = {"traces": 0}
    raw = build_paged_decode_step(spec, max_top_k, page_size, attn_impl)

    def _step(*args):
        counter["traces"] += 1
        return raw(*args)

    fn = jax.jit(_step)
    fn.trace_counter = counter
    return fn


def build_paged_prefill_fn(spec: GPTDecodeSpec, max_top_k: int,
                           page_size: int):
    """The RAW paged prefill: identical forward math to
    ``build_prefill_fn`` (so the sampled first token is bitwise equal);
    the K/V rows scatter through each request's block-table row, with
    right-padding junk routed to the trash page instead of parked past
    the slot length."""
    scale = 1.0 / np.sqrt(spec.head_dim)

    def _prefill(params, tokens, true_lens, kbuf, vbuf, block_tables,
                 lengths, finished, slot_ids, temperature, top_k,
                 do_sample, eos, key):
        b, lp_len = tokens.shape
        trash = jax.tree_util.tree_leaves(kbuf)[0].shape[0] - 1
        pos = jnp.arange(lp_len, dtype=jnp.int32)
        h = params["tok"][tokens] + params["pos"][pos][None]   # [B, L, E]
        mask = jnp.triu(jnp.full((lp_len, lp_len), -1e9, h.dtype),
                        1)[None, None]
        kcs, vcs = [], []
        for lp in params["layers"]:
            h, k, v = _block_prefill(spec, lp, h, mask, scale)
            kcs.append(k)
            vcs.append(v)
        k_new = jnp.stack(kcs, axis=1)                 # [B, L, Lp, H, D]
        v_new = jnp.stack(vcs, axis=1)
        ppos = pos % page_size
        page_idx = pos // page_size                    # < PP: buckets
        for i in range(b):                             # fit in max_seq
            bt_row = block_tables[slot_ids[i]]         # [PP]
            pid = jnp.where(pos < true_lens[i], bt_row[page_idx], trash)
            kbuf = paged_write_prompt_rows(
                kbuf, jnp.transpose(k_new[i], (1, 0, 2, 3)), pid, ppos)
            vbuf = paged_write_prompt_rows(
                vbuf, jnp.transpose(v_new[i], (1, 0, 2, 3)), pid, ppos)
        lengths = lengths.at[slot_ids].set(true_lens)
        h = _layer_norm(h, params["fnw"], params["fnb"], spec.ln_epsilon)
        last = jnp.take_along_axis(
            h, (true_lens - 1)[:, None, None].astype(jnp.int32),
            axis=1)[:, 0]                                      # [B, E]
        lraw = (last @ params["tok"].T).astype(jnp.float32)
        nxt = _sample(lraw, temperature, top_k, do_sample, key, max_top_k)
        finished = finished.at[slot_ids].set((nxt == eos) & (eos >= 0))
        return kbuf, vbuf, lengths, finished, nxt

    return _prefill


@functools.lru_cache(maxsize=64)
def get_paged_prefill_fn(spec: GPTDecodeSpec, max_top_k: int,
                         page_size: int):
    counter = {"traces": 0}
    raw = build_paged_prefill_fn(spec, max_top_k, page_size)

    def _prefill(*args):
        counter["traces"] += 1
        return raw(*args)

    fn = jax.jit(_prefill)
    fn.trace_counter = counter
    return fn


def build_paged_tail_prefill_fn(spec: GPTDecodeSpec, max_top_k: int,
                                page_size: int):
    """The RAW paged *tail* prefill — prefill a prompt suffix into a
    slot whose first ``starts[i]`` rows arrived as SHARED prefix pages
    (block-table splices, zero bytes copied — contrast the slot path,
    which bulk-copied them first). Attention gathers the slot's full
    logical row (shared pages + the fresh tail spliced in) under the
    same offset-causal mask, so the first sampled token is bitwise what
    a full prefill would produce."""
    scale = 1.0 / np.sqrt(spec.head_dim)
    max_pos = spec.max_position_embeddings

    def _tail(params, tokens, tail_lens, starts, kbuf, vbuf,
              block_tables, lengths, finished, slot_ids, temperature,
              top_k, do_sample, eos, key):
        if is_quantized_kv(kbuf):
            raise NotImplementedError(
                "tail prefill (prefix reuse) over int8 pages is "
                "unsupported; LLMEngineConfig gates prefix_cache off "
                "for kv_dtype='int8'")
        b, lt = tokens.shape
        pp_n = block_tables.shape[1]
        max_seq = pp_n * page_size
        trash = kbuf.shape[0] - 1
        pos = starts[:, None] + jnp.arange(lt, dtype=jnp.int32)[None]
        posc = jnp.clip(pos, 0, max_pos - 1)
        h = params["tok"][tokens] + params["pos"][posc]    # [B, Lt, E]
        j = jnp.arange(max_seq, dtype=jnp.int32)[None, None]
        mask = jnp.where(j <= pos[:, :, None], 0.0,
                         -1e9).astype(h.dtype)[:, None]    # [B,1,Lt,max]
        bt_sel = block_tables[slot_ids]                    # [B, PP]
        kcs, vcs = [], []
        for li, lp in enumerate(params["layers"]):
            x = _layer_norm(h, lp["n1w"], lp["n1b"], spec.ln_epsilon)

            def heads(t):
                return t.reshape(b, lt, spec.num_heads, spec.head_dim)

            q = heads(_mm(x, lp["qw"]) + lp["qb"])
            kn = heads(_mm(x, lp["kw"]) + lp["kb"])
            vn = heads(_mm(x, lp["vw"]) + lp["vb"])
            # attention reads the gathered logical rows with the fresh
            # tail spliced in; the arenas are written once, after the
            # layer loop
            row_k = paged_gather_rows(kv_layer_view(kbuf, li), bt_sel)
            row_v = paged_gather_rows(kv_layer_view(vbuf, li), bt_sel)

            def _splice(row, new, st):
                return jax.lax.dynamic_update_slice(row, new, (st, 0, 0))

            row_k = jax.vmap(_splice)(row_k, kn, starts)
            row_v = jax.vmap(_splice)(row_v, vn, starts)
            qh = jnp.transpose(q * scale, (0, 2, 1, 3))    # [B,H,Lt,D]
            kt = jnp.transpose(row_k, (0, 2, 1, 3))        # [B,H,max,D]
            vt = jnp.transpose(row_v, (0, 2, 1, 3))
            prod = jnp.matmul(qh, jnp.swapaxes(kt, -1, -2))
            weights = jax.nn.softmax(prod + mask, axis=-1)
            out = jnp.matmul(weights, vt)                  # [B,H,Lt,D]
            out = jnp.transpose(out, (0, 2, 1, 3)).reshape(
                b, lt, spec.hidden_size)
            h = h + (_mm(out, lp["ow"]) + lp["ob"])
            x = _layer_norm(h, lp["n2w"], lp["n2b"], spec.ln_epsilon)
            ffn = jax.nn.gelu(_mm(x, lp["w1"]) + lp["b1"],
                              approximate=False)
            h = h + (_mm(ffn, lp["w2"]) + lp["b2"])
            kcs.append(kn)
            vcs.append(vn)
        k_new = jnp.stack(kcs, axis=1)                 # [B, L, Lt, H, D]
        v_new = jnp.stack(vcs, axis=1)
        t = jnp.arange(lt, dtype=jnp.int32)
        for i in range(b):
            pos_i = starts[i] + t
            page_idx = jnp.clip(pos_i // page_size, 0, pp_n - 1)
            pid = jnp.where(t < tail_lens[i], bt_sel[i][page_idx], trash)
            kbuf = paged_write_prompt_rows(
                kbuf, jnp.transpose(k_new[i], (1, 0, 2, 3)), pid,
                pos_i % page_size)
            vbuf = paged_write_prompt_rows(
                vbuf, jnp.transpose(v_new[i], (1, 0, 2, 3)), pid,
                pos_i % page_size)
        lengths = lengths.at[slot_ids].set(starts + tail_lens)
        h = _layer_norm(h, params["fnw"], params["fnb"], spec.ln_epsilon)
        last = jnp.take_along_axis(
            h, (tail_lens - 1)[:, None, None].astype(jnp.int32),
            axis=1)[:, 0]                                      # [B, E]
        lraw = (last @ params["tok"].T).astype(jnp.float32)
        nxt = _sample(lraw, temperature, top_k, do_sample, key, max_top_k)
        finished = finished.at[slot_ids].set((nxt == eos) & (eos >= 0))
        return kbuf, vbuf, lengths, finished, nxt

    return _tail


@functools.lru_cache(maxsize=64)
def get_paged_tail_prefill_fn(spec: GPTDecodeSpec, max_top_k: int,
                              page_size: int):
    counter = {"traces": 0}
    raw = build_paged_tail_prefill_fn(spec, max_top_k, page_size)

    def _tail(*args):
        counter["traces"] += 1
        return raw(*args)

    fn = jax.jit(_tail)
    fn.trace_counter = counter
    return fn


class GPTPagedDecoder(GPTStaticDecoder):
    """GPTStaticDecoder with the KV substrate swapped for pages: same
    model façade, same ExecutableCache accounting, but ``new_kv``
    returns a :class:`PagedKVCache` and every compiled program threads
    its block table. ``attn_impl``: ``"auto"`` picks the Pallas kernel
    on TPU (dense arenas) and the gather lane elsewhere."""

    kv_layout = "paged"

    def __init__(self, model, max_top_k: int = 64, exec_cache=None,
                 mesh=None, slot_axis: str = "model",
                 weight_dtype: str = "float32",
                 kv_dtype: str = "float32", page_size: int = 16,
                 num_pages: Optional[int] = None,
                 attn_impl: str = "auto"):
        if mesh is not None:
            raise NotImplementedError(
                "paged KV over a slot-sharded mesh is not supported yet "
                "— the arena would need a page-granular GSPMD "
                "partitioning; use kv_layout='slot' with a mesh")
        super().__init__(model, max_top_k=max_top_k,
                         exec_cache=exec_cache, mesh=None,
                         slot_axis=slot_axis, weight_dtype=weight_dtype,
                         kv_dtype=kv_dtype)
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if attn_impl not in ("auto", "gather", "kernel"):
            raise ValueError(
                f"attn_impl must be 'auto', 'gather' or 'kernel', got "
                f"{attn_impl!r}")
        if attn_impl == "kernel" and kv_dtype == "int8":
            raise ValueError(
                "the paged kernel lane reads dense arenas; int8 pages "
                "use attn_impl='gather' (dequantize in-graph)")
        if attn_impl == "auto":
            on_tpu = jax.devices()[0].platform == "tpu"
            attn_impl = ("kernel" if on_tpu and kv_dtype != "int8"
                         else "gather")
        self.attn_impl = attn_impl
        self.page_size = int(page_size)
        self.num_pages = None if num_pages is None else int(num_pages)
        self._key = self._key + ("paged", self.page_size, self.attn_impl)

    def new_kv(self, num_slots: int, max_seq: int) -> PagedKVCache:
        if max_seq > self.spec.max_position_embeddings:
            raise ValueError(
                f"max_seq {max_seq} exceeds the model's "
                f"{self.spec.max_position_embeddings} positions")
        dtype = self._model.gpt.word_embeddings.weight._data.dtype
        return PagedKVCache(num_slots, self.spec.num_layers, max_seq,
                            self.spec.num_heads, self.spec.head_dim,
                            dtype=dtype,
                            kv_dtype=("int8" if self.kv_dtype == "int8"
                                      else None),
                            page_size=self.page_size,
                            num_pages=self.num_pages)

    # -- compiled-program access --------------------------------------------
    def decode_fn(self, num_slots: int, max_seq: int):
        return self.exec_cache.get_or_compile(
            self._key + ("decode", num_slots, max_seq),
            lambda: get_paged_decode_step(self.spec, self.max_top_k,
                                          self.page_size, self.attn_impl))

    def prefill_fn(self, batch: int, prompt_len: int):
        return self.exec_cache.get_or_compile(
            self._key + ("prefill", batch, prompt_len),
            lambda: get_paged_prefill_fn(self.spec, self.max_top_k,
                                         self.page_size))

    def tail_prefill_fn(self, batch: int, tail_len: int):
        return self.exec_cache.get_or_compile(
            self._key + ("tail_prefill", batch, tail_len),
            lambda: get_paged_tail_prefill_fn(self.spec, self.max_top_k,
                                              self.page_size))

    def insert_prefix_fn(self, prefix_len: int):
        raise NotImplementedError(
            "paged prefix reuse shares pages via the block table "
            "(PagedPrefixStore) — there is no bulk copy to compile")

    def insert_prefix(self, kv, k_pre, v_pre, slot: int):
        raise NotImplementedError(
            "paged prefix reuse shares pages via the block table "
            "(PagedPrefixStore.lookup + PagedKVCache.adopt_shared_page)"
            " — bulk-copying would defeat the zero-copy contract")

    def prefix_sig(self, kv: PagedKVCache):
        """Paged prefix entries are page-id lists into THIS cache's
        arena, so the signature also pins the page size (a different
        page size re-buckets every row)."""
        return (self.spec.num_layers, self.spec.num_heads,
                self.spec.head_dim, str(kv.dtype), self.page_size)

    # -- convenience wrappers (same signatures as the slot decoder) ----------
    def prefill(self, kv: PagedKVCache, params, tokens, true_lens,
                slot_ids, finished, samp_vecs, key):
        fn = self.prefill_fn(tokens.shape[0], tokens.shape[1])
        k, v, lengths, finished, nxt = fn(
            params, tokens, true_lens, kv.k, kv.v, kv.block_tables,
            kv.lengths, finished, slot_ids, *samp_vecs, key)
        kv.swap(k, v, lengths)
        return nxt, finished

    def tail_prefill(self, kv: PagedKVCache, params, tokens, tail_lens,
                     starts, slot_ids, finished, samp_vecs, key):
        if kv.quantized:
            raise NotImplementedError(
                "tail_prefill over int8 pages is unsupported; "
                "LLMEngineConfig gates prefix_cache off for "
                "kv_dtype='int8'")
        fn = self.tail_prefill_fn(tokens.shape[0], tokens.shape[1])
        k, v, lengths, finished, nxt = fn(
            params, tokens, tail_lens, starts, kv.k, kv.v,
            kv.block_tables, kv.lengths, finished, slot_ids, *samp_vecs,
            key)
        kv.swap(k, v, lengths)
        return nxt, finished

    def decode_step(self, kv: PagedKVCache, params, finished,
                    last_tokens, samp_vecs, key):
        fn = self.decode_fn(kv.num_slots, kv.max_seq)
        k, v, lengths, finished, nxt = fn(
            params, kv.k, kv.v, kv.block_tables, kv.lengths, finished,
            last_tokens, *samp_vecs, key)
        kv.swap(k, v, lengths)
        return nxt, finished

    # -- live sequence migration (docs/fault_tolerance.md) -------------------
    def export_sequence(self, kv: PagedKVCache, slot: int, n_tokens: int):
        """Snapshot the device half of a live sequence: host copies of
        the arena pages backing logical rows ``[0, n_tokens)``. Returns
        ``(page_ids, k_pages, v_pages)`` — the payload the migrator
        wraps into a :class:`~paddle_tpu.serving.fleet.migrate.
        SequenceManifest`. The sampling/progress half (tokens, RNG
        discipline, position) is host-derivable and assembled by the
        batcher; only the KV rows need a device fetch. Runs between
        decode ticks (engine worker), never inside one."""
        n_pages = pages_for_tokens(n_tokens, self.page_size)
        pids = kv.slot_page_ids(slot)[:n_pages]
        if len(pids) < n_pages:
            raise ValueError(
                f"slot {slot} maps {len(pids)} pages but {n_pages} are "
                f"needed for {n_tokens} cached tokens")
        k_pages, v_pages = kv.read_pages(pids)
        return pids, k_pages, v_pages

    def import_sequence(self, kv: PagedKVCache, slot: int, n_tokens: int,
                        k_pages, v_pages, shared_pages: int = 0):
        """Splice an exported sequence into ``slot``: pages
        ``[0, shared_pages)`` were already adopted zero-copy from this
        engine's prefix store (the chain-hash path); the remaining tail
        pages are allocated here and filled from the shipped payload.
        Installs the resume position so the next decode tick writes the
        exact next token."""
        total = pages_for_tokens(n_tokens, self.page_size)
        if not (0 <= shared_pages <= total):
            raise ValueError(
                f"shared_pages {shared_pages} out of range for "
                f"{total} total pages")
        kv.ensure_pages(slot, n_tokens)
        pids = kv.slot_page_ids(slot)
        tmap = jax.tree_util.tree_map
        for i in range(shared_pages, total):
            kv.write_page(pids[i],
                          tmap(lambda x, i=i: x[i], k_pages),
                          tmap(lambda x, i=i: x[i], v_pages))
        kv.set_length(slot, n_tokens)
        return total - shared_pages


# -- trace-audit registration (tools/analyze/trace, PTA009/PTA012) -----------

def _audit_paged_decode_spec():
    """Tiny paged geometry: 2 slots, max_seq 16 over 4-token pages, an
    8-page pool (+trash), both block tables fully pre-mapped. Proves the
    paged tick stays one fused zero-host-transfer program — the block
    table rides as a device input, never as host control flow."""
    from ....core import audit
    spec = _AUDIT_SPEC
    slots, page, phys = 2, 4, 8

    def make_args(variant):
        rng = np.random.default_rng(8642 + variant)
        arena = (phys + 1, spec.num_layers, page, spec.num_heads,
                 spec.head_dim)
        return (_audit_params(rng),
                jnp.zeros(arena, jnp.float32),
                jnp.zeros(arena, jnp.float32),
                jnp.asarray([[0, 1, 2, 3], [4, 5, 6, 7]], jnp.int32),
                jnp.asarray([3, 1], jnp.int32),           # lengths
                jnp.zeros((slots,), bool),                # finished
                jnp.asarray(rng.integers(0, spec.vocab_size, slots),
                            jnp.int32),                   # last_tokens
                jnp.ones((slots,), jnp.float32),          # temperature
                jnp.zeros((slots,), jnp.int32),           # top_k
                jnp.zeros((slots,), bool),                # do_sample
                jnp.full((slots,), -1, jnp.int32),        # eos
                jax.random.PRNGKey(variant))
    return audit.AuditSpec(
        fn=build_paged_decode_step(spec, _AUDIT_TOP_K, 4, "gather"),
        make_args=make_args)


def _register_audit_entrypoints():
    from ....core import audit
    audit.register_entrypoint("llm_paged_decode_step",
                              _audit_paged_decode_spec,
                              tags=("serving", "decode", "paged",
                                    "bench"))


_register_audit_entrypoints()
