"""PagePool + block tables: the paged KV memory substrate.

StaticKVCache gives every sequence a full ``[layers, max_seq, H, D]``
slot row for its whole lifetime — a 64-token chat in a 32k-max-seq fleet
wastes 99.8% of its reservation. This module rebuilds the substrate on
the vLLM/PagedAttention design: K and V live in ONE preallocated arena
of fixed-size token *pages*,

    arena[k|v] : [num_pages + 1, num_layers, page_size, H, D]

and each sequence owns a *block table* — a ``[pages_per_seq]`` int32
device row mapping logical page index -> physical arena page. Logical
row ``t`` of a sequence lives at ``arena[bt[t // page_size], :,
t % page_size]``. Pages are ref-counted on the host (a page shared by a
cached prefix and two live sequences has refcount 3), so prefix reuse is
a block-table splice (zero copied bytes) and divergence is a single-page
copy-on-write, not a whole-prefix copy.

The LAST physical page (index ``num_pages``) is the **trash page**: the
block tables of freed/unused slots point at it, and right-padded prefill
junk rows are routed to it, so every compiled program can write
unconditionally on uniform shapes (the LazyTensor one-program
discipline) while unmapped logical rows never corrupt live pages.
Whatever lands in the trash page is garbage by construction and every
read of it is masked by the per-slot length vector.

Host bookkeeping (free list, refcounts) mirrors StaticKVCache's slot
lifecycle: device arrays are only ever *replaced* by functional step
outputs; ``alloc``/``release`` never touch the device beyond the O(1)
block-table entry updates, which are jitted scalar scatters.
"""
from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..kvcache import (SlotsExhausted, is_quantized_kv, kv_nbytes,
                       quantize_kv_rows)


class PagesExhausted(RuntimeError):
    """The pool cannot satisfy an allocation (callers should gate on
    :attr:`PagePool.free_pages` / evict before hitting this)."""


class PagePool:
    """Host-side free list + per-page refcounts over the physical pages.

    A page is *free* when its refcount is 0. ``alloc`` hands out the
    lowest free index (deterministic tests) at refcount 1; ``retain``
    adds a sharer; ``release`` drops one reference and returns the page
    to the free list when the count hits zero. Releasing a free page
    raises — the page-level double-free guard the leak tests pin.
    """

    def __init__(self, num_pages: int):
        if num_pages < 1:
            raise ValueError(f"need num_pages >= 1, got {num_pages}")
        self.num_pages = int(num_pages)
        self._refs = np.zeros(self.num_pages, np.int64)
        self._free: List[int] = list(range(self.num_pages))
        heapq.heapify(self._free)
        #: lifetime counters — the leak invariant is
        #: ``total_allocs + total_retains == total_releases`` once every
        #: sequence/prefix-entry is gone (pages_in_use == 0)
        self.total_allocs = 0
        self.total_retains = 0
        self.total_releases = 0
        self.peak_in_use = 0

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    def refcount(self, pid: int) -> int:
        return int(self._refs[pid])

    def alloc_many(self, n: int) -> List[int]:
        """Claim ``n`` fresh pages (refcount 1 each), atomically: either
        all ``n`` allocate or none do and :class:`PagesExhausted` is
        raised — a partial allocation would leak on the error path."""
        if n < 0:
            raise ValueError(f"alloc_many({n})")
        if n > len(self._free):
            raise PagesExhausted(
                f"need {n} pages, only {len(self._free)} of "
                f"{self.num_pages} free")
        out = [heapq.heappop(self._free) for _ in range(n)]
        for pid in out:
            self._refs[pid] = 1
        self.total_allocs += n
        self.peak_in_use = max(self.peak_in_use, self.pages_in_use)
        return out

    def alloc(self) -> int:
        return self.alloc_many(1)[0]

    def retain(self, pid: int):
        """Add a reference to an already-live page (prefix sharing)."""
        if not (0 <= pid < self.num_pages):
            raise ValueError(f"retain of non-pool page {pid}")
        if self._refs[pid] <= 0:
            raise ValueError(f"retain of free page {pid}")
        self._refs[pid] += 1
        self.total_retains += 1

    def release(self, pid: int) -> bool:
        """Drop one reference; returns True when the page went back to
        the free list. Raises on over-release (page double-free)."""
        if not (0 <= pid < self.num_pages):
            raise ValueError(f"release of non-pool page {pid}")
        if self._refs[pid] <= 0:
            raise ValueError(
                f"page {pid} double-free: released with refcount 0")
        self._refs[pid] -= 1
        self.total_releases += 1
        if self._refs[pid] == 0:
            heapq.heappush(self._free, pid)
            return True
        return False

    def reset(self):
        self._refs[:] = 0
        self._free = list(range(self.num_pages))
        heapq.heapify(self._free)

    def __repr__(self):
        return (f"PagePool(pages={self.num_pages}, "
                f"in_use={self.pages_in_use}, "
                f"allocs={self.total_allocs}, "
                f"releases={self.total_releases})")


# -- jitted block-table / arena maintenance ops ------------------------------
# Scalar-indexed so ONE trace serves every (slot, idx, pid) triple; an
# eager `.at[3, 2].set(7)` would bake the constants in and compile a
# fresh executable per distinct index pair.

@jax.jit
def _bt_set_entry(bt, slot, idx, pid):
    return bt.at[slot, idx].set(pid)


@jax.jit
def _bt_reset_row(bt, slot, fill):
    return bt.at[slot].set(fill)


@jax.jit
def _arena_copy_page(buf, dst, src):
    """Copy physical page ``src`` -> ``dst`` (both arenas' leaves): the
    copy-on-write split. One traced program per arena shape."""
    def _cp(x):
        row = jax.lax.dynamic_index_in_dim(x, src, axis=0, keepdims=True)
        return jax.lax.dynamic_update_slice_in_dim(x, row, dst, axis=0)
    return jax.tree_util.tree_map(_cp, buf)


@jax.jit
def _arena_write_page(buf, dst, page):
    """Install one host-shipped physical page at index ``dst`` — the
    import half of sequence migration. ``page`` carries a single page's
    rows per leaf (``[L, page, H, D]``, or the quantized ``q``/``s``
    pair); scalar-indexed so one traced program serves every dst."""
    def _wr(x, p):
        return jax.lax.dynamic_update_slice_in_dim(
            x, p[None].astype(x.dtype), dst, axis=0)
    return jax.tree_util.tree_map(_wr, buf, page)


@jax.jit
def _len_set(lengths, slot, n):
    return lengths.at[slot].set(n)


# -- functional writers / readers (used inside jitted programs) --------------

def paged_write_rows(buf, rows, pids, ppos):
    """Write one K or V row per entry into a single layer's arena view.

    ``buf``: ``[P+1, page, H, D]`` (or the quantized dict view);
    ``rows``: ``[N, H, D]``; ``pids``/``ppos``: ``[N]`` int32 physical
    page + in-page offset. Rows routed to the trash page may collide —
    they are junk by construction. One scatter per leaf."""
    if is_quantized_kv(buf):
        qs = quantize_kv_rows(rows)            # q [N, H, D], s [N]
        return {"q": buf["q"].at[pids, ppos].set(qs["q"]),
                "s": buf["s"].at[pids, ppos].set(qs["s"])}
    return buf.at[pids, ppos].set(rows)


def paged_write_prompt_rows(buf, rows, pids, ppos):
    """Write ``N`` tokens' rows across ALL layers at once into a whole
    arena. ``buf``: ``[P+1, L, page, H, D]`` (or dict); ``rows``:
    ``[N, L, H, D]`` — token ``n``'s layer-``l`` row lands at
    ``buf[pids[n], l, ppos[n]]``. One scatter per leaf covers the whole
    prompt x layers block (the no-per-layer-host-loop invariant)."""
    num_layers = rows.shape[1]
    li = jnp.arange(num_layers, dtype=jnp.int32)[None, :]      # [1, L]
    pi = pids[:, None]                                         # [N, 1]
    oi = ppos[:, None]
    if is_quantized_kv(buf):
        qs = quantize_kv_rows(rows)            # q [N, L, H, D], s [N, L]
        return {"q": buf["q"].at[pi, li, oi].set(qs["q"]),
                "s": buf["s"].at[pi, li, oi].set(qs["s"])}
    return buf.at[pi, li, oi].set(rows)


def paged_gather_rows(buf, block_tables):
    """Reconstruct contiguous logical rows from a single layer's arena
    view: ``[P+1, page, H, D]`` gathered through ``[S, PP]`` block
    tables -> ``[S, PP*page, H, D]`` — shape-identical to a slot
    buffer's layer view, which is what makes the gather attention lane
    bitwise-equal to the slot path."""
    if is_quantized_kv(buf):
        q = buf["q"][block_tables]             # [S, PP, page, H, D]
        s = buf["s"][block_tables]             # [S, PP, page]
        sh = q.shape
        return {"q": q.reshape(sh[0], sh[1] * sh[2], sh[3], sh[4]),
                "s": s.reshape(sh[0], sh[1] * sh[2])}
    g = buf[block_tables]
    sh = g.shape
    return g.reshape(sh[0], sh[1] * sh[2], sh[3], sh[4])


def pages_for_tokens(n_tokens: int, page_size: int) -> int:
    """ceil(n_tokens / page_size) — the admission math helper."""
    return -(-int(n_tokens) // int(page_size))


class PagedKVCache:
    """Paged per-slot KV storage: one shared page arena + per-slot block
    tables + the same device ``lengths`` vector StaticKVCache threads.

    ``k``/``v``: ``[num_pages + 1, num_layers, page_size, H, D]`` device
    arenas (index ``num_pages`` is the trash page). ``block_tables``:
    ``[num_slots, pages_per_seq]`` int32 device array (unmapped entries
    point at the trash page). The host tracks which physical pages each
    slot holds references on (``_slot_pages``); ``free`` releases them
    back to the :class:`PagePool`.
    """

    def __init__(self, num_slots: int, num_layers: int, max_seq: int,
                 num_heads: int, head_dim: int, dtype="float32",
                 kv_dtype: Optional[str] = None, page_size: int = 16,
                 num_pages: Optional[int] = None):
        if num_slots < 1 or max_seq < 2:
            raise ValueError(
                f"need num_slots >= 1 and max_seq >= 2, got "
                f"{num_slots}/{max_seq}")
        if kv_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_dtype must be None (dense) or 'int8', got "
                f"{kv_dtype!r}")
        if page_size < 1 or max_seq % page_size:
            raise ValueError(
                f"page_size {page_size} must divide max_seq {max_seq} — "
                f"equal logical rows are what make paged decode "
                f"bitwise-comparable to the slot path")
        self.num_slots = int(num_slots)
        self.num_layers = int(num_layers)
        self.max_seq = int(max_seq)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.page_size = int(page_size)
        self.pages_per_seq = self.max_seq // self.page_size
        if num_pages is None:
            # worst case: every slot fully grown — byte parity with the
            # static cache; real deployments size this far smaller
            num_pages = self.num_slots * self.pages_per_seq
        if num_pages < self.pages_per_seq:
            raise ValueError(
                f"num_pages {num_pages} cannot hold even one full "
                f"sequence ({self.pages_per_seq} pages)")
        self.num_pages = int(num_pages)
        self.trash = self.num_pages            # physical junk-sink page
        self.dtype = jnp.dtype(dtype)
        self.kv_dtype = kv_dtype
        self.quantized = kv_dtype == "int8"
        shape = (self.num_pages + 1, self.num_layers, self.page_size,
                 self.num_heads, self.head_dim)
        if self.quantized:
            def _zero_buf():
                return {"q": jnp.zeros(shape, jnp.int8),
                        "s": jnp.zeros(shape[:3], jnp.float32)}
        else:
            def _zero_buf():
                return jnp.zeros(shape, self.dtype)
        self.k = _zero_buf()
        self.v = _zero_buf()
        self.block_tables = jnp.full(
            (self.num_slots, self.pages_per_seq), self.trash, jnp.int32)
        self.lengths = jnp.zeros((self.num_slots,), jnp.int32)
        self.pool = PagePool(self.num_pages)
        self._slot_pages: List[List[int]] = [[] for _ in
                                             range(self.num_slots)]
        self._free: List[int] = list(range(self.num_slots))
        self._active: set = set()
        #: copy-on-write splits performed (admission divergence)
        self.cow_splits = 0

    # -- slot lifecycle (host side) ------------------------------------------
    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def active_slots(self) -> Tuple[int, ...]:
        return tuple(sorted(self._active))

    def alloc(self) -> int:
        if not self._free:
            raise SlotsExhausted(
                f"all {self.num_slots} KV slots are in use")
        slot = self._free.pop(0)
        self._active.add(slot)
        return slot

    def free(self, slot: int):
        """Return a slot AND its page references to the pools. Raises on
        a slot double-free — handing one slot (and its pages) to two
        sequences is the corruption StaticKVCache.free guards against."""
        if not (0 <= slot < self.num_slots) or slot not in self._active:
            raise ValueError(
                f"slot {slot} is not active (double free?)")
        self._active.discard(slot)
        for pid in self._slot_pages[slot]:
            self.pool.release(pid)
        self._slot_pages[slot] = []
        self.block_tables = _bt_reset_row(self.block_tables, slot,
                                          self.trash)
        self._free.append(slot)
        self._free.sort()

    def reset(self):
        """Free every slot, every page reference, and zero the lengths
        (arenas are left as is — lengths + trash routing gate validity).
        For warmup and engine restarts."""
        for slot in list(self._active):
            self.free(slot)
        self._free = list(range(self.num_slots))
        self._active.clear()
        self._slot_pages = [[] for _ in range(self.num_slots)]
        self.pool.reset()
        self.block_tables = jnp.full(
            (self.num_slots, self.pages_per_seq), self.trash, jnp.int32)
        self.lengths = jnp.zeros((self.num_slots,), jnp.int32)

    # -- page mapping (host decides, device block table records) -------------
    def mapped_pages(self, slot: int) -> int:
        return len(self._slot_pages[slot])

    def mapped_tokens(self, slot: int) -> int:
        return len(self._slot_pages[slot]) * self.page_size

    def slot_page_ids(self, slot: int) -> Tuple[int, ...]:
        return tuple(self._slot_pages[slot])

    def _map_page(self, slot: int, pid: int):
        idx = len(self._slot_pages[slot])
        if idx >= self.pages_per_seq:
            raise ValueError(
                f"slot {slot} already maps {idx} pages (max_seq reached)")
        self._slot_pages[slot].append(pid)
        self.block_tables = _bt_set_entry(self.block_tables, slot, idx,
                                          pid)

    def ensure_pages(self, slot: int, n_tokens: int) -> int:
        """Map fresh pages so logical rows ``[0, n_tokens)`` are backed;
        returns how many pages were newly allocated. Atomic: raises
        :class:`PagesExhausted` without mapping anything when the pool
        cannot cover the need (callers evict and retry)."""
        need = pages_for_tokens(n_tokens, self.page_size)
        have = len(self._slot_pages[slot])
        if need <= have:
            return 0
        fresh = self.pool.alloc_many(need - have)
        for pid in fresh:
            self._map_page(slot, pid)
        return len(fresh)

    def adopt_shared_page(self, slot: int, pid: int):
        """Splice an already-live page (a prefix-store page) into the
        slot's block table at the next logical index: refcount +1, zero
        bytes copied."""
        self.pool.retain(pid)
        self._map_page(slot, pid)

    def adopt_copied_page(self, slot: int, src_pid: int) -> int:
        """Copy-on-write split: allocate a private page, device-copy the
        shared page's rows into it, and map it. The new occupant can now
        write its divergent tail rows without touching sharers."""
        pid = self.pool.alloc()
        dst = jnp.asarray(pid, jnp.int32)
        src = jnp.asarray(src_pid, jnp.int32)
        self.k = _arena_copy_page(self.k, dst, src)
        self.v = _arena_copy_page(self.v, dst, src)
        self._map_page(slot, pid)
        self.cow_splits += 1
        return pid

    # -- sequence migration (cold path: export / import) ---------------------
    def read_pages(self, page_ids) -> Tuple[object, object]:
        """Host copies of the K and V arena rows for ``page_ids`` — the
        export half of sequence migration. One gather + one transfer per
        arena leaf (``[n, L, page, H, D]`` stacked over the requested
        pages, or the quantized ``q``/``s`` pair). Runs between decode
        ticks on the engine worker, never inside one."""
        idx = jnp.asarray([int(p) for p in page_ids], jnp.int32)

        def _take(buf):
            return jax.tree_util.tree_map(
                lambda x: np.asarray(jax.device_get(jnp.take(x, idx, axis=0))),  # noqa: PTA002 -- sequence-export page fetch: a deliberate once-per-migration transfer on the between-tick control path
                buf)
        return _take(self.k), _take(self.v)

    def write_page(self, pid: int, k_page, v_page):
        """Install one host-shipped page (a ``read_pages`` row) at
        physical index ``pid`` — the import half of migration. The page
        must already be owned by the caller (allocated/mapped); sharers
        would observe the write."""
        if self.pool.refcount(pid) != 1:
            raise ValueError(
                f"write_page({pid}): refcount "
                f"{self.pool.refcount(pid)} != 1 — importing into a "
                f"shared or free page would corrupt sharers")
        dst = jnp.asarray(pid, jnp.int32)
        self.k = _arena_write_page(self.k, dst, k_page)
        self.v = _arena_write_page(self.v, dst, v_page)

    def set_length(self, slot: int, n_tokens: int):
        """Install a migrated sequence's resume position in the device
        lengths vector (the next decode step's write coordinate)."""
        if not (0 <= n_tokens <= self.max_seq):
            raise ValueError(f"set_length({slot}, {n_tokens})")
        self.lengths = _len_set(self.lengths, jnp.asarray(slot, jnp.int32),
                                jnp.asarray(n_tokens, jnp.int32))

    # -- functional state threading ------------------------------------------
    def swap(self, k, v, lengths):
        """Install the arrays returned by a jitted prefill/decode call.
        Shape-checked: a shape change would mean a recompile upstream."""
        def _shapes(buf):
            return [leaf.shape for leaf in jax.tree_util.tree_leaves(buf)]
        assert _shapes(k) == _shapes(self.k) \
            and _shapes(v) == _shapes(self.v), (_shapes(k), _shapes(self.k))
        self.k, self.v, self.lengths = k, v, lengths

    def kv_bytes(self) -> int:
        """Device bytes held by the K+V arenas (trash page included)."""
        return kv_nbytes(self.k) + kv_nbytes(self.v)

    def page_nbytes(self) -> int:
        """Device bytes of ONE physical page across both arenas and all
        layers — the unit the bytes_shared/bytes_copied counters count."""
        return self.kv_bytes() // (self.num_pages + 1)

    def host_lengths(self) -> np.ndarray:
        """One deliberate device->host fetch of the per-slot lengths
        (tests and ``/statsz`` only, never the per-tick path)."""
        return np.asarray(jax.device_get(self.lengths))  # noqa: PTA002 -- deliberate observability fetch (tests, /statsz); the tick loop never calls this

    def __repr__(self):
        return (f"PagedKVCache(slots={self.num_slots}, "
                f"layers={self.num_layers}, max_seq={self.max_seq}, "
                f"page={self.page_size}, pages={self.num_pages}, "
                f"in_use={self.pool.pages_in_use}, "
                f"active={len(self._active)})")
