"""Speculative decoding over a paged target cache.

Same draft+verify tick as ``serving/llm/spec.py`` — the draft model
keeps its own small slot-layout :class:`StaticKVCache` (draft contexts
are tiny; paging them buys nothing), only the TARGET's K/V moves through
the page arena. The verify step scatters all ``k+1`` candidate rows per
slot through the block table (``[S*(k+1)]`` flattened physical indices)
and gathers the full logical rows back for the multi-query attention,
so greedy output stays bitwise identical to the slot spec step, which is
itself bitwise the plain decoder (the composed parity test pins the
chain: paged-spec == slot-spec == plain slot decode on greedy).

The scheduler's room check must cover the SPECULATIVE horizon in pages:
a tick can advance a slot ``k+1`` positions, so ``PagedBatcher`` maps
pages for ``lengths + k + 1`` before a spec tick (its
``_ensure_decode_capacity``), exactly where the slot engine checked
``lengths + k + 1 <= max_seq``.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..decode import _block_decode, _layer_norm, _sample
from ..kvcache import valid_mask
from ..spec import GPTDecodeSpec, GPTSpecDecoder
from .decode import GPTPagedDecoder
from .pool import PagedKVCache, paged_gather_rows, paged_write_rows


def _paged_block_verify(spec, lp, h, kb, vb, block_tables, pid_flat,
                        ppos_flat, mask, scale):
    """``spec._block_verify`` with the K/V substrate paged: all T
    candidate rows scatter through (``pid_flat``, ``ppos_flat``) —
    the [S*T] physical coordinates of ``positions..positions+T-1`` —
    then the full logical rows gather back for the attention. Dense
    only (the spec engine path never runs over int8 KV; the config
    gate predates paging)."""
    s, t = h.shape[0], h.shape[1]
    x = _layer_norm(h, lp["n1w"], lp["n1b"], spec.ln_epsilon)

    def heads(z):                                          # [S, T, H, D]
        return z.reshape(s, t, spec.num_heads, spec.head_dim)

    q = heads(x @ lp["qw"] + lp["qb"])
    kn = heads(x @ lp["kw"] + lp["kb"])
    vn = heads(x @ lp["vw"] + lp["vb"])
    flat = (s * t, spec.num_heads, spec.head_dim)
    kb = paged_write_rows(kb, kn.reshape(flat), pid_flat, ppos_flat)
    vb = paged_write_rows(vb, vn.reshape(flat), pid_flat, ppos_flat)
    kg = paged_gather_rows(kb, block_tables)               # [S, max, H, D]
    vg = paged_gather_rows(vb, block_tables)
    qh = jnp.transpose(q * scale, (0, 2, 1, 3))            # [S, H, T, D]
    kt = jnp.transpose(kg, (0, 2, 1, 3))                   # [S, H, max, D]
    vt = jnp.transpose(vg, (0, 2, 1, 3))
    prod = jnp.matmul(qh, jnp.swapaxes(kt, -1, -2))        # [S, H, T, max]
    weights = jax.nn.softmax(prod + mask, axis=-1)
    out = jnp.matmul(weights, vt)                          # [S, H, T, D]
    out = jnp.transpose(out, (0, 2, 1, 3)).reshape(s, t, spec.hidden_size)
    h = h + (out @ lp["ow"] + lp["ob"])
    x = _layer_norm(h, lp["n2w"], lp["n2b"], spec.ln_epsilon)
    ffn = jax.nn.gelu(x @ lp["w1"] + lp["b1"], approximate=False)
    return h + (ffn @ lp["w2"] + lp["b2"]), kb, vb


def build_paged_spec_decode_step(tspec: GPTDecodeSpec,
                                 dspec: GPTDecodeSpec, k: int,
                                 max_top_k: int, page_size: int):
    """The RAW paged speculative step; same signature as
    ``build_spec_decode_step`` with the target block table threaded
    after the draft buffers:

    step(params_t, params_d, kbuf_t, vbuf_t, kbuf_d, vbuf_d,
         block_tables, lengths, finished, last_tokens, temperature,
         top_k, do_sample, eos, key)
      -> (kbuf_t, vbuf_t, kbuf_d, vbuf_d, lengths + n, finished,
          new_last, out[S, k+2])

    The caller guarantees every ACTIVE slot has pages mapped through
    position ``lengths + k`` (PagedBatcher's pre-tick capacity pass).
    """
    if k < 1:
        raise ValueError(f"speculation depth k must be >= 1, got {k}")
    t_scale = 1.0 / np.sqrt(tspec.head_dim)
    d_scale = 1.0 / np.sqrt(dspec.head_dim)
    t_max_pos = tspec.max_position_embeddings
    d_max_pos = dspec.max_position_embeddings

    def _step(params_t, params_d, kbuf_t, vbuf_t, kbuf_d, vbuf_d,
              block_tables, lengths, finished, last_tokens, temperature,
              top_k, do_sample, eos, key):
        s = lengths.shape[0]
        pp_n = block_tables.shape[1]
        max_seq = pp_n * page_size
        d_max_seq = kbuf_d.shape[2]
        # -- 1. draft proposes k tokens greedily (slot-layout cache) -----
        # identical to the slot spec step, k+1 micro-steps (the last one
        # only deposits the final proposal's K/V row)
        d_last = last_tokens
        drafts = []
        for i in range(k + 1):
            pos_i = lengths + i
            posc = jnp.clip(pos_i, 0, d_max_pos - 1)
            h = params_d["tok"][d_last] + params_d["pos"][posc]
            mask = valid_mask(pos_i, d_max_seq, h.dtype)
            new_k, new_v = [], []
            for li, lp in enumerate(params_d["layers"]):
                h, kb, vb = _block_decode(dspec, lp, h, kbuf_d[:, li],
                                          vbuf_d[:, li], pos_i, mask,
                                          d_scale)
                new_k.append(kb)
                new_v.append(vb)
            kbuf_d = jnp.stack(new_k, axis=1)
            vbuf_d = jnp.stack(new_v, axis=1)
            if i == k:
                break
            h = _layer_norm(h, params_d["fnw"], params_d["fnb"],
                            dspec.ln_epsilon)
            lraw_d = (h @ params_d["tok"].T).astype(jnp.float32)
            d_i = jnp.argmax(lraw_d, axis=-1).astype(jnp.int32)
            drafts.append(d_i)
            d_last = d_i
        drafts_arr = jnp.stack(drafts, axis=1)                 # [S, k]

        # -- 2. target verifies through the page arena -------------------
        t_len = k + 1
        u = jnp.concatenate([last_tokens[:, None], drafts_arr], axis=1)
        pos_mat = lengths[:, None] + jnp.arange(t_len, dtype=jnp.int32)
        posc = jnp.clip(pos_mat, 0, t_max_pos - 1)
        h = params_t["tok"][u] + params_t["pos"][posc]         # [S, T, E]
        j = jnp.arange(max_seq, dtype=jnp.int32)[None, None]
        vmask = jnp.where(j <= pos_mat[:, :, None], 0.0,
                          -1e9).astype(h.dtype)[:, None]       # [S,1,T,max]
        # physical coordinates of all S*T candidate rows; out-of-range
        # positions (inactive slots) clip to the last table entry — the
        # trash page for freed slots
        page_idx = jnp.clip(pos_mat // page_size, 0, pp_n - 1)
        pid_flat = jnp.take_along_axis(block_tables, page_idx,
                                       axis=1).reshape(-1)     # [S*T]
        ppos_flat = (pos_mat % page_size).reshape(-1)
        new_k, new_v = [], []
        for li, lp in enumerate(params_t["layers"]):
            h, kb, vb = _paged_block_verify(
                tspec, lp, h, kbuf_t[:, li], vbuf_t[:, li],
                block_tables, pid_flat, ppos_flat, vmask, t_scale)
            new_k.append(kb)
            new_v.append(vb)
        kbuf_t = jnp.stack(new_k, axis=1)
        vbuf_t = jnp.stack(new_v, axis=1)
        h = _layer_norm(h, params_t["fnw"], params_t["fnb"],
                        tspec.ln_epsilon)
        lraw = (h @ params_t["tok"].T).astype(jnp.float32)     # [S, T, V]
        t_greedy = jnp.argmax(lraw, axis=-1).astype(jnp.int32)

        # -- 3. accept-prefix + bonus (identical to the slot step) -------
        match = (drafts_arr == t_greedy[:, :k]).astype(jnp.int32)
        m = jnp.sum(jnp.cumprod(match, axis=1), axis=1)        # [S], 0..k
        m = jnp.where(do_sample | finished, 0, m)
        bonus = jnp.take_along_axis(t_greedy, m[:, None], axis=1)[:, 0]
        samp_tok = _sample(lraw[:, 0], temperature, top_k, do_sample,
                           key, max_top_k)
        step_tok = jnp.where(do_sample, samp_tok, bonus)
        step_tok = jnp.where(finished & (eos >= 0), eos, step_tok)
        idx = jnp.arange(t_len, dtype=jnp.int32)[None]         # [1, T]
        ext_drafts = jnp.concatenate(
            [drafts_arr, jnp.zeros((s, 1), jnp.int32)], axis=1)
        emit = jnp.where(idx < m[:, None], ext_drafts,
                         jnp.where(idx == m[:, None], step_tok[:, None],
                                   0))
        n_emit = m + 1
        hit_eos = ((emit == eos[:, None]) & (eos >= 0)[:, None]
                   & (idx < n_emit[:, None])).any(axis=1)
        finished = finished | hit_eos
        out = jnp.concatenate([n_emit[:, None], emit],
                              axis=1).astype(jnp.int32)        # [S, k+2]
        return (kbuf_t, vbuf_t, kbuf_d, vbuf_d, lengths + n_emit,
                finished, step_tok, out)

    return _step


@functools.lru_cache(maxsize=32)
def get_paged_spec_decode_step(tspec: GPTDecodeSpec,
                               dspec: GPTDecodeSpec, k: int,
                               max_top_k: int, page_size: int):
    counter = {"traces": 0}
    raw = build_paged_spec_decode_step(tspec, dspec, k, max_top_k,
                                       page_size)

    def _step(*args):
        counter["traces"] += 1
        return raw(*args)

    fn = jax.jit(_step)
    fn.trace_counter = counter
    return fn


class GPTPagedSpecDecoder(GPTSpecDecoder):
    """GPTSpecDecoder whose TARGET is a :class:`GPTPagedDecoder` —
    the draft cache stays slot-layout (``new_draft_kv`` inherited
    unchanged), only the verify step is swapped for the paged one."""

    def __init__(self, target: GPTPagedDecoder, draft_model, k: int = 4,
                 exec_cache=None):
        if not isinstance(target, GPTPagedDecoder):
            raise TypeError(
                "GPTPagedSpecDecoder needs a GPTPagedDecoder target; "
                "use GPTSpecDecoder for slot-layout targets")
        super().__init__(target, draft_model, k=k, exec_cache=exec_cache)
        self._key = self._key + ("paged", target.page_size)

    def spec_step_fn(self, num_slots: int, max_seq: int):
        return self.exec_cache.get_or_compile(
            self._key + ("spec_step", num_slots, max_seq),
            lambda: get_paged_spec_decode_step(
                self.target.spec, self.dspec, self.k,
                self.target.max_top_k, self.target.page_size))

    def step(self, kv: PagedKVCache, kv_draft, params_t, params_d,
             finished, last_tokens, samp_vecs, key):
        fn = self.spec_step_fn(kv.num_slots, kv.max_seq)
        (kt, vt, kd, vd, lengths, finished, last_new, out) = fn(
            params_t, params_d, kv.k, kv.v, kv_draft.k, kv_draft.v,
            kv.block_tables, kv.lengths, finished, last_tokens,
            *samp_vecs, key)
        kv.swap(kt, vt, lengths)
        kv_draft.swap(kd, vd, lengths)
        return finished, last_new, out
