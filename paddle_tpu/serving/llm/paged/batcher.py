"""PagedBatcher: admission on pages-at-current-lengths, not max_seq slots.

The slot batcher admits whenever a slot is free, because a slot IS the
worst case: ``max_seq`` rows, reserved up front. With paged KV the
resource is the page pool, and the question changes from "is a slot
free" to "are there enough pages for THIS prompt at ITS length, plus
headroom for the sequences already running". This subclass keeps the
whole tick loop (the compiled-step dispatch, token delivery, finish and
deadline logic are inherited unchanged) and replaces the memory policy:

- **admit** maps exactly the pages the prompt needs now. If the pool
  (or slot table) can't take it, the request parks in a pending deque
  — admission is no longer slot-gated, so ``free_slots`` reports 0
  while anything is pending, and ``active`` counts pending so the
  worker keeps ticking (each tick frees pages, which is what pending
  requests are waiting for). A request that can't fit even with the
  pool EMPTY of other users fails outright instead of deadlocking.

- **per-tick capacity**: before each tick, one page-table pass maps the
  next write position (``+k+1`` under speculation) for every active
  slot. When the pool runs dry mid-stream, unpinned prefix entries are
  dropped first, then the YOUNGEST request is evicted (least progress
  lost) — pages reclaimed mid-stream, the slot-path analogue being
  deadline eviction.

- **prefix sharing is zero-copy**: a :class:`PagedPrefixStore` hit
  adopts full shared pages by table splice (``bytes_shared``), and when
  the entry extends past the last full page boundary the one partial
  page is COW-split (``adopt_copied_page``: the only bytes a hit ever
  copies, counted in ``bytes_copied`` — page-aligned hits copy ZERO).
  On a miss, the freshly prefilled sequence's own page-aligned head is
  claimed by the store by refcount, again copying nothing.

Gauges: ``<stat_prefix>.pages_free`` and ``.pages_cow_splits`` publish
the pool state at every admission and tick (the /metricsz view of the
admission math in docs/serving.md).
"""
from __future__ import annotations

import collections
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ...request import RequestTooLarge
from ..decode import pack_sampling
from ..scheduler import ContinuousBatcher, GenerationRequest
from .decode import GPTPagedDecoder
from .pool import PagedKVCache, PagesExhausted, pages_for_tokens
from .prefix import PagedPrefixStore


class PagedBatcher(ContinuousBatcher):
    """Page-pool admission + COW prefix sharing over the inherited tick
    loop. Single-threaded like the base: only the engine worker calls
    in."""

    def __init__(self, decoder: GPTPagedDecoder, config, registry,
                 clock=None, prefix_store=None, spec_decoder=None):
        if not isinstance(decoder, GPTPagedDecoder):
            raise TypeError("PagedBatcher needs a GPTPagedDecoder "
                            "(kv_layout='paged')")
        if prefix_store is not None:
            raise NotImplementedError(
                "paged engines share prefix pages inside their own arena "
                "— an external (host) PrefixStore cannot be attached; "
                "set prefix_cache=True and let the batcher build its "
                "PagedPrefixStore")
        kw = {} if clock is None else {"clock": clock}
        super().__init__(decoder, config, registry, prefix_store=None,
                         spec_decoder=spec_decoder, **kw)
        self.kv: PagedKVCache
        self._pending = collections.deque()
        if config.prefix_cache:
            self.prefix_store = PagedPrefixStore(
                self.kv, registry=registry,
                stat_prefix=f"{config.stat_prefix}.prefix")
        self._stat_set("pages_free", self.kv.pool.free_pages)
        self._stat_set("pages_cow_splits", 0)

    # -- introspection -------------------------------------------------------
    @property
    def active(self) -> int:
        # pending requests count: the worker must keep ticking (ticks
        # free pages) and drain must not exit while any wait for pages
        return len(self._reqs) + len(self._pending)

    @property
    def free_slots(self) -> int:
        # stop pulling from the queue while requests already wait for
        # pages — queue order is admission order
        if self._pending:
            return 0
        return self.kv.free_slots

    def _publish_pages(self):
        self._stat_set("pages_free", self.kv.pool.free_pages)
        self._stat_set("pages_cow_splits", self.kv.cow_splits)

    # -- admission -----------------------------------------------------------
    def admit(self, req: GenerationRequest):
        self._drain_pending()
        if self._pending or not self._try_admit(req):
            self._park_or_fail(req)
        self._publish_pages()

    def _drain_pending(self):
        while self._pending:
            head = self._pending[0]
            if head.expired:
                self._pending.popleft()
                head.fail_expired()
                continue
            if not self._try_admit(head):
                if not self._reqs:
                    # nothing running -> no pages will ever free up;
                    # _try_admit already drained the prefix store, so
                    # this request simply does not fit the pool
                    self._pending.popleft()
                    self._fail_oversize(head)
                    continue
                break
            self._pending.popleft()

    def _park_or_fail(self, req: GenerationRequest):
        if not self._reqs:
            self._fail_oversize(req)
            return
        self._pending.append(req)
        self._stat_set("pages_pending_requests", len(self._pending))

    def _fail_oversize(self, req: GenerationRequest):
        need = pages_for_tokens(req.prompt_len, self.kv.page_size)
        req.fail(RequestTooLarge(
            f"prompt of {req.prompt_len} tokens needs {need} pages but "
            f"the pool holds {self.kv.pool.num_pages} "
            f"({self.kv.pool.free_pages} free, none reclaimable)"))
        self._stat_add("rejected_pool_exhausted", 1)

    def _try_admit(self, req: GenerationRequest) -> bool:
        """Admit ``req`` if a slot AND enough pages are available at its
        actual length; True on success. No partial state on False: the
        page math runs before any allocation."""
        if self.kv.free_slots < 1:
            return False
        page = self.kv.page_size
        sig = self.decoder.prefix_sig(self.kv)
        entry, reuse_n = None, 0
        if self.prefix_store is not None:
            entry, reuse_n = self.prefix_store.lookup(
                req.prompt, req.prompt_len - 1, sig)
            # the PADDED tail bucket must fit behind the reused head
            # (same shrink rule as the slot path)
            while reuse_n > 0 and reuse_n + self.config.bucket_for(
                    req.prompt_len - reuse_n) > self.config.max_seq:
                reuse_n -= page
            if entry is not None and reuse_n <= 0:
                self.prefix_store.unpin(entry)
                entry, reuse_n = None, 0
        # COW extension: when the entry's pages run past the last FULL
        # page boundary we may reuse (store hits are page-aligned, the
        # reusable-token cap prompt_len-1 usually is not), the one
        # partial page is copied and the divergent tail overwrites the
        # private copy — rows [reuse_n, ext_n) come along for free.
        cow_src = None
        ext_n = min(entry.n_tokens, req.prompt_len - 1) if entry else 0
        if (entry is not None and reuse_n < ext_n
                and ext_n - reuse_n < page
                and ext_n + self.config.bucket_for(
                    req.prompt_len - ext_n) <= self.config.max_seq
                and np.array_equal(entry.tokens[reuse_n:ext_n],
                                   req.prompt[reuse_n:ext_n])):
            cow_src = entry.page_ids[reuse_n // page]
        else:
            ext_n = reuse_n
        shared_pages = reuse_n // page
        total_pages = pages_for_tokens(req.prompt_len, page)
        need_alloc = total_pages - shared_pages     # COW page included
        # headroom: one lookahead page per running sequence, so an
        # admission cannot immediately force a mid-stream eviction at
        # the next tick's capacity pass
        reserve = len(self._reqs)
        shortfall = need_alloc + reserve - self.kv.pool.free_pages
        if shortfall > 0 and self.prefix_store is not None:
            shortfall -= self.prefix_store.evict_unpinned(shortfall)
        if shortfall > 0:
            if entry is not None:
                self.prefix_store.unpin(entry)
            return False
        self._admit_paged(req, entry, reuse_n, ext_n, cow_src)
        return True

    def _admit_paged(self, req: GenerationRequest, entry, reuse_n: int,
                     ext_n: int, cow_src: Optional[int]):
        """The committed admission: slot + page mapping + prefill +
        first-token delivery (the paged ``_admit_inner``)."""
        t0 = self._clock()
        page = self.kv.page_size
        slot = self.kv.alloc()
        req.weights_version = self.weights_version
        self._reqs[slot] = req
        self._slot_samp[slot] = req.sampling
        self._samp_vecs = pack_sampling(self._slot_samp)
        samp1 = pack_sampling([req.sampling])
        slot_arr = jnp.asarray([slot], jnp.int32)
        if reuse_n > 0:
            for pid in entry.page_ids[:reuse_n // page]:
                self.kv.adopt_shared_page(slot, pid)
            self.prefix_store.note_shared(
                (reuse_n // page) * self.kv.page_nbytes())
        if cow_src is not None:
            self.kv.adopt_copied_page(slot, cow_src)
            self.prefix_store.note_copied(self.kv.page_nbytes())
            self._stat_add("prefix.cow_splits", 1)
        self.kv.ensure_pages(slot, req.prompt_len)
        if entry is not None:
            req._prefix_entry = entry       # stays pinned until release
            tail = req.prompt[ext_n:]
            lt = self.config.bucket_for(int(tail.size))
            padded = np.zeros((1, lt), np.int32)
            padded[0, :tail.size] = tail
            nxt, self._finished = self.decoder.tail_prefill(
                self.kv, self._params, jnp.asarray(padded),
                jnp.asarray([int(tail.size)], jnp.int32),
                jnp.asarray([ext_n], jnp.int32), slot_arr,
                self._finished, samp1, self._next_key())
            self._stat_add("prefix.reused_tokens", ext_n)
        else:
            lp = self.config.bucket_for(req.prompt_len)
            padded = np.zeros((1, lp), np.int32)
            padded[0, :req.prompt_len] = req.prompt
            nxt, self._finished = self.decoder.prefill(
                self.kv, self._params, jnp.asarray(padded),
                jnp.asarray([req.prompt_len], jnp.int32), slot_arr,
                self._finished, samp1, self._next_key())
            if self.prefix_store is not None:
                # miss: claim the page-aligned head BY REFERENCE — the
                # store retains the sequence's own pages, nothing moves
                n = (req.prompt_len // page) * page
                if n >= page:
                    ins = self.prefix_store.insert(
                        req.prompt[:n],
                        self.kv.slot_page_ids(slot)[:n // page],
                        self.decoder.prefix_sig(self.kv))
                    if ins is not None:
                        req._prefix_entry = ins
        if self.spec is not None:
            lp = self.config.bucket_for(req.prompt_len)
            dpad = np.zeros((1, lp), np.int32)
            dpad[0, :req.prompt_len] = req.prompt
            self.spec.draft_prefill(
                self.kv_draft, self._draft_params, jnp.asarray(dpad),
                jnp.asarray([req.prompt_len], jnp.int32), slot_arr,
                self.kv.lengths, self._finished, samp1, self._next_key())
        self._last = self._last.at[jnp.asarray([slot])].set(nxt)
        tok = int(np.asarray(jax.device_get(nxt))[0])  # noqa: PTA002 -- one [1]-token fetch per admission; first-token delivery (TTFT) needs the value on host
        now = self._clock()
        self._stat_observe("prefill_ms", (now - t0) * 1000.0)
        self._stat_observe("ttft_ms", (now - req.t_enqueue) * 1000.0)
        self._stat_add("prefills", 1)
        if not req._emit(tok):
            self._forget(slot, req)
            return
        req._t_last = now
        self._stat_add("tokens_generated", 1)
        self._maybe_finish(slot, req, tok)

    # -- per-tick capacity ---------------------------------------------------
    def tick(self) -> int:
        self._drain_pending()
        self._stat_set("pages_pending_requests", len(self._pending))
        if not self._reqs:
            self._publish_pages()
            return 0
        self._ensure_decode_capacity()
        if not self._reqs:              # capacity pass may evict
            self._publish_pages()
            return 0
        n = super().tick()
        self._publish_pages()
        return n

    def _ensure_decode_capacity(self):
        """Map the next write position for every active slot before the
        tick — ``+1`` token plain, ``+k+1`` speculative (the verify step
        lands k+1 candidate rows). Pool dry: drop unpinned prefix
        entries, then evict the youngest request; a lone un-mappable
        sequence finishes with reason 'length' (nothing left to
        reclaim)."""
        horizon = (self.spec.k + 1) if self.spec is not None else 1
        for slot in sorted(self._reqs):
            req = self._reqs.get(slot)
            if req is None:
                continue
            pos = req.seq_len - 1
            need_tok = min(pos + horizon, self.config.max_seq)
            while True:
                try:
                    self.kv.ensure_pages(slot, need_tok)
                    break
                except PagesExhausted:
                    short = (pages_for_tokens(need_tok, self.kv.page_size)
                             - self.kv.mapped_pages(slot)
                             - self.kv.pool.free_pages)
                    if self.prefix_store is not None and \
                            self.prefix_store.evict_unpinned(
                                max(1, short)) > 0:
                        continue
                    victim = self._youngest_other(slot)
                    if victim is None:
                        # this is the only sequence and the pool cannot
                        # grow it — finish at current length rather
                        # than deadlock
                        self._stat_add("pages_truncations", 1)
                        self._release(slot, req, "length")
                        break
                    self._evict_for_pages(victim)

    def _youngest_other(self, slot: int) -> Optional[int]:
        others = [(s, r) for s, r in self._reqs.items() if s != slot]
        if not others:
            return None
        return max(others, key=lambda sr: sr[1].t_enqueue)[0]

    def _evict_for_pages(self, slot: int):
        req = self._reqs.pop(slot)
        self.kv.free(slot)
        self._unpin_prefix(req)
        req.fail(PagesExhausted(
            f"request {req.req_id} evicted after {len(req.tokens)} "
            f"tokens: page pool exhausted and it was the youngest "
            f"sequence"))
        self._stat_add("pages_evicted_midstream", 1)
        self._stat_add("evicted_midstream", 1)

    # -- live sequence migration (docs/fault_tolerance.md) -------------------
    #: the paged substrate can ship sequences as page payloads
    supports_export = True

    def export_all(self):
        """Snapshot-and-detach every live sequence into host-side
        manifests (worker thread, between ticks). A request still
        mid-replay from an earlier resume ships payload-free — its
        cache is not yet a faithful transcript, so the target replays
        it instead of splicing. Pending (page-starved) requests ship
        cold. On return the batcher holds none of them."""
        from ...fleet.migrate import SequenceManifest
        sig = self.decoder.prefix_sig(self.kv)
        out = []
        for slot in sorted(self._reqs):
            req = self._reqs[slot]
            if req._replay_pos is None:
                n_cached = req.seq_len - 1   # last token not yet in cache
                pids, k_pages, v_pages = self.decoder.export_sequence(
                    self.kv, slot, n_cached)
                man = SequenceManifest(
                    req, req.prompt, req.tokens, req.sampling,
                    weights_version=req.weights_version,
                    n_cached_tokens=n_cached,
                    page_size=self.kv.page_size, sig=sig,
                    k_pages=k_pages, v_pages=v_pages)
            else:
                man = SequenceManifest.for_queued(req)
            out.append(man)
            del self._reqs[slot]
            self.kv.free(slot)
            self._unpin_prefix(req)
        while self._pending:
            out.append(SequenceManifest.for_queued(
                self._pending.popleft()))
        self._stat_set("pages_pending_requests", 0)
        self._publish_pages()
        return out

    def import_manifest(self, man) -> bool:
        """Splice a migrated sequence into a free slot and arm it for
        the next tick (worker thread, between ticks). Page-aligned
        prompt-prefix pages this engine already holds are adopted
        zero-copy through the prefix store's chain hash; the rest are
        allocated and filled from the shipped payload. Returns False
        WITHOUT side effects when geometry differs or the slot table /
        page pool cannot take it — the migrator falls back to replay."""
        if man.sig != self.decoder.prefix_sig(self.kv) \
                or man.page_size != self.kv.page_size:
            return False
        n_cached = man.n_cached_tokens
        if not (0 < n_cached < self.config.max_seq) or not man.tokens:
            return False
        if self.kv.free_slots < 1:
            return False
        req = man.req
        page = self.kv.page_size
        total = pages_for_tokens(n_cached, page)
        entry, reuse_n = None, 0
        if self.prefix_store is not None:
            entry, reuse_n = self.prefix_store.lookup(
                req.prompt, min(req.prompt_len, n_cached), man.sig)
            reuse_n = (reuse_n // page) * page   # whole pages only
            if entry is not None and reuse_n <= 0:
                self.prefix_store.unpin(entry)
                entry, reuse_n = None, 0
        shared = reuse_n // page
        # same admission math as _try_admit: tail pages + one lookahead
        # page per running sequence
        shortfall = (total - shared) + len(self._reqs) \
            - self.kv.pool.free_pages
        if shortfall > 0 and self.prefix_store is not None:
            shortfall -= self.prefix_store.evict_unpinned(shortfall)
        if shortfall > 0:
            if entry is not None:
                self.prefix_store.unpin(entry)
            return False
        slot = self.kv.alloc()
        try:
            if shared:
                for pid in entry.page_ids[:shared]:
                    self.kv.adopt_shared_page(slot, pid)
                self.prefix_store.note_shared(
                    shared * self.kv.page_nbytes())
            self.decoder.import_sequence(
                self.kv, slot, n_cached, man.k_pages, man.v_pages,
                shared_pages=shared)
        except Exception:
            self.kv.free(slot)
            if entry is not None:
                self.prefix_store.unpin(entry)
            raise
        req._prefix_entry = entry
        req._t_last = None
        self._reqs[slot] = req
        self._slot_samp[slot] = req.sampling
        self._samp_vecs = pack_sampling(self._slot_samp)
        # arm the compiled step's per-slot state: the next tick feeds
        # the last emitted token and writes its KV row at n_cached
        self._finished = self._finished.at[slot].set(False)
        self._last = self._last.at[slot].set(int(req.tokens[-1]))
        self._stat_add("migrated_pages_shared", shared)
        self._stat_add("migrated_pages_copied", total - shared)
        self._publish_pages()
        return True

    # -- exits ---------------------------------------------------------------
    def evacuate(self):
        out = super().evacuate()
        while self._pending:
            out.append(self._pending.popleft())
        self._stat_set("pages_pending_requests", 0)
        self._publish_pages()
        return out

    def abort_all(self, exc_factory):
        super().abort_all(exc_factory)
        while self._pending:
            req = self._pending.popleft()
            req.fail(exc_factory(req))
        self._publish_pages()

    # -- mfu -----------------------------------------------------------------
    def _measure_decode_flops(self):
        # the XLA cost probe compiles the SLOT decode program, which the
        # paged engine never runs; skip rather than mis-measure
        self._decode_flops = 0.0
        self._peak_flops = 1.0
