"""PagedPrefixStore: zero-copy prefix sharing through the page arena.

The slot-path :class:`~paddle_tpu.serving.llm.prefix.PrefixStore` keeps
prefix K/V on HOST and bulk-copies it into a fresh slot on every hit —
correct, but a hit still costs a device copy proportional to the prefix.
With paged KV the rows never need to move: a cached prefix is just a
list of PAGE IDS into the live arena. A hit pins those pages into the
new sequence's block table (``PagedKVCache.adopt_shared_page`` — one
refcount bump and one int32 table write per page, zero K/V bytes
copied), and the store itself holds one pool reference per page so the
rows survive as long as the entry does, even after every sharing
sequence has finished.

Copy-on-write: shared pages are IMMUTABLE by convention — a sequence
never writes into a page whose pool refcount it does not exclusively
own. The batcher enforces this at admission: full shared pages are
adopted in place, and the first page the sequence will WRITE into (the
partial page covering ``reuse_n .. prompt_len``, or the page right at
the divergence point) is materialized via
``PagedKVCache.adopt_copied_page`` — a one-page arena copy, the COW
split. ``bytes_shared`` / ``bytes_copied`` counters make the zero-copy
claim observable on ``/metricsz`` (the acceptance test asserts
``bytes_copied == 0`` for page-aligned hits).

Hashing reuses ``prefix.chain_hashes`` with ``block = page_size``, so
equal chain values identify equal token prefixes at page granularity,
verified byte-for-byte on lookup. Eviction is LRU by last hit under a
PAGE budget; evicting an entry releases its pool references (pages
whose last reference drops return to the free list — a sequence still
sharing them keeps them alive through its own references).

Thread safety: same discipline as the host store — every mutable
structure guarded by ``self._lock``. Pool refcount mutations happen
inside the store lock; the pool itself is only ever touched from the
engine worker thread and admission path, which the batcher already
serializes.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from ....core import monitor as _mon
from ..prefix import ShapeSig, chain_hashes
from .pool import PagedKVCache


class PagedPrefixEntry:
    """One cached page-aligned prefix: the token prefix plus the page
    ids holding its K/V rows in the arena. The payload is a *claim* on
    live arena pages (the store holds one pool ref per page), not a
    copy."""

    __slots__ = ("key", "tokens", "page_ids", "n_tokens", "sig")

    def __init__(self, key: bytes, tokens: np.ndarray,
                 page_ids: Tuple[int, ...], sig: ShapeSig):
        self.key = key
        self.tokens = tokens
        self.page_ids = tuple(int(p) for p in page_ids)
        self.n_tokens = int(tokens.size)
        self.sig = sig

    def __repr__(self):
        return (f"PagedPrefixEntry(n_tokens={self.n_tokens}, "
                f"pages={len(self.page_ids)})")


class PagedPrefixStore:
    """Ref-counted, page-budgeted store of shared prefix pages."""

    def __init__(self, kv: PagedKVCache,
                 capacity_pages: Optional[int] = None,
                 registry: Optional[_mon.StatRegistry] = None,
                 stat_prefix: str = "serving.llm.prefix"):
        self.kv = kv
        self.page_size = kv.page_size
        # default budget: a quarter of the pool may sit in cached
        # prefixes — enough to keep hot system prompts resident without
        # starving admission
        self.capacity_pages = (max(1, kv.pool.num_pages // 4)
                               if capacity_pages is None
                               else int(capacity_pages))
        self._registry = registry if registry is not None \
            else _mon.default_registry()
        self._prefix = stat_prefix
        self._lock = threading.Lock()
        self._entries: Dict[bytes, PagedPrefixEntry] = {}
        self._index: Dict[bytes, bytes] = {}           # chain point -> key
        self._refs: Dict[bytes, int] = {}
        self._last_hit: Dict[bytes, int] = {}
        self._tick = 0
        self._pages = 0
        self._bytes_shared = 0
        self._bytes_copied = 0
        self._hits = 0
        self._misses = 0
        self._stat_set("pages", 0)
        self._stat_set("entries", 0)

    # -- stats ---------------------------------------------------------------
    def _stat_add(self, name, v):
        self._registry.add(f"{self._prefix}.{name}", v)

    def _stat_set(self, name, v):
        self._registry.set(f"{self._prefix}.{name}", v)

    def note_shared(self, nbytes: int):
        """Record a zero-copy adoption: ``nbytes`` of prefix K/V reused
        by table splice instead of being recomputed or copied."""
        with self._lock:
            self._bytes_shared += int(nbytes)
        self._stat_add("bytes_shared", int(nbytes))

    def note_copied(self, nbytes: int):
        """Record bytes actually copied on a hit (COW splits of partial
        pages) — the counter the zero-copy acceptance test pins at 0
        for page-aligned prefixes."""
        with self._lock:
            self._bytes_copied += int(nbytes)
        self._stat_add("bytes_copied", int(nbytes))

    @property
    def pages_used(self) -> int:
        with self._lock:
            return self._pages

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "pages": self._pages,
                "capacity_pages": self.capacity_pages,
                "page_size": self.page_size,
                "pinned": sum(1 for n in self._refs.values() if n > 0),
                "bytes_shared": self._bytes_shared,
                "bytes_copied": self._bytes_copied,
                "hits": self._hits,
                "misses": self._misses,
            }

    # -- pin / unpin ---------------------------------------------------------
    def unpin(self, entry: PagedPrefixEntry):
        with self._lock:
            if entry.key in self._refs:
                self._refs[entry.key] = max(0, self._refs[entry.key] - 1)

    # -- lookup / insert -----------------------------------------------------
    def lookup(self, tokens, max_tokens: int,
               sig: ShapeSig) -> Tuple[Optional[PagedPrefixEntry], int]:
        """Longest cached prefix of ``tokens`` reusable at most
        ``max_tokens`` tokens (a page multiple). A hit comes back
        *pinned*; the caller adopts ``entry.page_ids[: n //
        page_size]`` into its block table and unpins when the request
        leaves the engine."""
        toks = np.asarray(tokens, dtype=np.int32).reshape(-1)  # noqa: PTA002 -- admission-time view of the caller's host-side prompt
        np_max = min(int(max_tokens), toks.size) // self.page_size
        if np_max < 1:
            with self._lock:
                self._misses += 1
            self._stat_add("misses", 1)
            return None, 0
        hashes = chain_hashes(toks, self.page_size)[:np_max]
        with self._lock:
            for i in range(len(hashes) - 1, -1, -1):
                key = self._index.get(hashes[i])
                if key is None:
                    continue
                entry = self._entries.get(key)
                n = (i + 1) * self.page_size
                if entry is None or entry.sig != sig \
                        or entry.n_tokens < n \
                        or not np.array_equal(entry.tokens[:n], toks[:n]):
                    continue
                self._tick += 1
                self._last_hit[key] = self._tick
                self._refs[key] = self._refs.get(key, 0) + 1
                self._hits += 1
                self._stat_add("hits", 1)
                self._stat_add("hit_tokens", n)
                return entry, n
            self._misses += 1
        self._stat_add("misses", 1)
        return None, 0

    def insert(self, tokens,
               page_ids, sig: ShapeSig) -> Optional[PagedPrefixEntry]:
        """Claim the pages holding a freshly prefilled prompt's
        page-aligned prefix. ``page_ids``: the sequence's OWN pages
        covering ``tokens[: len(page_ids) * page_size]`` — the store
        retains each (so they outlive the sequence), copying nothing.
        Returns the entry *pinned*; dedups against an existing entry
        for the same chain (in which case no new refs are taken). May
        evict LRU unpinned entries past the page budget."""
        toks = np.asarray(tokens, dtype=np.int32).reshape(-1)  # noqa: PTA002 -- admission-time view of the caller's host-side prompt
        page_ids = tuple(int(p) for p in page_ids)
        n = len(page_ids) * self.page_size
        if n < self.page_size or toks.size < n:
            return None
        toks = toks[:n]
        hashes = chain_hashes(toks, self.page_size)
        key = hashes[-1]
        with self._lock:
            existing_key = self._index.get(key)
            if existing_key is not None:
                existing = self._entries.get(existing_key)
                if existing is not None and existing.sig == sig \
                        and existing.n_tokens >= n \
                        and np.array_equal(existing.tokens[:n], toks):
                    self._tick += 1
                    self._last_hit[existing.key] = self._tick
                    self._refs[existing.key] = \
                        self._refs.get(existing.key, 0) + 1
                    return existing
            for pid in page_ids:
                self.kv.pool.retain(pid)
            entry = PagedPrefixEntry(key, toks, page_ids, sig)
            self._entries[key] = entry
            self._pages += len(page_ids)
            self._tick += 1
            self._last_hit[key] = self._tick
            self._refs[key] = 1
            for h in hashes:
                self._index[h] = key
            if self._pages > self.capacity_pages:
                recency = dict(self._last_hit)
                victims = sorted(
                    (vk for vk, e in self._entries.items()
                     if self._refs.get(vk, 0) == 0),
                    key=lambda vk: recency.get(vk, 0))
                for vk in victims:
                    if self._pages <= self.capacity_pages:
                        break
                    self._evict_locked(vk)
            self._stat_add("inserts", 1)
            self._stat_set("pages", self._pages)
            self._stat_set("entries", len(self._entries))
            return entry

    def _evict_locked(self, key: bytes):
        victim = self._entries.pop(key)  # noqa: PTA006 -- _locked suffix contract: all callers hold self._lock
        self._pages -= len(victim.page_ids)  # noqa: PTA006 -- _locked suffix contract: all callers hold self._lock
        self._refs.pop(key, None)  # noqa: PTA006 -- _locked suffix contract: all callers hold self._lock
        self._last_hit.pop(key, None)  # noqa: PTA006 -- _locked suffix contract: all callers hold self._lock
        stale = [h for h, k2 in self._index.items() if k2 == key]  # noqa: PTA006 -- _locked suffix contract: all callers hold self._lock
        for h in stale:
            del self._index[h]  # noqa: PTA006 -- _locked suffix contract: all callers hold self._lock
        for pid in victim.page_ids:
            self.kv.pool.release(pid)
        self._stat_add("evictions", 1)

    def evict_unpinned(self, need_pages: int) -> int:
        """Drop LRU unpinned entries until ``need_pages`` pool pages
        were released (or no victims remain). The batcher's admission
        fallback when the pool runs dry. Returns pages released."""
        released = 0
        with self._lock:
            recency = dict(self._last_hit)
            victims = sorted(
                (vk for vk in self._entries
                 if self._refs.get(vk, 0) == 0),
                key=lambda vk: recency.get(vk, 0))
            for vk in victims:
                if released >= need_pages:
                    break
                released += len(self._entries[vk].page_ids)
                self._evict_locked(vk)
            self._stat_set("pages", self._pages)
            self._stat_set("entries", len(self._entries))
        return released

    def clear(self):
        """Drop every entry (pinned or not), releasing all page refs —
        engine-teardown path, pairs with ``PagedKVCache.reset`` leak
        accounting in tests."""
        with self._lock:
            for key in list(self._entries):
                self._evict_locked(key)
            self._stat_set("pages", self._pages)
            self._stat_set("entries", len(self._entries))

    def __repr__(self):
        with self._lock:
            return (f"PagedPrefixStore(entries={len(self._entries)}, "
                    f"pages={self._pages}/{self.capacity_pages}, "
                    f"page={self.page_size})")
