"""Pure-jax prefill + single-compile decode step for GPT models.

The concat-cache ``generate`` retraces every token because the KV shapes
grow; here the whole decode tick is one jitted function over fixed
``[num_slots, ...]`` shapes — greedy/temperature/top-k sampling and eos
masking included — so XLA fuses it once and reuses it for every token of
every request ("Operator Fusion in XLA", arxiv 2301.13062). Parameters
are passed as a pytree argument (not baked into the trace), so training
and serving can share one executable across checkpoint reloads.

The math mirrors the framework's dense eval path operation-for-operation
(``nn.transformer.MultiHeadAttention`` dense branch, ``F.layer_norm``,
``F.gelu(approximate=False)``, tied-embedding logits, and the sampling
recipe of ``models.gpt._gpt_generate``), so static-slot decode emits the
same tokens as the reference concat-cache path — the equivalence test in
``tests/test_llm_serving.py`` asserts it token-for-token.

Per-slot sampling state travels as device vectors (``temperature``,
``top_k``, ``do_sample``, ``eos``; eos < 0 means "no eos"), so requests
with different sampling settings share the single compiled step.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..cache import ExecutableCache, default_cache
from .kvcache import StaticKVCache, append_token_kv, dequantize_kv, \
    is_quantized_kv, kv_layer_view, kv_max_seq, kv_stack_layers, \
    valid_mask, write_prompt_kv, write_prompt_kv_at


@dataclass(frozen=True)
class GPTDecodeSpec:
    """The static facts the compiled decode program is specialized on.

    Frozen + hashable: it keys the process-wide jit-function caches, so
    two engines (or ``generate`` calls) over same-shaped models share one
    traced program family.
    """
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    max_position_embeddings: int
    ln_epsilon: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def from_model(cls, model) -> "GPTDecodeSpec":
        c = model.gpt.config
        return cls(vocab_size=c.vocab_size, hidden_size=c.hidden_size,
                   num_layers=c.num_layers, num_heads=c.num_heads,
                   max_position_embeddings=c.max_position_embeddings)


@dataclass
class SamplingParams:
    """Per-request decode settings (host side; the scheduler packs them
    into the per-slot device vectors)."""
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    eos_token_id: Optional[int] = None
    max_new_tokens: int = 32

    def clamped_temperature(self) -> float:
        # same guard the reference generate applies host-side
        return max(float(self.temperature), 1e-6)


def extract_gpt_params(model) -> Dict[str, Any]:
    """The GPT parameter pytree as raw jnp arrays (references, not copies —
    re-extract after an optimizer step to pick up new values)."""
    gpt = model.gpt
    layers = []
    for lyr in gpt.decoder.layers:
        a = lyr.self_attn
        layers.append({
            "qw": a.q_proj.weight._data, "qb": a.q_proj.bias._data,
            "kw": a.k_proj.weight._data, "kb": a.k_proj.bias._data,
            "vw": a.v_proj.weight._data, "vb": a.v_proj.bias._data,
            "ow": a.out_proj.weight._data, "ob": a.out_proj.bias._data,
            "w1": lyr.linear1.weight._data, "b1": lyr.linear1.bias._data,
            "w2": lyr.linear2.weight._data, "b2": lyr.linear2.bias._data,
            "n1w": lyr.norm1.weight._data, "n1b": lyr.norm1.bias._data,
            "n2w": lyr.norm2.weight._data, "n2b": lyr.norm2.bias._data,
        })
    return {
        "tok": gpt.word_embeddings.weight._data,
        "pos": gpt.position_embeddings.weight._data,
        "fnw": gpt.decoder.norm.weight._data,
        "fnb": gpt.decoder.norm.bias._data,
        "layers": tuple(layers),
    }


#: per-layer weight matrices that quantize to int8 (biases/norms stay f32
#: — they are O(E) bytes and scale-sensitive)
_QUANT_WEIGHT_KEYS = ("qw", "kw", "vw", "ow", "w1", "w2")


def quantize_gpt_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Per-out-channel int8 quantization of the GPT weight pytree: each
    matmul weight becomes ``{"q": int8 [in, out], "s": f32 [out]}`` with
    ``w ≈ q * s`` (scale = absmax/127 per column). The embedding tables
    stay f32: ``tok`` doubles as the logit head, where a per-row scale
    would perturb the argmax ordering the accuracy budget is measured on.
    Layout matches :func:`extract_gpt_params`, so the same step builders
    serve both — ``_mm`` dispatches on the leaf type."""
    from ...quantization import quantize_weight_int8

    def _q(w):
        q, s = quantize_weight_int8(w, quant_axis=1)
        return {"q": q, "s": s}

    layers = tuple(
        {k: (_q(v) if k in _QUANT_WEIGHT_KEYS else v)
         for k, v in lp.items()}
        for lp in params["layers"])
    return dict(params, layers=layers)


def _mm(x, w):
    """``x @ w`` for a dense f32 weight or an int8 ``{"q", "s"}`` leaf.
    The int8 path multiplies against the raw codes and applies the
    per-out-channel scale to the product — exactly equal to dequantizing
    first (scales distribute over the contraction), but the weight reads
    stay int8, which is the memory-bandwidth win."""
    if isinstance(w, dict):
        return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w


# -- building blocks (must mirror the framework eval ops exactly) -----------

def _layer_norm(x, w, b, eps):
    # mirrors F.layer_norm: mean/var over the last axis, rsqrt, scale+shift
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * w + b


def _sample(lraw, temperature, top_k, do_sample, key, max_top_k):
    """Greedy argmax / temperature+top-k categorical, vectorized per slot.

    ``lraw``: [S, V] float32 last-token logits. Mirrors the reference
    ``_gpt_generate`` recipe: greedy ignores temperature; sampling divides
    by (pre-clamped) temperature, masks everything below the k-th logit to
    -1e9 when ``top_k > 0``, then draws ``jax.random.categorical(key, ·)``.
    ``max_top_k`` is the static top-k width; per-slot ``top_k`` selects the
    effective threshold inside it.
    """
    greedy = jnp.argmax(lraw, axis=-1).astype(jnp.int32)
    lt = lraw / temperature[:, None]
    if max_top_k > 0:
        vals = jax.lax.top_k(lt, max_top_k)[0]            # [S, maxK] desc
        kidx = jnp.clip(top_k, 1, max_top_k) - 1
        kth = jnp.take_along_axis(vals, kidx[:, None], axis=-1)
        filtered = jnp.where(lt < kth, -1e9, lt)
        lt = jnp.where((top_k > 0)[:, None], filtered, lt)
    sampled = jax.random.categorical(key, lt, axis=-1).astype(jnp.int32)
    return jnp.where(do_sample, sampled, greedy)


def _block_decode(spec, lp, h, kb, vb, positions, mask, scale):
    """One pre-norm transformer block for a single new token per slot.

    ``h``: [S, E]; ``kb``/``vb``: this layer's [S, max_seq, H, D] cache;
    returns (h, kb, vb) with the token's K/V written at ``positions``.
    """
    s = h.shape[0]
    x = _layer_norm(h, lp["n1w"], lp["n1b"], spec.ln_epsilon)
    q = (_mm(x, lp["qw"]) + lp["qb"]).reshape(s, spec.num_heads,
                                              spec.head_dim)
    kn = (_mm(x, lp["kw"]) + lp["kb"]).reshape(s, spec.num_heads,
                                               spec.head_dim)
    vn = (_mm(x, lp["vw"]) + lp["vb"]).reshape(s, spec.num_heads,
                                               spec.head_dim)
    kb, vb = append_token_kv(kb, vb, kn, vn, positions)
    # int8 cache: dequantize in-register for the attention reads; the
    # buffers themselves stay quantized
    kd = dequantize_kv(kb, h.dtype)
    vd = dequantize_kv(vb, h.dtype)
    qh = (q * scale)[:, :, None, :]                       # [S, H, 1, D]
    kt = jnp.transpose(kd, (0, 2, 1, 3))                  # [S, H, max, D]
    vt = jnp.transpose(vd, (0, 2, 1, 3))
    prod = jnp.matmul(qh, jnp.swapaxes(kt, -1, -2))       # [S, H, 1, max]
    weights = jax.nn.softmax(prod + mask, axis=-1)
    out = jnp.matmul(weights, vt)                         # [S, H, 1, D]
    out = jnp.transpose(out, (0, 2, 1, 3)).reshape(s, spec.hidden_size)
    h = h + (_mm(out, lp["ow"]) + lp["ob"])
    x = _layer_norm(h, lp["n2w"], lp["n2b"], spec.ln_epsilon)
    ffn = jax.nn.gelu(_mm(x, lp["w1"]) + lp["b1"], approximate=False)
    return h + (_mm(ffn, lp["w2"]) + lp["b2"]), kb, vb


def _block_prefill(spec, lp, h, mask, scale):
    """One pre-norm block over a whole [B, L, E] prompt; returns
    (h, k, v) with K/V in cache layout [B, L, H, D]."""
    b, l = h.shape[0], h.shape[1]
    x = _layer_norm(h, lp["n1w"], lp["n1b"], spec.ln_epsilon)

    def heads(t):                                         # [B, L, H, D]
        return t.reshape(b, l, spec.num_heads, spec.head_dim)

    q = heads(_mm(x, lp["qw"]) + lp["qb"])
    k = heads(_mm(x, lp["kw"]) + lp["kb"])
    v = heads(_mm(x, lp["vw"]) + lp["vb"])
    qh = jnp.transpose(q * scale, (0, 2, 1, 3))           # [B, H, L, D]
    kh = jnp.transpose(k, (0, 2, 1, 3))
    vh = jnp.transpose(v, (0, 2, 1, 3))
    prod = jnp.matmul(qh, jnp.swapaxes(kh, -1, -2))       # [B, H, L, L]
    weights = jax.nn.softmax(prod + mask, axis=-1)
    out = jnp.matmul(weights, vh)                         # [B, H, L, D]
    out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, l, spec.hidden_size)
    h = h + (_mm(out, lp["ow"]) + lp["ob"])
    x = _layer_norm(h, lp["n2w"], lp["n2b"], spec.ln_epsilon)
    ffn = jax.nn.gelu(_mm(x, lp["w1"]) + lp["b1"], approximate=False)
    return h + (_mm(ffn, lp["w2"]) + lp["b2"]), k, v


# -- the compiled programs ---------------------------------------------------

def build_decode_step(spec: GPTDecodeSpec, max_top_k: int):
    """The RAW (un-jitted) decode step — the auditable program.

    Split out of :func:`get_decode_step` so the trace auditor
    (tools/analyze/trace, PTA009/PTA010) can wrap the same function in its
    own counting jit without disturbing the production lru-cached wrapper.
    """
    scale = 1.0 / np.sqrt(spec.head_dim)
    max_pos = spec.max_position_embeddings

    def _step(params, kbuf, vbuf, lengths, finished, last_tokens,
              temperature, top_k, do_sample, eos, key):
        max_seq = kv_max_seq(kbuf)
        positions = lengths                       # write position per slot
        posc = jnp.clip(positions, 0, max_pos - 1)
        h = params["tok"][last_tokens] + params["pos"][posc]      # [S, E]
        mask = valid_mask(positions, max_seq, h.dtype)
        new_k, new_v = [], []
        for li, lp in enumerate(params["layers"]):
            h, kb, vb = _block_decode(spec, lp, h, kv_layer_view(kbuf, li),
                                      kv_layer_view(vbuf, li),
                                      positions, mask, scale)
            new_k.append(kb)
            new_v.append(vb)
        kbuf = kv_stack_layers(new_k)
        vbuf = kv_stack_layers(new_v)
        h = _layer_norm(h, params["fnw"], params["fnb"], spec.ln_epsilon)
        lraw = (h @ params["tok"].T).astype(jnp.float32)          # [S, V]
        nxt = _sample(lraw, temperature, top_k, do_sample, key, max_top_k)
        nxt = jnp.where(finished & (eos >= 0), eos, nxt)
        finished = finished | ((nxt == eos) & (eos >= 0))
        return kbuf, vbuf, lengths + 1, finished, nxt

    return _step


@functools.lru_cache(maxsize=64)
def get_decode_step(spec: GPTDecodeSpec, max_top_k: int):
    """THE decode step: jitted once per (spec, max_top_k); each distinct
    (num_slots, max_seq) shape pair traces exactly once (the attached
    ``trace_counter["traces"]`` counts Python-body executions == XLA
    traces — the compile-counter tests assert it stays flat after warmup).

    step(params, kbuf, vbuf, lengths, finished, last_tokens,
         temperature, top_k, do_sample, eos, key)
      -> (kbuf, vbuf, lengths+1, finished, next_tokens)

    All slots advance unconditionally (inactive slots compute masked
    garbage that the scheduler discards — uniform shapes are what keep the
    program unique); per-slot eos semantics match the reference generate:
    finished rows keep emitting their eos token.
    """
    counter = {"traces": 0}
    raw = build_decode_step(spec, max_top_k)

    def _step(*args):
        counter["traces"] += 1
        return raw(*args)

    fn = jax.jit(_step)
    fn.trace_counter = counter
    return fn


def build_prefill_fn(spec: GPTDecodeSpec, max_top_k: int):
    """The RAW (un-jitted) prefill — see :func:`build_decode_step`."""
    scale = 1.0 / np.sqrt(spec.head_dim)

    def _prefill(params, tokens, true_lens, kbuf, vbuf, lengths, finished,
                 slot_ids, temperature, top_k, do_sample, eos, key):
        b, lp_len = tokens.shape
        pos = jnp.arange(lp_len, dtype=jnp.int32)
        h = params["tok"][tokens] + params["pos"][pos][None]   # [B, L, E]
        # the same additive causal triu the dense path materialises
        mask = jnp.triu(jnp.full((lp_len, lp_len), -1e9, h.dtype),
                        1)[None, None]
        kcs, vcs = [], []
        for lp in params["layers"]:
            h, k, v = _block_prefill(spec, lp, h, mask, scale)
            kcs.append(k)
            vcs.append(v)
        kbuf, vbuf = write_prompt_kv(
            kbuf, vbuf, jnp.stack(kcs, axis=1), jnp.stack(vcs, axis=1),
            slot_ids)
        lengths = lengths.at[slot_ids].set(true_lens)
        h = _layer_norm(h, params["fnw"], params["fnb"], spec.ln_epsilon)
        last = jnp.take_along_axis(
            h, (true_lens - 1)[:, None, None].astype(jnp.int32),
            axis=1)[:, 0]                                      # [B, E]
        lraw = (last @ params["tok"].T).astype(jnp.float32)
        nxt = _sample(lraw, temperature, top_k, do_sample, key, max_top_k)
        finished = finished.at[slot_ids].set((nxt == eos) & (eos >= 0))
        return kbuf, vbuf, lengths, finished, nxt

    return _prefill


@functools.lru_cache(maxsize=64)
def get_prefill_fn(spec: GPTDecodeSpec, max_top_k: int):
    """Bucketed prefill: run the whole (right-padded) prompt batch through
    the causal stack, write its K/V into the target slots, set their
    lengths, and sample the first generated token. One trace per
    (batch, prompt_bucket) shape — a small closed set when prompts are
    padded to buckets.

    prefill(params, tokens[B, Lp], true_lens[B], kbuf, vbuf, lengths,
            finished, slot_ids[B], temperature[B], top_k[B], do_sample[B],
            eos[B], key)
      -> (kbuf, vbuf, lengths, finished, next_tokens[B])

    Right-padding is safe under the causal mask: real position i only
    attends j <= i < true_len, and the junk K/V written at
    [true_len, Lp) is masked by the slot length until later tokens
    overwrite it.
    """
    counter = {"traces": 0}
    raw = build_prefill_fn(spec, max_top_k)

    def _prefill(*args):
        counter["traces"] += 1
        return raw(*args)

    fn = jax.jit(_prefill)
    fn.trace_counter = counter
    return fn


def build_tail_prefill_fn(spec: GPTDecodeSpec, max_top_k: int):
    """The RAW (un-jitted) tail prefill — prefill a prompt *suffix* into a
    slot whose first ``starts[i]`` rows were bulk-copied from the prefix
    store. Queries attend over the slot's FULL cache row (cached prefix +
    freshly written tail) under an offset-causal mask, so the produced
    hidden states — and therefore the first sampled token — are bitwise
    what a full prefill of the whole prompt would produce: masked
    positions contribute exactly-0.0 softmax weight (same -1e9 additive
    mask as the dense path), and row-wise dot products contract in the
    same order regardless of the extra zero-weight columns.
    """
    scale = 1.0 / np.sqrt(spec.head_dim)
    max_pos = spec.max_position_embeddings

    def _tail(params, tokens, tail_lens, starts, kbuf, vbuf, lengths,
              finished, slot_ids, temperature, top_k, do_sample, eos, key):
        # tokens: [B, Lt] right-padded tails; tail_lens: [B] true tail
        # counts; starts: [B] reuse offsets (block multiples).
        if is_quantized_kv(kbuf):
            raise NotImplementedError(
                "tail prefill (prefix reuse) over an int8 KV cache is "
                "unsupported; LLMEngineConfig gates prefix_cache off for "
                "kv_dtype='int8'")
        b, lt = tokens.shape
        max_seq = kbuf.shape[2]
        pos = starts[:, None] + jnp.arange(lt, dtype=jnp.int32)[None]
        posc = jnp.clip(pos, 0, max_pos - 1)
        h = params["tok"][tokens] + params["pos"][posc]        # [B, Lt, E]
        # offset-causal over the whole row: tail query i (absolute
        # position starts+i) sees cache rows j <= starts+i — the reused
        # prefix plus the tail K/V written below (its own row included)
        j = jnp.arange(max_seq, dtype=jnp.int32)[None, None]
        mask = jnp.where(j <= pos[:, :, None], 0.0,
                         -1e9).astype(h.dtype)[:, None]        # [B,1,Lt,max]
        kcs, vcs = [], []
        for li, lp in enumerate(params["layers"]):
            x = _layer_norm(h, lp["n1w"], lp["n1b"], spec.ln_epsilon)

            def heads(t):
                return t.reshape(b, lt, spec.num_heads, spec.head_dim)

            q = heads(_mm(x, lp["qw"]) + lp["qb"])
            kn = heads(_mm(x, lp["kw"]) + lp["kb"])
            vn = heads(_mm(x, lp["vw"]) + lp["vb"])
            # attention reads the gathered slot rows with the fresh tail
            # K/V spliced in; the buffers themselves are written once,
            # after the layer loop, via ONE update per request
            row_k = kbuf[slot_ids, li]                         # [B,max,H,D]
            row_v = vbuf[slot_ids, li]

            def _splice(row, new, st):
                return jax.lax.dynamic_update_slice(row, new, (st, 0, 0))

            row_k = jax.vmap(_splice)(row_k, kn, starts)
            row_v = jax.vmap(_splice)(row_v, vn, starts)
            qh = jnp.transpose(q * scale, (0, 2, 1, 3))        # [B,H,Lt,D]
            kt = jnp.transpose(row_k, (0, 2, 1, 3))            # [B,H,max,D]
            vt = jnp.transpose(row_v, (0, 2, 1, 3))
            prod = jnp.matmul(qh, jnp.swapaxes(kt, -1, -2))    # [B,H,Lt,max]
            weights = jax.nn.softmax(prod + mask, axis=-1)
            out = jnp.matmul(weights, vt)                      # [B,H,Lt,D]
            out = jnp.transpose(out, (0, 2, 1, 3)).reshape(
                b, lt, spec.hidden_size)
            h = h + (_mm(out, lp["ow"]) + lp["ob"])
            x = _layer_norm(h, lp["n2w"], lp["n2b"], spec.ln_epsilon)
            ffn = jax.nn.gelu(_mm(x, lp["w1"]) + lp["b1"],
                              approximate=False)
            h = h + (_mm(ffn, lp["w2"]) + lp["b2"])
            kcs.append(kn)
            vcs.append(vn)
        kbuf, vbuf = write_prompt_kv_at(
            kbuf, vbuf, jnp.stack(kcs, axis=1), jnp.stack(vcs, axis=1),
            slot_ids, starts)
        lengths = lengths.at[slot_ids].set(starts + tail_lens)
        h = _layer_norm(h, params["fnw"], params["fnb"], spec.ln_epsilon)
        last = jnp.take_along_axis(
            h, (tail_lens - 1)[:, None, None].astype(jnp.int32),
            axis=1)[:, 0]                                      # [B, E]
        lraw = (last @ params["tok"].T).astype(jnp.float32)
        nxt = _sample(lraw, temperature, top_k, do_sample, key, max_top_k)
        finished = finished.at[slot_ids].set((nxt == eos) & (eos >= 0))
        return kbuf, vbuf, lengths, finished, nxt

    return _tail


@functools.lru_cache(maxsize=64)
def get_tail_prefill_fn(spec: GPTDecodeSpec, max_top_k: int):
    """Bucketed *tail* prefill for prefix-cache hits: same contract as
    :func:`get_prefill_fn` plus a per-request ``starts`` offset vector.
    One trace per (batch, tail_bucket) shape.

    tail_prefill(params, tokens[B, Lt], tail_lens[B], starts[B], kbuf,
                 vbuf, lengths, finished, slot_ids[B], temperature[B],
                 top_k[B], do_sample[B], eos[B], key)
      -> (kbuf, vbuf, lengths, finished, next_tokens[B])
    """
    counter = {"traces": 0}
    raw = build_tail_prefill_fn(spec, max_top_k)

    def _tail(*args):
        counter["traces"] += 1
        return raw(*args)

    fn = jax.jit(_tail)
    fn.trace_counter = counter
    return fn


def build_insert_prefix_fn():
    """The RAW prefix bulk-copy: land a cached ``[L, n, H, D]`` prefix
    into one slot's rows [0, n) — ONE batched ``dynamic_update_slice``
    per buffer across all layers (the tentpole's no-per-layer-host-loop
    invariant lives here)."""

    def _insert(kbuf, vbuf, k_pre, v_pre, slot):
        return write_prompt_kv_at(kbuf, vbuf, k_pre[None], v_pre[None],
                                  jnp.asarray([slot], jnp.int32),
                                  jnp.asarray([0], jnp.int32))

    return _insert


@functools.lru_cache(maxsize=8)
def get_insert_prefix_fn():
    """Jitted prefix bulk-copy; retraces only per distinct prefix-row
    count (block multiples — a small closed set)."""
    counter = {"traces": 0}
    raw = build_insert_prefix_fn()

    def _insert(*args):
        counter["traces"] += 1
        return raw(*args)

    fn = jax.jit(_insert)
    fn.trace_counter = counter
    return fn


def pack_sampling(params_list: Sequence[SamplingParams]):
    """Host-side SamplingParams -> the per-slot device vectors the compiled
    step consumes (eos -1 disables eos handling for that slot)."""
    temps = [p.clamped_temperature() for p in params_list]
    eoses = [-1 if p.eos_token_id is None else int(p.eos_token_id)
             for p in params_list]
    temp = np.asarray(temps, np.float32)  # noqa: PTA002 -- packs host-side SamplingParams fields (python scalars), no device value involved
    topk = np.asarray([int(p.top_k) for p in params_list], np.int32)  # noqa: PTA002 -- host python scalars
    do_s = np.asarray([bool(p.do_sample) for p in params_list], np.bool_)  # noqa: PTA002 -- host python scalars
    eos = np.asarray(eoses, np.int32)  # noqa: PTA002 -- host python scalars
    return (jnp.asarray(temp), jnp.asarray(topk), jnp.asarray(do_s),
            jnp.asarray(eos))


class GPTStaticDecoder:
    """Object façade over the compiled prefill/decode programs for one
    GPT model: parameter extraction, KV-cache construction, and
    ExecutableCache-audited access to the jitted functions (a cache miss
    marks the first time a shape signature is seen == one XLA trace, the
    same accounting the classifier Engine uses)."""

    def __init__(self, model, max_top_k: int = 64,
                 exec_cache: Optional[ExecutableCache] = None,
                 mesh=None, slot_axis: str = "model",
                 weight_dtype: str = "float32",
                 kv_dtype: str = "float32"):
        self.spec = GPTDecodeSpec.from_model(model)
        self._model = model
        self.max_top_k = max(0, min(int(max_top_k), self.spec.vocab_size))
        if weight_dtype not in ("float32", "int8"):
            raise ValueError(
                f"weight_dtype must be 'float32' or 'int8', got "
                f"{weight_dtype!r}")
        if kv_dtype not in ("float32", "int8"):
            raise ValueError(
                f"kv_dtype must be 'float32' or 'int8', got {kv_dtype!r}")
        self.weight_dtype = weight_dtype
        self.kv_dtype = kv_dtype
        # NOT `exec_cache or ...`: an empty ExecutableCache has len() == 0
        # and is falsy, which would silently orphan the engine's cache.
        # Default is the ONE process-wide cache (serving/cache.py), shared
        # with Predictors and batch engines; the spec-based key below
        # keeps decoders from colliding in it.
        self.exec_cache = (exec_cache if exec_cache is not None
                           else default_cache())
        # GSPMD: with a mesh, params are replicated onto it and KV slots
        # shard over `slot_axis` (see StaticKVCache). The mesh token —
        # axis names + shape + device ids — joins the cache key so two
        # replica decoders over different device subsets sharing one
        # ExecutableCache never collide (and neither collides with the
        # unsharded key).
        self.mesh = mesh
        self.slot_axis = slot_axis
        self._key = ("gpt-static", self.spec, self.max_top_k,
                     self.weight_dtype, self.kv_dtype)
        self._param_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            from ..sharding import mesh_token
            self._key = self._key + (mesh_token(mesh),)
            self._param_sharding = NamedSharding(mesh, PartitionSpec())

    @property
    def model(self):
        """The live model object (weight hot-swap mutates it in place via
        ``set_state_dict``, then re-extracts params)."""
        return self._model

    def params(self):
        p = extract_gpt_params(self._model)
        if self.weight_dtype == "int8":
            p = quantize_gpt_params(p)
        if self._param_sharding is not None:
            sh = self._param_sharding
            p = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sh), p)
        return p

    def new_kv(self, num_slots: int, max_seq: int) -> StaticKVCache:
        if max_seq > self.spec.max_position_embeddings:
            raise ValueError(
                f"max_seq {max_seq} exceeds the model's "
                f"{self.spec.max_position_embeddings} positions")
        dtype = self._model.gpt.word_embeddings.weight._data.dtype
        return StaticKVCache(num_slots, self.spec.num_layers, max_seq,
                             self.spec.num_heads, self.spec.head_dim,
                             dtype=dtype, mesh=self.mesh,
                             slot_axis=self.slot_axis,
                             kv_dtype=("int8" if self.kv_dtype == "int8"
                                       else None))

    # -- compiled-program access --------------------------------------------
    def decode_fn(self, num_slots: int, max_seq: int):
        """The single decode step; the ExecutableCache key carries the
        shape pair so its miss counter mirrors XLA traces."""
        return self.exec_cache.get_or_compile(
            self._key + ("decode", num_slots, max_seq),
            lambda: get_decode_step(self.spec, self.max_top_k))

    def prefill_fn(self, batch: int, prompt_len: int):
        return self.exec_cache.get_or_compile(
            self._key + ("prefill", batch, prompt_len),
            lambda: get_prefill_fn(self.spec, self.max_top_k))

    def tail_prefill_fn(self, batch: int, tail_len: int):
        return self.exec_cache.get_or_compile(
            self._key + ("tail_prefill", batch, tail_len),
            lambda: get_tail_prefill_fn(self.spec, self.max_top_k))

    def insert_prefix_fn(self, prefix_len: int):
        return self.exec_cache.get_or_compile(
            self._key + ("insert_prefix", prefix_len),
            lambda: get_insert_prefix_fn())

    def prefix_sig(self, kv: StaticKVCache):
        """The shape signature a PrefixStore entry must match to be
        copyable into this decoder's cache (max_seq deliberately NOT part
        of it — a prefix exported from a larger-max_seq engine reuses
        fine in a smaller slot as long as it fits, which the scheduler's
        reuse cap guarantees)."""
        return (self.spec.num_layers, self.spec.num_heads,
                self.spec.head_dim, str(kv.dtype))

    # -- convenience wrappers ------------------------------------------------
    def prefill(self, kv: StaticKVCache, params, tokens, true_lens,
                slot_ids, finished, samp_vecs, key):
        """Run bucketed prefill for ``tokens`` [B, Lp] into ``slot_ids``;
        updates ``kv`` in place (functionally) and returns
        (next_tokens[B] device, finished[S] device)."""
        fn = self.prefill_fn(tokens.shape[0], tokens.shape[1])
        k, v, lengths, finished, nxt = fn(
            params, tokens, true_lens, kv.k, kv.v, kv.lengths, finished,
            slot_ids, *samp_vecs, key)
        kv.swap(k, v, lengths)
        return nxt, finished

    def tail_prefill(self, kv: StaticKVCache, params, tokens, tail_lens,
                     starts, slot_ids, finished, samp_vecs, key):
        """Prefill prompt *tails* at per-request offsets (after an
        :meth:`insert_prefix` landed the cached head); same return shape
        as :meth:`prefill`."""
        if kv.quantized:
            raise NotImplementedError(
                "tail_prefill over an int8 KV cache is unsupported; "
                "LLMEngineConfig gates prefix_cache off for "
                "kv_dtype='int8'")
        fn = self.tail_prefill_fn(tokens.shape[0], tokens.shape[1])
        k, v, lengths, finished, nxt = fn(
            params, tokens, tail_lens, starts, kv.k, kv.v, kv.lengths,
            finished, slot_ids, *samp_vecs, key)
        kv.swap(k, v, lengths)
        return nxt, finished

    def insert_prefix(self, kv: StaticKVCache, k_pre, v_pre, slot: int):
        """Bulk-copy a cached host prefix ``[L, n, H, D]`` into ``slot``'s
        rows [0, n) — one batched device update across all layers. The
        slot's length is set by the tail prefill that follows."""
        if kv.quantized:
            raise NotImplementedError(
                "insert_prefix into an int8 KV cache is unsupported; "
                "LLMEngineConfig gates prefix_cache off for "
                "kv_dtype='int8'")
        fn = self.insert_prefix_fn(int(k_pre.shape[1]))
        k, v = fn(kv.k, kv.v, jnp.asarray(k_pre, dtype=kv.dtype),
                  jnp.asarray(v_pre, dtype=kv.dtype), slot)
        kv.swap(k, v, kv.lengths)

    def decode_step(self, kv: StaticKVCache, params, finished, last_tokens,
                    samp_vecs, key):
        """Advance every slot one token; updates ``kv`` and returns
        (next_tokens[S] device, finished[S] device)."""
        fn = self.decode_fn(kv.num_slots, kv.max_seq)
        k, v, lengths, finished, nxt = fn(
            params, kv.k, kv.v, kv.lengths, finished, last_tokens,
            *samp_vecs, key)
        kv.swap(k, v, lengths)
        return nxt, finished


# -- trace-audit registration (tools/analyze/trace, PTA009/PTA010) -----------

_AUDIT_SPEC = GPTDecodeSpec(vocab_size=32, hidden_size=8, num_layers=1,
                            num_heads=2, max_position_embeddings=64)
_AUDIT_TOP_K = 4


def _audit_params(rng, spec: GPTDecodeSpec = _AUDIT_SPEC):
    """A synthetic tiny GPT parameter pytree matching extract_gpt_params'
    layout; values vary with the rng so PTA010's perturbed variants share
    shapes but not data. ``spec`` must be single-layer (the audit
    entrypoints all are); spec.py reuses this for its draft pytree."""
    e, v, p = spec.hidden_size, spec.vocab_size, spec.max_position_embeddings

    def arr(*shape):
        return jnp.asarray(rng.standard_normal(shape) * 0.02, jnp.float32)

    layer = {
        "qw": arr(e, e), "qb": arr(e), "kw": arr(e, e), "kb": arr(e),
        "vw": arr(e, e), "vb": arr(e), "ow": arr(e, e), "ob": arr(e),
        "w1": arr(e, 4 * e), "b1": arr(4 * e), "w2": arr(4 * e, e),
        "b2": arr(e), "n1w": arr(e), "n1b": arr(e), "n2w": arr(e),
        "n2b": arr(e),
    }
    return {"tok": arr(v, e), "pos": arr(p, e), "fnw": arr(e),
            "fnb": arr(e), "layers": (layer,)}


def _audit_decode_spec():
    from ...core import audit
    spec = _AUDIT_SPEC
    slots, max_seq, layers = 2, 16, spec.num_layers
    hd = spec.head_dim

    def make_args(variant):
        rng = np.random.default_rng(1234 + variant)
        kv_shape = (slots, layers, max_seq, spec.num_heads, hd)
        return (_audit_params(rng),
                jnp.zeros(kv_shape, jnp.float32),
                jnp.zeros(kv_shape, jnp.float32),
                jnp.asarray([3, 1], jnp.int32),           # lengths
                jnp.zeros((slots,), bool),                # finished
                jnp.asarray(rng.integers(0, spec.vocab_size, slots),
                            jnp.int32),                   # last_tokens
                jnp.ones((slots,), jnp.float32),          # temperature
                jnp.zeros((slots,), jnp.int32),           # top_k
                jnp.zeros((slots,), bool),                # do_sample
                jnp.full((slots,), -1, jnp.int32),        # eos
                jax.random.PRNGKey(variant))
    return audit.AuditSpec(fn=build_decode_step(spec, _AUDIT_TOP_K),
                           make_args=make_args)


def _audit_int8_decode_spec():
    """Same decode step, int8 weights + int8 KV: the serving-memory
    tentpole's executable. Proves the quantized hot path keeps PTA009's
    zero-host-transfer invariant (dequantization is fused in-graph)."""
    from ...core import audit
    spec = _AUDIT_SPEC
    slots, max_seq, layers = 2, 16, spec.num_layers
    hd = spec.head_dim

    def make_args(variant):
        rng = np.random.default_rng(5678 + variant)
        q_shape = (slots, layers, max_seq, spec.num_heads, hd)
        s_shape = (slots, layers, max_seq)

        def qbuf():
            return {"q": jnp.zeros(q_shape, jnp.int8),
                    "s": jnp.zeros(s_shape, jnp.float32)}

        return (quantize_gpt_params(_audit_params(rng)),
                qbuf(), qbuf(),
                jnp.asarray([3, 1], jnp.int32),           # lengths
                jnp.zeros((slots,), bool),                # finished
                jnp.asarray(rng.integers(0, spec.vocab_size, slots),
                            jnp.int32),                   # last_tokens
                jnp.ones((slots,), jnp.float32),          # temperature
                jnp.zeros((slots,), jnp.int32),           # top_k
                jnp.zeros((slots,), bool),                # do_sample
                jnp.full((slots,), -1, jnp.int32),        # eos
                jax.random.PRNGKey(variant))
    return audit.AuditSpec(fn=build_decode_step(spec, _AUDIT_TOP_K),
                           make_args=make_args)


def _audit_prefill_spec():
    from ...core import audit
    spec = _AUDIT_SPEC
    slots, max_seq, layers, b, lp = 2, 16, spec.num_layers, 2, 4
    hd = spec.head_dim

    def make_args(variant):
        rng = np.random.default_rng(4321 + variant)
        kv_shape = (slots, layers, max_seq, spec.num_heads, hd)
        return (_audit_params(rng),
                jnp.asarray(rng.integers(0, spec.vocab_size, (b, lp)),
                            jnp.int32),                   # tokens
                jnp.asarray([lp, lp - 1], jnp.int32),     # true_lens
                jnp.zeros(kv_shape, jnp.float32),
                jnp.zeros(kv_shape, jnp.float32),
                jnp.zeros((slots,), jnp.int32),           # lengths
                jnp.zeros((slots,), bool),                # finished
                jnp.asarray([0, 1], jnp.int32),           # slot_ids
                jnp.ones((b,), jnp.float32),
                jnp.zeros((b,), jnp.int32),
                jnp.zeros((b,), bool),
                jnp.full((b,), -1, jnp.int32),
                jax.random.PRNGKey(100 + variant))
    return audit.AuditSpec(fn=build_prefill_fn(spec, _AUDIT_TOP_K),
                           make_args=make_args)


def _register_audit_entrypoints():
    from ...core import audit
    audit.register_entrypoint("llm_decode_step", _audit_decode_spec,
                              tags=("serving", "decode"))
    audit.register_entrypoint("llm_int8_decode_step",
                              _audit_int8_decode_spec,
                              tags=("serving", "decode", "quantized",
                                    "bench"))
    audit.register_entrypoint("llm_prefill", _audit_prefill_spec,
                              tags=("serving", "prefill"))


_register_audit_entrypoints()
