"""StaticKVCache: preallocated slot-structured KV buffers for decode.

The concat-grown cache (``MultiHeadAttention.Cache``) changes shape every
token, so XLA specializes a new executable per length — the per-token
recompile flagged in ROADMAP item 1. This cache fixes every shape up
front: K and V live in ``[num_slots, num_layers, max_seq, heads,
head_dim]`` buffers, a sequence occupies one *slot* row for its whole
lifetime, and all writes are functional ``lax.dynamic_update_slice``
updates inside the jitted prefill/decode programs — the arrays never
change shape, so one compiled decode step serves every token of every
request (LazyTensor's keep-one-program-hot discipline, arxiv 2102.13267).

Slot lifecycle (host-side bookkeeping; device arrays are only ever
*replaced* by the functional step outputs):

    free ──alloc()──> active ──free()──> free
                (prefill writes [0, L))   (buffers keep stale rows; the
                                           per-slot length masks them and
                                           the next prefill overwrites)

The length vector lives on device (it is an input of the compiled step);
``alloc``/``free`` only mutate the host free-list, so slot churn costs no
host↔device traffic beyond the admission-time prompt upload.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class SlotsExhausted(RuntimeError):
    """alloc() called with every slot in use (callers should gate on
    :attr:`StaticKVCache.free_slots` instead of catching this)."""


# -- int8 KV representation ---------------------------------------------------
# A quantized buffer is a dict pytree {"q": int8 [..., H, D] codes,
# "s": f32 [...] per-row absmax scales} — one scale per (slot, layer,
# position) row, so a loud token cannot flatten its neighbours'
# resolution. Dequant is q/127*s, computed INSIDE the fused decode step
# (the codes never round-trip through the host). Dicts are pytrees, so
# the quantized buffers flow through jax.jit/device_put exactly like the
# dense arrays they replace.

def quantize_kv_rows(x):
    """``[..., H, D]`` float rows -> ({int8 codes, f32 scales}) with one
    absmax scale per row (all leading axes)."""
    absmax = jnp.max(jnp.abs(x), axis=(-2, -1))
    s = jnp.where(absmax > 0, absmax, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x / s[..., None, None] * 127.0),
                 -127.0, 127.0).astype(jnp.int8)
    return {"q": q, "s": s}


def dequantize_kv(buf, dtype=jnp.float32):
    """Dense view of a quantized buffer (or identity on a dense one)."""
    if not isinstance(buf, dict):
        return buf
    return (buf["q"].astype(dtype)
            * (buf["s"][..., None, None] / 127.0).astype(dtype))


def is_quantized_kv(buf) -> bool:
    return isinstance(buf, dict)


def kv_layer_view(buf, li: int):
    """Layer ``li``'s slice of a whole-cache buffer: dense
    ``[S, L, max, H, D] -> [S, max, H, D]``, quantized dict likewise on
    both leaves."""
    if isinstance(buf, dict):
        return {"q": buf["q"][:, li], "s": buf["s"][:, li]}
    return buf[:, li]


def kv_stack_layers(bufs):
    """Inverse of :func:`kv_layer_view` over all layers: re-stack the
    per-layer buffers on axis 1."""
    if bufs and isinstance(bufs[0], dict):
        return {"q": jnp.stack([b["q"] for b in bufs], axis=1),
                "s": jnp.stack([b["s"] for b in bufs], axis=1)}
    return jnp.stack(bufs, axis=1)


def kv_max_seq(buf) -> int:
    return (buf["q"] if isinstance(buf, dict) else buf).shape[2]


def kv_nbytes(buf) -> int:
    """Device bytes of a (possibly quantized) KV buffer."""
    return sum(int(leaf.nbytes)
               for leaf in jax.tree_util.tree_leaves(buf))


class StaticKVCache:
    """Preallocated per-slot KV storage + per-slot length/position state.

    ``k``/``v``: ``[num_slots, num_layers, max_seq, heads, head_dim]``
    device arrays. ``lengths``: ``[num_slots]`` int32 device vector — the
    number of valid cache rows per slot (== the absolute position the next
    token will be written at). Both are replaced wholesale by the outputs
    of the jitted prefill/decode functions; this object is the host-side
    holder that threads them from tick to tick.
    """

    def __init__(self, num_slots: int, num_layers: int, max_seq: int,
                 num_heads: int, head_dim: int, dtype="float32",
                 mesh=None, slot_axis: str = "model",
                 kv_dtype: Optional[str] = None):
        if num_slots < 1 or max_seq < 2:
            raise ValueError(
                f"need num_slots >= 1 and max_seq >= 2, got "
                f"{num_slots}/{max_seq}")
        if kv_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_dtype must be None (dense) or 'int8', got "
                f"{kv_dtype!r}")
        self.num_slots = int(num_slots)
        self.num_layers = int(num_layers)
        self.max_seq = int(max_seq)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = jnp.dtype(dtype)
        self.kv_dtype = kv_dtype
        self.quantized = kv_dtype == "int8"
        self.mesh = mesh
        self.slot_axis = slot_axis
        shape = (self.num_slots, self.num_layers, self.max_seq,
                 self.num_heads, self.head_dim)
        if self.quantized:
            # {"q": int8 codes, "s": f32 per-(slot,layer,row) scales} —
            # halves KV memory (+1 scale per H*D row); the decode step
            # dequantizes in-register, so the codes never leave device
            def _zero_buf():
                return {"q": jnp.zeros(shape, jnp.int8),
                        "s": jnp.zeros(shape[:3], jnp.float32)}
        else:
            def _zero_buf():
                return jnp.zeros(shape, self.dtype)
        if mesh is not None:
            # GSPMD: shard the slot axis over the model axis of the mesh.
            # Slot rows are independent (attention never crosses slots),
            # so this partitioning is bitwise-identical to single-device
            # decode — each device owns whole slots, no reduction is split.
            from jax.sharding import NamedSharding, PartitionSpec
            axis_size = int(mesh.shape[slot_axis])
            if self.num_slots % axis_size:
                raise ValueError(
                    f"num_slots={self.num_slots} must divide evenly over "
                    f"mesh axis {slot_axis!r} (size {axis_size})")
            self._kv_sharding = NamedSharding(mesh,
                                              PartitionSpec(slot_axis))
            self._len_sharding = NamedSharding(mesh,
                                               PartitionSpec(slot_axis))
            sh = self._kv_sharding
            self.k = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sh), _zero_buf())
            self.v = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sh), _zero_buf())
            self.lengths = jax.device_put(
                jnp.zeros((self.num_slots,), jnp.int32),
                self._len_sharding)
        else:
            self._kv_sharding = None
            self._len_sharding = None
            self.k = _zero_buf()
            self.v = _zero_buf()
            self.lengths = jnp.zeros((self.num_slots,), jnp.int32)
        self._free: List[int] = list(range(self.num_slots))
        self._active: set = set()

    # -- slot lifecycle (host side) -----------------------------------------
    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def active_slots(self) -> Tuple[int, ...]:
        return tuple(sorted(self._active))

    def alloc(self) -> int:
        """Claim a free slot (lowest-index first, so short-lived tests are
        deterministic). The caller must prefill before decoding it."""
        if not self._free:
            raise SlotsExhausted(
                f"all {self.num_slots} KV slots are in use")
        slot = self._free.pop(0)
        self._active.add(slot)
        return slot

    def free(self, slot: int):
        """Return a slot to the pool. Stale K/V rows stay in the buffers —
        they are masked by the length vector and overwritten by the next
        occupant's prefill, so no device work is needed.

        Raises on an out-of-range slot and on a slot that is not active
        — a silent double-free would re-append the slot and hand it to
        two sequences at once (interleaved K/V corruption). The
        regression test pins both guards."""
        if not (0 <= slot < self.num_slots) or slot not in self._active:
            raise ValueError(
                f"slot {slot} is not active (double free?)")
        self._active.discard(slot)
        self._free.append(slot)
        self._free.sort()

    def reset(self):
        """Free every slot and zero the length vector (buffers are left as
        is — lengths gate validity). For tests and engine restarts."""
        self._free = list(range(self.num_slots))
        self._active.clear()
        lengths = jnp.zeros((self.num_slots,), jnp.int32)
        if self._len_sharding is not None:
            lengths = jax.device_put(lengths, self._len_sharding)
        self.lengths = lengths

    # -- functional state threading -----------------------------------------
    def swap(self, k, v, lengths):
        """Install the arrays returned by a jitted prefill/decode call.
        Shape-checked: a shape change would mean a recompile upstream."""
        def _shapes(buf):
            return [leaf.shape for leaf in jax.tree_util.tree_leaves(buf)]
        assert _shapes(k) == _shapes(self.k) \
            and _shapes(v) == _shapes(self.v), (_shapes(k), _shapes(self.k))
        self.k, self.v, self.lengths = k, v, lengths

    def kv_bytes(self) -> int:
        """Device bytes held by the K+V buffers (the slots-per-chip
        denominator the int8 acceptance bar is measured with)."""
        return kv_nbytes(self.k) + kv_nbytes(self.v)

    def host_lengths(self) -> np.ndarray:
        """One deliberate device->host fetch of the per-slot lengths (used
        by tests and ``/statsz``, never by the per-tick hot path — the
        scheduler tracks lengths on host from the tokens it already
        fetched)."""
        return np.asarray(jax.device_get(self.lengths))  # noqa: PTA002 -- deliberate observability fetch (tests, /statsz); the tick loop never calls this

    def host_slot_kv(self, slot: int, n: int) -> Tuple[np.ndarray, np.ndarray]:
        """One deliberate device->host copy of a slot's first ``n`` K/V
        rows as ``[num_layers, n, heads, head_dim]`` host arrays — the
        prefix-store export path. Called once per *admission* (after a
        prefill populated the rows), never on the per-tick path."""
        if self.quantized:
            raise NotImplementedError(
                "prefix export from an int8 KV cache is unsupported "
                "(prefix reuse is gated off at config time for "
                "kv_dtype='int8'; see LLMEngineConfig)")
        if not (0 <= slot < self.num_slots) or not (0 < n <= self.max_seq):
            raise ValueError(f"bad prefix export slot={slot} n={n}")
        k = np.asarray(jax.device_get(self.k[slot, :, :n]))  # noqa: PTA002 -- admission-time prefix-store export (one copy per admitted prompt); never on the per-tick path
        v = np.asarray(jax.device_get(self.v[slot, :, :n]))  # noqa: PTA002 -- admission-time prefix-store export; paired with the K fetch above
        return k, v

    def __repr__(self):
        return (f"StaticKVCache(slots={self.num_slots}, "
                f"layers={self.num_layers}, max_seq={self.max_seq}, "
                f"heads={self.num_heads}, head_dim={self.head_dim}, "
                f"active={len(self._active)})")


# -- functional update kernels (used inside jitted programs) ----------------

def append_token_kv(kb, vb, k_new, v_new, positions):
    """Write one new token's K/V for every slot at that slot's position
    (one layer's buffers — decode updates layer *l*'s cache before layer
    *l* attends, so the update is interleaved with the forward pass).

    ``kb``/``vb``: ``[S, max_seq, H, D]``; ``k_new``/``v_new``:
    ``[S, H, D]`` (the current token's projections); ``positions``:
    ``[S]`` int32. A vmapped ``lax.dynamic_update_slice`` over the slot
    axis — per-slot starts are traced values, so XLA lowers this to one
    scatter, keeping the decode step a single fused program.
    """
    if is_quantized_kv(kb):
        return (_append_token_kv_q(kb, k_new, positions),
                _append_token_kv_q(vb, v_new, positions))

    def _one(row_k, row_v, kn, vn, pos):
        # row_*: [max_seq, H, D]; kn/vn: [H, D]
        start = (pos, 0, 0)
        return (jax.lax.dynamic_update_slice(row_k, kn[None], start),
                jax.lax.dynamic_update_slice(row_v, vn[None], start))

    return jax.vmap(_one)(kb, vb, k_new, v_new, positions)


def _append_token_kv_q(buf, new, positions):
    """int8 variant of the single-token writer: quantize the new rows
    (one scale per slot) and land code + scale with the same vmapped
    ``dynamic_update_slice`` shape — still one scatter per leaf."""
    qs = quantize_kv_rows(new)                 # q [S, H, D], s [S]

    def _one(row_q, row_s, qn, sn, pos):
        # row_q: [max_seq, H, D] int8; row_s: [max_seq] f32
        return (jax.lax.dynamic_update_slice(row_q, qn[None], (pos, 0, 0)),
                jax.lax.dynamic_update_slice(row_s, sn[None], (pos,)))

    q, s = jax.vmap(_one)(buf["q"], buf["s"], qs["q"], qs["s"], positions)
    return {"q": q, "s": s}


def append_tokens_kv(kb, vb, k_new, v_new, positions):
    """Multi-token generalisation of :func:`append_token_kv`: write T new
    tokens' K/V per slot starting at that slot's position (the speculative
    verify step lands its k+1 candidate rows with this).

    ``kb``/``vb``: ``[S, max_seq, H, D]``; ``k_new``/``v_new``:
    ``[S, T, H, D]``; ``positions``: ``[S]`` int32. Same vmapped
    ``lax.dynamic_update_slice`` shape as the single-token writer, so XLA
    lowers it to one scatter per buffer.
    """
    def _one(row_k, row_v, kn, vn, pos):
        # row_*: [max_seq, H, D]; kn/vn: [T, H, D]
        start = (pos, 0, 0)
        return (jax.lax.dynamic_update_slice(row_k, kn, start),
                jax.lax.dynamic_update_slice(row_v, vn, start))

    return jax.vmap(_one)(kb, vb, k_new, v_new, positions)


def write_prompt_kv_at(k_buf, v_buf, k_new, v_new, slot_ids, starts):
    """Write K/V rows into slots at per-request offsets — the
    prefix-reuse writer. ``k_new``/``v_new``: ``[B, L_layers, L, H, D]``;
    ``starts``: length-B offsets (0 == :func:`write_prompt_kv`). ONE
    batched ``dynamic_update_slice`` per request covers all layers at
    once — no per-layer host loop, the tentpole invariant for prefix
    bulk-copy."""
    if is_quantized_kv(k_buf):
        return (_write_prompt_kv_q(k_buf, k_new, slot_ids, starts),
                _write_prompt_kv_q(v_buf, v_new, slot_ids, starts))
    b = k_new.shape[0]
    for i in range(b):
        start = (slot_ids[i], 0, starts[i], 0, 0)
        k_buf = jax.lax.dynamic_update_slice(k_buf, k_new[i][None], start)
        v_buf = jax.lax.dynamic_update_slice(v_buf, v_new[i][None], start)
    return k_buf, v_buf


def _write_prompt_kv_q(buf, new, slot_ids, starts=None):
    """int8 variant of the prompt writers: quantize the ``[B, L, Lp, H,
    D]`` rows (one scale per row) and land codes + scales per request —
    still one ``dynamic_update_slice`` pair per request for all layers."""
    qs = quantize_kv_rows(new)           # q like new, s [B, L, Lp]
    q, s = buf["q"], buf["s"]
    b = new.shape[0]
    for i in range(b):
        st = 0 if starts is None else starts[i]
        q = jax.lax.dynamic_update_slice(
            q, qs["q"][i][None], (slot_ids[i], 0, st, 0, 0))
        s = jax.lax.dynamic_update_slice(
            s, qs["s"][i][None], (slot_ids[i], 0, st))
    return {"q": q, "s": s}


def write_prompt_kv(k_buf, v_buf, k_prompt, v_prompt, slot_ids):
    """Write whole-prompt K/V into the given slots at offset 0.

    ``k_prompt``/``v_prompt``: ``[B, L_layers, L_prompt, H, D]``;
    ``slot_ids``: length-B int sequence (static Python ints or traced
    scalars). B is static, so the loop unrolls into B
    ``dynamic_update_slice`` ops — prefill batches are small (usually 1
    per admission) and each op writes one contiguous slot row.
    """
    if is_quantized_kv(k_buf):
        return (_write_prompt_kv_q(k_buf, k_prompt, slot_ids),
                _write_prompt_kv_q(v_buf, v_prompt, slot_ids))
    b = k_prompt.shape[0]
    for i in range(b):
        start = (slot_ids[i], 0, 0, 0, 0)
        k_buf = jax.lax.dynamic_update_slice(k_buf, k_prompt[i][None], start)
        v_buf = jax.lax.dynamic_update_slice(v_buf, v_prompt[i][None], start)
    return k_buf, v_buf


def valid_mask(lengths, max_seq, dtype=jnp.float32):
    """Additive attention mask ``[S, 1, 1, max_seq]``: 0 where the cache
    row index is <= the slot's current position (the just-written token
    attends to itself and the whole valid prefix), -1e9 beyond — the same
    finite -1e9 the dense path uses, so softmax zeros stale rows exactly
    (exp(-1e9) underflows to 0.0 in f32)."""
    idx = jnp.arange(max_seq, dtype=jnp.int32)[None, :]        # [1, max_seq]
    ok = idx <= lengths[:, None]                               # [S, max_seq]
    return jnp.where(ok, 0.0, -1e9).astype(dtype)[:, None, None, :]
