"""paddle_tpu.serving.llm: static-slot KV-cache decode + continuous batching.

The LLM half of the serving stack. Classifier serving (the parent package)
batches *requests*; LLM serving batches *sequences in flight*: every decode
tick advances all active sequences by one token through ONE compiled XLA
program, and sequences join (prefill into a free slot) or leave (eos /
length / deadline) the in-flight batch between ticks — continuous batching.

Three layers:

* :class:`StaticKVCache` (``kvcache.py``) — preallocated
  ``[num_slots, num_layers, max_seq, heads, head_dim]`` K/V slot buffers
  with per-slot lengths, updated functionally via
  ``lax.dynamic_update_slice``; slot alloc/free/reset is host-side
  bookkeeping so the device arrays never change shape.
* :class:`GPTStaticDecoder` (``decode.py``) — pure-jax prefill +
  ``decode_step`` over the extracted GPT parameter pytree: greedy and
  temperature/top-k sampling, per-slot eos masking, all on device. Shapes
  are fixed by (num_slots, max_seq), so after warmup one executable serves
  every token of every request.
* :class:`LLMEngine` / :class:`ContinuousBatcher` (``scheduler.py``) — the
  serving loop: bounded admission through the shared :class:`BatchQueue`,
  per-request :class:`Deadline`, bucketed prefill through the shape-keyed
  :class:`ExecutableCache`, token streaming, and graceful drain chained
  with preemption (SIGTERM finishes in-flight sequences).

Quick start::

    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving.llm import LLMEngine, LLMEngineConfig

    engine = LLMEngine(GPTForCausalLM(cfg),
                       LLMEngineConfig(num_slots=8, max_seq=512))
    req = engine.submit([1, 2, 3], max_new_tokens=32)
    print(req.future.result()["tokens"])
    engine.drain()

Over HTTP: ``python -m paddle_tpu.serving serve-llm ...`` exposes
``POST /generate`` (optionally streaming newline-delimited JSON tokens).
See docs/serving.md "LLM serving".
"""
from __future__ import annotations

from .kvcache import StaticKVCache  # noqa: F401
from .decode import (  # noqa: F401
    GPTDecodeSpec, GPTStaticDecoder, SamplingParams, extract_gpt_params,
    pack_sampling)
from .prefix import PrefixEntry, PrefixStore, chain_hashes  # noqa: F401
from .spec import GPTSpecDecoder  # noqa: F401
from .scheduler import (  # noqa: F401
    ContinuousBatcher, GenerationRequest, LLMEngine, LLMEngineConfig)

__all__ = [
    "StaticKVCache", "GPTDecodeSpec", "GPTStaticDecoder", "SamplingParams",
    "extract_gpt_params", "pack_sampling", "ContinuousBatcher",
    "GenerationRequest", "LLMEngine", "LLMEngineConfig",
    "PrefixStore", "PrefixEntry", "chain_hashes", "GPTSpecDecoder",
]
