"""PrefixStore: cross-request prefix KV reuse for the static-slot decoder.

Chatbot traffic at scale shares system prompts: two requests whose first
N tokens are identical compute *identical* K/V rows for those N
positions (causal attention never looks right), so the second prefill is
pure waste. This store keeps completed prompts' K/V on host, keyed by a
**block chain hash** over the token prefix, and the scheduler bulk-copies
the longest cached prefix into a fresh slot on admission — one batched
``lax.dynamic_update_slice`` across all layers (see
``kvcache.write_prompt_kv_at``) — then prefills only the uncached tail
bucket. LazyTensor's async-dispatch discipline (arxiv 2102.13267) is the
design anchor: the store lives entirely off the per-tick path; its only
device traffic is one admission-time insert copy and one admission-time
export copy.

Layout and hash scheme
----------------------

Tokens are grouped into fixed ``block_tokens`` blocks. The chain hash of
block *i* is ``H(chain[i-1] || tokens[i*B:(i+1)*B])`` — a hash over the
*entire* prefix, so equal chain values identify equal token prefixes
(verified byte-for-byte on lookup anyway; hashes only prune the search).
An entry stores host numpy K/V ``[num_layers, n_tokens, heads,
head_dim]`` for one block-aligned prefix and is indexed under *every*
intermediate chain point, so a new prompt sharing only the first 2 of an
entry's 4 blocks still hits (and reuses ``entry.k[:, :2 * B]``).

Eviction is LRU by last hit under a byte capacity; entries pinned by an
in-flight request (``refs > 0``) are never evicted — the router's
prefill->decode KV handoff pins the entry on the prefill replica until
the decode replica has consumed it.

Thread safety: lookups/inserts run on engine worker threads and (for the
handoff) the router's dispatch threads; every mutable structure is
guarded by ``self._lock``.
"""
from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...core import monitor as _mon

#: shape signature an entry must match to be reusable by a decoder:
#: (num_layers, num_heads, head_dim, dtype_str)
ShapeSig = Tuple[int, int, int, str]


def chain_hashes(tokens: np.ndarray, block: int) -> List[bytes]:
    """Chain hash per complete block: ``out[i]`` identifies the token
    prefix ``tokens[: (i + 1) * block]``."""
    out: List[bytes] = []
    prev = b""
    n = (len(tokens) // block) * block
    arr = np.asarray(tokens[:n], dtype=np.int32)  # noqa: PTA002 -- hashes the caller's host-side prompt tokens, no device value involved
    for i in range(n // block):
        blk = arr[i * block:(i + 1) * block].tobytes()
        prev = hashlib.sha1(prev + blk).digest()
        out.append(prev)
    return out


class PrefixEntry:
    """One cached block-aligned prefix: immutable payload; the store owns
    the mutable refcount / recency bookkeeping (under its lock)."""

    __slots__ = ("key", "tokens", "k", "v", "n_tokens", "nbytes", "sig")

    def __init__(self, key: bytes, tokens: np.ndarray, k: np.ndarray,
                 v: np.ndarray, sig: ShapeSig):
        self.key = key
        self.tokens = tokens
        self.k = k
        self.v = v
        self.n_tokens = int(tokens.size)
        self.nbytes = int(k.nbytes + v.nbytes)
        self.sig = sig

    def __repr__(self):
        return (f"PrefixEntry(n_tokens={self.n_tokens}, "
                f"nbytes={self.nbytes})")


class PrefixStore:
    """Ref-counted, capacity-bounded host store of prompt-prefix K/V."""

    def __init__(self, capacity_bytes: int = 256 << 20,
                 block_tokens: int = 16,
                 registry: Optional[_mon.StatRegistry] = None,
                 stat_prefix: str = "serving.llm.prefix"):
        if block_tokens < 1:
            raise ValueError(f"block_tokens must be >= 1, got {block_tokens}")
        self.capacity_bytes = int(capacity_bytes)
        self.block_tokens = int(block_tokens)
        self._registry = registry if registry is not None \
            else _mon.default_registry()
        self._prefix = stat_prefix
        self._lock = threading.Lock()
        self._entries: Dict[bytes, PrefixEntry] = {}   # full-chain key
        self._index: Dict[bytes, bytes] = {}           # chain point -> key
        self._refs: Dict[bytes, int] = {}
        self._last_hit: Dict[bytes, int] = {}
        self._tick = 0                                  # recency clock
        self._bytes = 0
        # copied-vs-shared accounting: the host store COPIES every reused
        # byte into the hitting slot (bytes_copied), the paged store
        # shares pages by refcount (bytes_shared) — both surface the same
        # two counters so /metricsz can prove the zero-copy claim
        self._bytes_copied = 0
        self._bytes_shared = 0
        self._stat_set("bytes", 0)
        self._stat_set("entries", 0)

    # -- stats ---------------------------------------------------------------
    def _stat_add(self, name, v):
        self._registry.add(f"{self._prefix}.{name}", v)

    def _stat_set(self, name, v):
        self._registry.set(f"{self._prefix}.{name}", v)

    def note_copied(self, nbytes: int):
        """Record reused-prefix bytes that were COPIED into a slot (the
        host store's bulk insert path)."""
        with self._lock:
            self._bytes_copied += int(nbytes)
        self._stat_add("bytes_copied", int(nbytes))

    def note_shared(self, nbytes: int):
        """Record reused-prefix bytes shared WITHOUT a copy (always 0
        for the host store; the paged store's table-splice path)."""
        with self._lock:
            self._bytes_shared += int(nbytes)
        self._stat_add("bytes_shared", int(nbytes))

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "capacity_bytes": self.capacity_bytes,
                "block_tokens": self.block_tokens,
                "pinned": sum(1 for n in self._refs.values() if n > 0),
                "bytes_copied": self._bytes_copied,
                "bytes_shared": self._bytes_shared,
            }

    # -- pin / unpin ---------------------------------------------------------
    def unpin(self, entry: PrefixEntry):
        """Release one in-flight reference (eviction becomes possible at
        refs == 0). Safe after the entry was evicted is impossible —
        pinned entries are never evicted — but tolerate a double unpin
        going negative-proof."""
        with self._lock:
            if entry.key in self._refs:
                self._refs[entry.key] = max(0, self._refs[entry.key] - 1)

    # -- lookup / insert -----------------------------------------------------
    def lookup(self, tokens, max_tokens: int,
               sig: ShapeSig) -> Tuple[Optional[PrefixEntry], int]:
        """Longest cached prefix of ``tokens`` reusable at most
        ``max_tokens`` tokens with a matching shape signature. A hit is
        returned *pinned* (the caller owns one reference and must
        :meth:`unpin` when its request leaves the engine) with the number
        of reusable tokens (a block multiple <= max_tokens)."""
        toks = np.asarray(tokens, dtype=np.int32).reshape(-1)  # noqa: PTA002 -- admission-time view of the caller's host-side prompt
        nb_max = min(int(max_tokens), toks.size) // self.block_tokens
        if nb_max < 1:
            self._stat_add("misses", 1)
            return None, 0
        hashes = chain_hashes(toks, self.block_tokens)[:nb_max]
        with self._lock:
            for i in range(len(hashes) - 1, -1, -1):
                key = self._index.get(hashes[i])
                if key is None:
                    continue
                entry = self._entries.get(key)
                n = (i + 1) * self.block_tokens
                if entry is None or entry.sig != sig \
                        or entry.n_tokens < n \
                        or not np.array_equal(entry.tokens[:n], toks[:n]):
                    continue
                self._tick += 1
                self._last_hit[key] = self._tick
                self._refs[key] = self._refs.get(key, 0) + 1
                self._stat_add("hits", 1)
                self._stat_add("hit_tokens", n)
                return entry, n
        self._stat_add("misses", 1)
        return None, 0

    def insert(self, tokens, k: np.ndarray, v: np.ndarray,
               sig: ShapeSig) -> Optional[PrefixEntry]:
        """Store the K/V of a block-aligned prompt prefix (``k``/``v``:
        host ``[L, n, H, D]`` with n a block multiple == len(tokens)).
        Returns the entry *pinned* (caller unpins when its request leaves
        the engine); dedups against an existing entry covering the same
        chain. May evict LRU unpinned entries to fit the byte budget;
        pinned entries are never evicted, so the store can transiently
        exceed capacity under pin churn."""
        toks = np.asarray(tokens, dtype=np.int32).reshape(-1)  # noqa: PTA002 -- admission-time view of the caller's host-side prompt
        n = (toks.size // self.block_tokens) * self.block_tokens
        if n < self.block_tokens:
            return None
        toks = toks[:n]
        if k.shape[1] != n or v.shape[1] != n:
            raise ValueError(
                f"prefix K/V rows {k.shape[1]}/{v.shape[1]} != {n} tokens")
        hashes = chain_hashes(toks, self.block_tokens)
        key = hashes[-1]
        with self._lock:
            existing_key = self._index.get(key)
            if existing_key is not None:
                existing = self._entries.get(existing_key)
                if existing is not None and existing.sig == sig \
                        and existing.n_tokens >= n \
                        and np.array_equal(existing.tokens[:n], toks):
                    self._tick += 1
                    self._last_hit[existing.key] = self._tick
                    self._refs[existing.key] = \
                        self._refs.get(existing.key, 0) + 1
                    return existing
            entry = PrefixEntry(key, toks,
                                np.ascontiguousarray(k),   # noqa: PTA002 -- k/v are host numpy arrays by contract (kvcache.host_slot_kv already fetched them)
                                np.ascontiguousarray(v),   # noqa: PTA002 -- see above; layout-normalizing host copy, no device value
                                sig)
            self._entries[key] = entry
            self._bytes += entry.nbytes
            self._tick += 1
            self._last_hit[key] = self._tick
            self._refs[key] = 1
            for h in hashes:
                self._index[h] = key
            # LRU-by-last-hit eviction down to capacity; pinned
            # (refs > 0) entries are skipped — an in-flight prefix is
            # never evicted. Inline so the lock scope is self-evident.
            if self._bytes > self.capacity_bytes:
                recency = dict(self._last_hit)
                victims = sorted(
                    (vk for vk, e in self._entries.items()
                     if self._refs.get(vk, 0) == 0),
                    key=lambda vk: recency.get(vk, 0))
                for vk in victims:
                    if self._bytes <= self.capacity_bytes:
                        break
                    victim = self._entries.pop(vk)
                    self._bytes -= victim.nbytes
                    self._refs.pop(vk, None)
                    self._last_hit.pop(vk, None)
                    stale = [h for h, k2 in self._index.items() if k2 == vk]
                    for h in stale:
                        del self._index[h]
                    self._stat_add("evictions", 1)
            self._stat_add("inserts", 1)
            self._stat_set("bytes", self._bytes)
            self._stat_set("entries", len(self._entries))
            return entry

    def __repr__(self):
        with self._lock:
            return (f"PrefixStore(entries={len(self._entries)}, "
                    f"bytes={self._bytes}/{self.capacity_bytes}, "
                    f"block={self.block_tokens})")
