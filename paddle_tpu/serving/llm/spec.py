"""Draft-model speculative decoding inside the jitted decode step.

Decode is memory-bound: each tick streams the whole KV cache to produce
ONE token per slot. Speculative decoding spends the idle FLOPs — a small
draft GPT proposes ``k`` tokens per tick (k cheap micro-steps over its
own small cache), then the target model verifies all ``k`` in ONE
multi-query step (k+1 queries over the full cache — barely more
expensive than the single-query tick it replaces) and the accept-prefix
selection happens on device. A tick emits 1..k+1 tokens.

Greedy acceptance math (``build_spec_decode_step``): with per-slot
position ``p`` and last emitted token ``x0`` (not yet in cache, same
convention as the plain step),

1. the draft greedily proposes ``d[0..k-1]`` (k+1 micro-steps share the
   tick; K/V rows for all of ``[x0, d0, .., d_{k-1}]`` land at
   ``p..p+k`` in the DRAFT cache, so a fully-accepted tick leaves the
   draft self-consistent);
2. the target runs queries ``u = [x0, d0, .., d_{k-1}]`` at positions
   ``p..p+k`` under an offset-causal mask, writing all k+1 K/V rows,
   producing greedy verdicts ``t[0..k]`` — ``t[i]`` is exactly what the
   plain decoder would emit after ``u[0..i]``;
3. ``m = |longest prefix with d[i] == t[i]|`` tokens of the draft are
   accepted and the bonus token ``t[m]`` rides along free: the tick emits
   ``d[0..m-1], t[m]`` (``m+1`` tokens) and advances lengths by ``m+1``.

Because each query's attention sees exactly the rows the plain decoder
would have seen (extra candidate rows are masked at -1e9 → exactly-0.0
softmax weight in f32), greedy output is **bitwise identical** to the
non-speculative static decoder — the regression test asserts it.
Sampling slots fall back to one verified token per tick (the position-0
logits ARE the plain step's logits, drawn with the tick key); note the
key-per-tick schedule means a sampling request's draw sequence matches
the plain engine only when tick counts align — greedy is the bitwise
contract, sampling stays distribution-correct.

The per-tick host traffic stays ONE fetch: the step packs
``[n_emitted | tokens...]`` per slot into a single ``[S, k+2]`` int32
array (LazyTensor async-dispatch discipline, arxiv 2102.13267 — the
fetch-counter test pins it).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..cache import ExecutableCache
from .decode import (GPTDecodeSpec, GPTStaticDecoder, _block_decode,
                     _layer_norm, _sample, extract_gpt_params,
                     get_prefill_fn)
from .kvcache import StaticKVCache, append_tokens_kv, valid_mask


def _block_verify(spec, lp, h, kb, vb, positions, mask, scale):
    """One pre-norm block over T=k+1 candidate tokens per slot against
    the full cache row. ``h``: [S, T, E]; ``kb``/``vb``: this layer's
    [S, max_seq, H, D] cache; all T candidate K/V rows are written at
    ``positions..positions+T-1`` before attending (query i's own row is
    visible to it, mirroring the single-token step)."""
    s, t = h.shape[0], h.shape[1]
    x = _layer_norm(h, lp["n1w"], lp["n1b"], spec.ln_epsilon)

    def heads(z):                                          # [S, T, H, D]
        return z.reshape(s, t, spec.num_heads, spec.head_dim)

    q = heads(x @ lp["qw"] + lp["qb"])
    kn = heads(x @ lp["kw"] + lp["kb"])
    vn = heads(x @ lp["vw"] + lp["vb"])
    kb, vb = append_tokens_kv(kb, vb, kn, vn, positions)
    qh = jnp.transpose(q * scale, (0, 2, 1, 3))            # [S, H, T, D]
    kt = jnp.transpose(kb, (0, 2, 1, 3))                   # [S, H, max, D]
    vt = jnp.transpose(vb, (0, 2, 1, 3))
    prod = jnp.matmul(qh, jnp.swapaxes(kt, -1, -2))        # [S, H, T, max]
    weights = jax.nn.softmax(prod + mask, axis=-1)
    out = jnp.matmul(weights, vt)                          # [S, H, T, D]
    out = jnp.transpose(out, (0, 2, 1, 3)).reshape(s, t, spec.hidden_size)
    h = h + (out @ lp["ow"] + lp["ob"])
    x = _layer_norm(h, lp["n2w"], lp["n2b"], spec.ln_epsilon)
    ffn = jax.nn.gelu(x @ lp["w1"] + lp["b1"], approximate=False)
    return h + (ffn @ lp["w2"] + lp["b2"]), kb, vb


def build_spec_decode_step(tspec: GPTDecodeSpec, dspec: GPTDecodeSpec,
                           k: int, max_top_k: int):
    """The RAW (un-jitted) speculative decode step — the auditable
    program (registered as PTA009 entrypoint ``llm_spec_decode_step``).

    step(params_t, params_d, kbuf_t, vbuf_t, kbuf_d, vbuf_d, lengths,
         finished, last_tokens, temperature, top_k, do_sample, eos, key)
      -> (kbuf_t, vbuf_t, kbuf_d, vbuf_d, lengths + n, finished,
          new_last, out[S, k+2])

    ``out[s] = [n_emitted, tok_0, .., tok_{n-1}, 0...]`` — the single
    per-tick host fetch. The caller guarantees every ACTIVE slot has
    ``lengths + k + 1 <= max_seq`` (the scheduler's room check; it falls
    back to the plain tick otherwise).
    """
    if k < 1:
        raise ValueError(f"speculation depth k must be >= 1, got {k}")
    t_scale = 1.0 / np.sqrt(tspec.head_dim)
    d_scale = 1.0 / np.sqrt(dspec.head_dim)
    t_max_pos = tspec.max_position_embeddings
    d_max_pos = dspec.max_position_embeddings

    def _step(params_t, params_d, kbuf_t, vbuf_t, kbuf_d, vbuf_d, lengths,
              finished, last_tokens, temperature, top_k, do_sample, eos,
              key):
        s = lengths.shape[0]
        max_seq = kbuf_t.shape[2]
        d_max_seq = kbuf_d.shape[2]
        # -- 1. draft proposes k tokens greedily (its own small cache) ---
        # k+1 micro-steps, not k: when every draft is accepted the tick's
        # valid rows extend to position p+k, so the draft cache needs the
        # LAST proposal's K/V row too — without it the next tick's draft
        # attends a garbage row and acceptance collapses. The extra step
        # only deposits that row; its logits are never formed.
        d_last = last_tokens
        drafts = []
        for i in range(k + 1):
            pos_i = lengths + i
            posc = jnp.clip(pos_i, 0, d_max_pos - 1)
            h = params_d["tok"][d_last] + params_d["pos"][posc]
            mask = valid_mask(pos_i, d_max_seq, h.dtype)
            new_k, new_v = [], []
            for li, lp in enumerate(params_d["layers"]):
                h, kb, vb = _block_decode(dspec, lp, h, kbuf_d[:, li],
                                          vbuf_d[:, li], pos_i, mask,
                                          d_scale)
                new_k.append(kb)
                new_v.append(vb)
            kbuf_d = jnp.stack(new_k, axis=1)
            vbuf_d = jnp.stack(new_v, axis=1)
            if i == k:
                break
            h = _layer_norm(h, params_d["fnw"], params_d["fnb"],
                            dspec.ln_epsilon)
            lraw_d = (h @ params_d["tok"].T).astype(jnp.float32)
            d_i = jnp.argmax(lraw_d, axis=-1).astype(jnp.int32)
            drafts.append(d_i)
            d_last = d_i
        drafts_arr = jnp.stack(drafts, axis=1)                 # [S, k]

        # -- 2. target verifies all k (+ the carried last token) at once -
        t_len = k + 1
        u = jnp.concatenate([last_tokens[:, None], drafts_arr], axis=1)
        pos_mat = lengths[:, None] + jnp.arange(t_len, dtype=jnp.int32)
        posc = jnp.clip(pos_mat, 0, t_max_pos - 1)
        h = params_t["tok"][u] + params_t["pos"][posc]         # [S, T, E]
        j = jnp.arange(max_seq, dtype=jnp.int32)[None, None]
        vmask = jnp.where(j <= pos_mat[:, :, None], 0.0,
                          -1e9).astype(h.dtype)[:, None]       # [S,1,T,max]
        new_k, new_v = [], []
        for li, lp in enumerate(params_t["layers"]):
            h, kb, vb = _block_verify(tspec, lp, h, kbuf_t[:, li],
                                      vbuf_t[:, li], lengths, vmask,
                                      t_scale)
            new_k.append(kb)
            new_v.append(vb)
        kbuf_t = jnp.stack(new_k, axis=1)
        vbuf_t = jnp.stack(new_v, axis=1)
        h = _layer_norm(h, params_t["fnw"], params_t["fnb"],
                        tspec.ln_epsilon)
        lraw = (h @ params_t["tok"].T).astype(jnp.float32)     # [S, T, V]
        t_greedy = jnp.argmax(lraw, axis=-1).astype(jnp.int32)

        # -- 3. accept-prefix + bonus, all on device ---------------------
        match = (drafts_arr == t_greedy[:, :k]).astype(jnp.int32)
        m = jnp.sum(jnp.cumprod(match, axis=1), axis=1)        # [S], 0..k
        # sampling slots take one verified token per tick; finished slots
        # freeze (the host released them already — mirror the plain step)
        m = jnp.where(do_sample | finished, 0, m)
        bonus = jnp.take_along_axis(t_greedy, m[:, None], axis=1)[:, 0]
        samp_tok = _sample(lraw[:, 0], temperature, top_k, do_sample, key,
                           max_top_k)
        step_tok = jnp.where(do_sample, samp_tok, bonus)
        step_tok = jnp.where(finished & (eos >= 0), eos, step_tok)
        idx = jnp.arange(t_len, dtype=jnp.int32)[None]         # [1, T]
        ext_drafts = jnp.concatenate(
            [drafts_arr, jnp.zeros((s, 1), jnp.int32)], axis=1)
        emit = jnp.where(idx < m[:, None], ext_drafts,
                         jnp.where(idx == m[:, None], step_tok[:, None], 0))
        n_emit = m + 1
        hit_eos = ((emit == eos[:, None]) & (eos >= 0)[:, None]
                   & (idx < n_emit[:, None])).any(axis=1)
        finished = finished | hit_eos
        out = jnp.concatenate([n_emit[:, None], emit],
                              axis=1).astype(jnp.int32)        # [S, k+2]
        return (kbuf_t, vbuf_t, kbuf_d, vbuf_d, lengths + n_emit,
                finished, step_tok, out)

    return _step


@functools.lru_cache(maxsize=32)
def get_spec_decode_step(tspec: GPTDecodeSpec, dspec: GPTDecodeSpec,
                         k: int, max_top_k: int):
    """THE speculative decode step: jitted once per (target spec, draft
    spec, k, max_top_k); one trace per (num_slots, max_seq) shape pair
    (``trace_counter`` pins it, same contract as ``get_decode_step``)."""
    counter = {"traces": 0}
    raw = build_spec_decode_step(tspec, dspec, k, max_top_k)

    def _step(*args):
        counter["traces"] += 1
        return raw(*args)

    fn = jax.jit(_step)
    fn.trace_counter = counter
    return fn


class GPTSpecDecoder:
    """Draft+verify façade over one target :class:`GPTStaticDecoder` and
    a small draft GPT model: draft parameter extraction, the draft's own
    :class:`StaticKVCache` (same slots/positions, smaller heads), and
    ExecutableCache-audited access to the compiled spec step and draft
    prefill. The draft cache advances in lockstep with the target's —
    they share ONE lengths vector."""

    def __init__(self, target: GPTStaticDecoder, draft_model, k: int = 4,
                 exec_cache: Optional[ExecutableCache] = None):
        if k < 1:
            raise ValueError(f"speculation depth k must be >= 1, got {k}")
        if target.mesh is not None:
            raise NotImplementedError(
                "speculative decoding over a slot-sharded (mesh) decoder "
                "is not supported yet — the draft cache would need the "
                "same GSPMD partitioning")
        self.target = target
        self.k = int(k)
        self.dspec = GPTDecodeSpec.from_model(draft_model)
        if self.dspec.vocab_size != target.spec.vocab_size:
            raise ValueError(
                f"draft vocab {self.dspec.vocab_size} != target vocab "
                f"{target.spec.vocab_size} — speculative verification "
                f"compares token ids, the vocabularies must be shared")
        self._draft_model = draft_model
        # `is not None`, not truthiness: an empty ExecutableCache is falsy
        self.exec_cache = (exec_cache if exec_cache is not None
                           else target.exec_cache)
        self._key = ("gpt-spec", target.spec, self.dspec, self.k,
                     target.max_top_k)
        #: tuned (block_q, block_k) for the verify attention shape, when
        #: the autotuner knows this (q=k+1, kv=max_seq) flash family — the
        #: dense CPU lane ignores it; the TPU flash-verify lane consumes
        #: it (resolved lazily per max_seq in :meth:`verify_blocks`)
        self._verify_blocks: Optional[Tuple[int, int]] = None

    def draft_params(self):
        return extract_gpt_params(self._draft_model)

    def new_draft_kv(self, num_slots: int, max_seq: int) -> StaticKVCache:
        dtype = self._draft_model.gpt.word_embeddings.weight._data.dtype
        return StaticKVCache(num_slots, self.dspec.num_layers, max_seq,
                             self.dspec.num_heads, self.dspec.head_dim,
                             dtype=dtype)

    def verify_blocks(self, max_seq: int) -> Optional[Tuple[int, int]]:
        """Tuned Pallas blocks for the verify-step attention — the
        (q = k+1, kv = max_seq) causal flash shape — from the autotuner's
        winner memo (``paddle_tpu.tuner``). None when untuned (the dense
        verify lane needs no blocks; a TPU flash-verify lane would)."""
        if self._verify_blocks is None:
            from ...tuner import get_spec_verify_blocks
            self._verify_blocks = get_spec_verify_blocks(
                self.k, max_seq, self.target.spec.head_dim, "float32")
        return self._verify_blocks

    # -- compiled-program access ---------------------------------------------
    def spec_step_fn(self, num_slots: int, max_seq: int):
        return self.exec_cache.get_or_compile(
            self._key + ("spec_step", num_slots, max_seq),
            lambda: get_spec_decode_step(self.target.spec, self.dspec,
                                         self.k, self.target.max_top_k))

    def draft_prefill_fn(self, batch: int, prompt_len: int):
        # draft prefill is greedy-only (drafts are proposals): top-k 0
        return self.exec_cache.get_or_compile(
            self._key + ("draft_prefill", batch, prompt_len),
            lambda: get_prefill_fn(self.dspec, 0))

    # -- convenience wrappers ------------------------------------------------
    def draft_prefill(self, kv_draft: StaticKVCache, params_d, tokens,
                      true_lens, slot_ids, lengths, finished, samp_vecs,
                      key):
        """Prefill the DRAFT cache for a newly admitted prompt. Only the
        K/V outputs are kept — lengths/finished/first-token are the
        target prefill's business (both prefills would compute identical
        lengths; the draft's sampled token is discarded)."""
        fn = self.draft_prefill_fn(tokens.shape[0], tokens.shape[1])
        kd, vd, _lens, _fin, _nxt = fn(
            params_d, tokens, true_lens, kv_draft.k, kv_draft.v, lengths,
            finished, slot_ids, *samp_vecs, key)
        kv_draft.k, kv_draft.v = kd, vd

    def step(self, kv: StaticKVCache, kv_draft: StaticKVCache, params_t,
             params_d, finished, last_tokens, samp_vecs, key):
        """Advance every slot 1..k+1 tokens; swaps BOTH caches and
        returns (finished[S] device, new_last[S] device, out[S, k+2]
        device) — the caller performs the tick's single host fetch on
        ``out``."""
        fn = self.spec_step_fn(kv.num_slots, kv.max_seq)
        (kt, vt, kd, vd, lengths, finished, last_new, out) = fn(
            params_t, params_d, kv.k, kv.v, kv_draft.k, kv_draft.v,
            kv.lengths, finished, last_tokens, *samp_vecs, key)
        kv.swap(kt, vt, lengths)
        kv_draft.swap(kd, vd, lengths)
        return finished, last_new, out


# -- trace-audit registration (tools/analyze/trace, PTA009/PTA010) -----------

_AUDIT_TSPEC = GPTDecodeSpec(vocab_size=32, hidden_size=8, num_layers=1,
                             num_heads=2, max_position_embeddings=64)
_AUDIT_DSPEC = GPTDecodeSpec(vocab_size=32, hidden_size=4, num_layers=1,
                             num_heads=1, max_position_embeddings=64)
_AUDIT_K = 2
_AUDIT_TOP_K = 4


def _audit_spec_step():
    from ...core import audit
    from .decode import _audit_params
    slots, max_seq = 2, 16
    tkv = (slots, _AUDIT_TSPEC.num_layers, max_seq,
           _AUDIT_TSPEC.num_heads, _AUDIT_TSPEC.head_dim)
    dkv = (slots, _AUDIT_DSPEC.num_layers, max_seq,
           _AUDIT_DSPEC.num_heads, _AUDIT_DSPEC.head_dim)

    def make_args(variant):
        rng = np.random.default_rng(777 + variant)
        return (_audit_params(rng, _AUDIT_TSPEC),
                _audit_params(rng, _AUDIT_DSPEC),
                jnp.zeros(tkv, jnp.float32),
                jnp.zeros(tkv, jnp.float32),
                jnp.zeros(dkv, jnp.float32),
                jnp.zeros(dkv, jnp.float32),
                jnp.asarray([3, 1], jnp.int32),           # lengths
                jnp.zeros((slots,), bool),                # finished
                jnp.asarray(rng.integers(0, 32, slots), jnp.int32),
                jnp.ones((slots,), jnp.float32),          # temperature
                jnp.zeros((slots,), jnp.int32),           # top_k
                jnp.zeros((slots,), bool),                # do_sample
                jnp.full((slots,), -1, jnp.int32),        # eos
                jax.random.PRNGKey(variant))
    return audit.AuditSpec(
        fn=build_spec_decode_step(_AUDIT_TSPEC, _AUDIT_DSPEC, _AUDIT_K,
                                  _AUDIT_TOP_K),
        make_args=make_args)


def _register_audit_entrypoints():
    from ...core import audit
    audit.register_entrypoint("llm_spec_decode_step", _audit_spec_step,
                              tags=("serving", "decode", "speculative",
                                    "bench"))


_register_audit_entrypoints()
