"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capabilities (reference surveyed in SURVEY.md), built on JAX/XLA/Pallas.

Public namespace mirrors `paddle.*`: tensor creation + math at top level,
paddle_tpu.nn, .optimizer, .amp, .jit, .static, .distributed, .vision, ...
"""
from __future__ import annotations

import warnings as _warnings

# Without jax_enable_x64, int64 requests silently execute as int32 (paddle's
# default int dtype is int64; the semantics are preserved modulo width).
_warnings.filterwarnings(
    "ignore", message=".*requested in astype is not available.*")
_warnings.filterwarnings(
    "ignore", message=".*Explicitly requested dtype.*is not available.*")
_warnings.filterwarnings(
    "ignore", message=".*donated buffers were not usable.*")

import jax as _jax

# jax < 0.6 exposes shard_map only under jax.experimental (and spells
# check_vma as check_rep); the codebase is written against the stable
# ``jax.shard_map`` surface, so alias it here — before any subpackage
# that shard_maps is imported.
if not hasattr(_jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map_compat(f=None, /, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        # the old replication checker predates the vma type system this
        # codebase is written against and rejects valid programs (e.g.
        # cond branches with different inferred replication — the error
        # itself recommends check_rep=False). It is a static lint with no
        # numeric effect, so default it off under old jax.
        kw.setdefault("check_rep", False)
        if f is None:  # decorator form: jax.shard_map(mesh=..., ...)
            return lambda g: _exp_shard_map(g, **kw)
        return _exp_shard_map(f, **kw)

    _jax.shard_map = _shard_map_compat

# jax < 0.5 has no lax.axis_size; psum of the python literal 1 over the
# named axis is the classic spelling and is evaluated statically (returns
# a python int), so `range(axis_size)` keeps working.
from jax import lax as _lax
if not hasattr(_lax, "axis_size"):
    _lax.axis_size = lambda axis_name: _lax.psum(1, axis_name)

# jax < 0.6 has no jax.typeof; get_aval is the same lookup (callers here
# only probe optional attrs like .vma on the result, with defaults)
if not hasattr(_jax, "typeof"):
    from jax.core import get_aval as _get_aval
    _jax.typeof = _get_aval

# jax < 0.6 has no lax.pcast / vma type system; marking a value
# device-varying is meaningless there (the old check_rep machinery infers
# replication itself), so the compat spelling is identity
if not hasattr(_lax, "pcast"):
    _lax.pcast = lambda x, axes, to=None: x

# Under a launcher/spawn (PADDLE_TRAINERS_NUM > 1) the distributed runtime
# must come up before the first XLA-backend touch below. The retry loop
# lives in distributed/env.py (bootstrap_pre_backend); importing the
# paddle_tpu.distributed *package* this early would pull in
# backend-touching modules, so load the env module standalone under its
# canonical name — the package's later `from .env import ...` reuses this
# sys.modules entry, keeping exactly one copy of the bootstrap.
import os as _os
if (int(_os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1
        and not _os.environ.get("_PADDLE_TPU_DIST_INITIALIZED")):
    import importlib.util as _ilu
    import sys as _sys
    _spec = _ilu.spec_from_file_location(
        "paddle_tpu.distributed.env",
        _os.path.join(_os.path.dirname(__file__), "distributed", "env.py"))
    _env_mod = _ilu.module_from_spec(_spec)
    _sys.modules["paddle_tpu.distributed.env"] = _env_mod
    _spec.loader.exec_module(_env_mod)
    _env_mod.bootstrap_pre_backend()
    del _spec, _env_mod, _ilu, _sys

# float32 ops must be float32-accurate (the reference computes true fp32 unless
# AMP is enabled). XLA's default runs f32 matmuls with bf16 passes on TPU;
# force full precision for f32 — the AMP/bf16 path (paddle_tpu.amp) is the MXU
# perf path and is unaffected by this setting.
_jax.config.update("jax_default_matmul_precision", "highest")

# Fleet-wide persistent compilation cache (serving/cache.py owns the full
# story): when PADDLE_TPU_COMPILE_CACHE names a root, point JAX's own
# persistent cache at <root>/xla HERE — before the first import-time jit —
# so a warm process start performs zero XLA backend compiles at all, not
# just zero for serving signatures. Inlined (not imported from
# serving.cache, which would be circular this early); the values match
# enable_persistent_compilation(), whose later idempotent update is a
# no-op.
_cc_root = _os.environ.get("PADDLE_TPU_COMPILE_CACHE", "").strip()
if _cc_root:
    try:
        _cc_dir = _os.path.join(_os.path.expanduser(_cc_root), "xla")
        _os.makedirs(_cc_dir, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cc_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass                     # serving.cache warns with the details
del _cc_root

from .core import (  # noqa: F401
    Tensor, Parameter, no_grad, enable_grad, is_grad_enabled, set_grad_enabled,
    grad as _functional_grad, seed, get_rng_state, set_rng_state,
    set_default_dtype, get_default_dtype,
    set_flags, get_flags, set_device, get_device, device_count,
    CPUPlace, CUDAPlace, TPUPlace, Place,
    is_compiled_with_cuda, is_compiled_with_tpu,
    bool_ as bool8, uint8, int8, int16, int32, int64, float16, bfloat16,
    float32, float64, complex64, complex128,
)
from .core.dtypes import bool_  # noqa: F401

from .ops import *  # noqa: F401,F403
from .ops.dispatch import in_dygraph_mode, enable_static, disable_static  # noqa: F401
in_dynamic_mode = in_dygraph_mode  # reference: paddle/__init__.py:268 alias
from .ops import linalg  # noqa: F401
from .ops.linalg import cholesky, inverse, matrix_power  # noqa: F401
from . import tensor  # noqa: E402,F401
from .tensor import rank  # noqa: E402,F401

# grad function (paddle.grad)
grad = _functional_grad

from . import autograd  # noqa: E402,F401
from .autograd import PyLayer, PyLayerContext  # noqa: E402,F401

from . import nn  # noqa: E402,F401
from .ops import _late_alias as _ops_late_alias  # noqa: E402
_ops_late_alias()
from . import optimizer  # noqa: E402,F401
from . import regularizer  # noqa: E402,F401
from .nn.layer_base import ParamAttr  # noqa: E402,F401
from .nn.clip import (ClipGradByValue, ClipGradByNorm,  # noqa: E402,F401
                      ClipGradByGlobalNorm)
from . import jit  # noqa: E402,F401
from . import static  # noqa: E402,F401
from .framework_io import save, load  # noqa: E402,F401



def is_grad_enabled_():
    from .core import autograd_engine
    return autograd_engine.is_grad_enabled()


def disable_signal_handler():  # API parity no-op (reference: platform/init.cc:363)
    return None
from . import distributed  # noqa: E402,F401
from . import vision  # noqa: E402,F401
from . import models  # noqa: E402,F401
from .distributed import DataParallel  # noqa: E402,F401
from . import io  # noqa: E402,F401
from . import amp  # noqa: E402,F401
from . import profiler  # noqa: E402,F401
from . import observability  # noqa: E402,F401
from . import incubate  # noqa: E402,F401
from . import utils  # noqa: E402,F401
from . import text  # noqa: E402,F401
from . import quantization  # noqa: E402,F401
from . import inference  # noqa: E402,F401
from . import serving  # noqa: E402,F401
from . import sentinel  # noqa: E402,F401
from . import onnx  # noqa: E402,F401
from . import distribution  # noqa: E402,F401
from . import reader  # noqa: E402,F401
from .reader import batch  # noqa: E402,F401
from .hapi import callbacks  # noqa: E402,F401
from . import sysconfig  # noqa: E402,F401
from . import version  # noqa: E402,F401
# single source of truth for __version__: the reference-parity surface
# (version.py, v2.0-era snapshot) — pyproject's dist version is the
# package's own release number, deliberately distinct
__version__ = version.full_version
from .hapi import hub  # noqa: E402,F401
from . import metric  # noqa: E402,F401
from . import hapi  # noqa: E402,F401
from .hapi import Model, summary  # noqa: E402,F401
from .hapi.dynamic_flops import flops  # noqa: E402,F401
from .compat_surface import (  # noqa: E402,F401
    add_n, is_tensor, create_parameter, set_printoptions, scatter_,
    tanh_, is_compiled_with_xpu, is_compiled_with_npu,
    is_compiled_with_rocm, CUDAPinnedPlace, NPUPlace, XPUPlace,
    get_cudnn_version, get_cuda_rng_state, set_cuda_rng_state,
    ComplexTensor)
from numpy import dtype  # noqa: E402,F401  (paddle.dtype parity)
from .ops import reverse  # noqa: E402,F401  (late alias of flip)
from .core.dtypes import bool_ as bool  # noqa: E402,F401,A001
from .io import DataLoader  # noqa: E402,F401
